"""Flagship benchmark: BERT-Large pretraining step (BASELINE.md config #2)
plus the fused-optimizer step-time microbench (BASELINE metric #2).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
vs_baseline = measured MFU / 0.45 (the BASELINE.json north-star MFU target).
Extra keys: "mfu", "step_ms", "optimizer_speedup" (fused flat-buffer LAMB
step vs naive per-param jitted optax-style update). On ANY failure the line
is {"metric": ..., "value": 0, "unit": ..., "vs_baseline": 0, "error": "..."}
— never a bare stack trace (round-1 lesson: BENCH_r01 recorded a crash and
no number). All diagnostics go to stderr.

Hardening history:
- round 1: one-shot jax.devices() died on transient UNAVAILABLE → watchdog
  subprocess probe + retry before in-process init.
- round 2: probe succeeded, then the FIRST COMPILE died on a transient
  `remote_compile: Connection refused` — so now the whole build+compile+time
  block is also retried with backoff, re-probing the tunnel between attempts
  (the compile server is a separate endpoint from the device tunnel; both
  flake independently).

Multi-device honesty: the train step is sharded over a `data` mesh of ALL
local devices (batch split over the mesh, params/opt-state replicated), so
dividing by n_chips measures genuinely-parallel throughput. On today's
1-chip env this is the identity; `APEX_TPU_BENCH_PLATFORM=cpu` with
`XLA_FLAGS=--xla_force_host_platform_device_count=8` exercises the 8-way
sharded path (tests/test_bench_smoke.py).
"""

import json
import os
import subprocess
import sys
import time
import traceback


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(value=0.0, unit="tokens/s/chip", vs_baseline=0.0, **extra):
    rec = {"metric": "bert_large_pretrain_tokens_per_sec_per_chip",
           "value": round(float(value), 1), "unit": unit,
           "vs_baseline": round(float(vs_baseline), 4)}
    rec.update(extra)
    print(json.dumps(rec), flush=True)


# bf16 peak FLOPs/s per chip by device kind (public TPU specs)
PEAK_FLOPS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def peak_flops(device) -> float:
    kind = device.device_kind.lower()
    for token, f in PEAK_FLOPS:
        if token in kind:
            return f
    if device.platform == "cpu":
        return 1e12  # arbitrary: MFU meaningless on CPU smoke runs
    log(f"unknown device kind {device.device_kind!r}; assuming v5e peak")
    return 197e12


def _probe_once(platform, timeout_s: int):
    """Run jax.devices() in a subprocess with a hard timeout (the PJRT claim
    blocks forever in C when the tunnel is down — uninterruptible in-process)."""
    probe_src = (
        "import os, jax\n"
        + (f"jax.config.update('jax_platforms', {platform!r})\n"
           if platform else "")
        + "ds = jax.devices()\n"
        "print('PROBE_OK', len(ds), ds[0].device_kind, ds[0].platform)\n")
    try:
        r = subprocess.run([sys.executable, "-c", probe_src],
                           capture_output=True, text=True, timeout=timeout_s)
        if "PROBE_OK" in r.stdout:
            return True, r.stdout.strip().splitlines()[-1]
        return False, f"probe rc={r.returncode}: {r.stderr.strip()[-500:]}"
    except subprocess.TimeoutExpired:
        return False, f"backend init hung >{timeout_s}s (TPU tunnel down?)"


def probe_backend(retries: int, wait_s: float, platform, timeout_s: int):
    last = None
    for attempt in range(1, retries + 1):
        t0 = time.perf_counter()
        ok, msg = _probe_once(platform, timeout_s)
        if ok:
            log(f"probe ok after {time.perf_counter()-t0:.1f}s "
                f"(attempt {attempt}): {msg}")
            return
        last = msg
        log(f"backend probe attempt {attempt}/{retries} failed: {msg}")
        if attempt < retries:
            time.sleep(wait_s)
    raise RuntimeError(f"backend init failed after {retries} attempts: {last}")


def _enable_compile_cache(jax):
    """Persistent compilation cache: the BERT-Large train step takes 15+ min
    to compile through the remote-compile tunnel — caching it means a
    healthy window after a failed one skips straight to measurement. Silent
    no-op when the backend can't serialize executables."""
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
        log(f"compilation cache: {cache_dir}")
    except Exception as e:  # noqa: BLE001
        log(f"compilation cache unavailable: {e}")


def init_backend(retries: int, wait_s: float):
    platform = os.environ.get("APEX_TPU_BENCH_PLATFORM")
    init_timeout = int(os.environ.get("APEX_TPU_BENCH_INIT_TIMEOUT", "420"))
    probe_backend(retries, wait_s, platform, init_timeout)

    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    _enable_compile_cache(jax)
    t0 = time.perf_counter()
    devs = jax.devices()
    log(f"backend up after {time.perf_counter()-t0:.1f}s: "
        f"{len(devs)} x {devs[0].device_kind} ({devs[0].platform})")
    return devs


def _is_transient(e: BaseException) -> bool:
    s = f"{type(e).__name__}: {e}".lower()
    return any(tok in s for tok in (
        "unavailable", "connection refused", "connection failed",
        "remote_compile", "transport", "deadline_exceeded", "socket closed",
        "connection reset", "broken pipe"))


def model_flops_per_token(cfg, seq_len: int, mlm_k: int = None) -> float:
    """Matmul FLOPs per token, fwd+bwd (bwd = 2x fwd), BERT-Large shape.

    ``mlm_k``: with the gathered MLM head (max_predictions_per_seq), the
    dense+decode GEMMs run at K of S positions — count only that fraction
    so MFU stays honest about the work actually done."""
    e, i, L, v = (cfg.hidden_size, cfg.intermediate_size, cfg.num_layers,
                  cfg.vocab_size)
    per_layer = 8 * e * e + 4 * seq_len * e + 4 * e * i
    head_frac = 1.0 if mlm_k is None else mlm_k / seq_len
    head = (2 * e * e + 2 * e * v) * head_frac
    return 3.0 * (L * per_layer + head)


def bench_optimizer_speedup(params_like, steps: int = 20) -> float:
    """BASELINE metric #2: fused flat-buffer LAMB step time vs a naive
    per-param jitted update (optax-style tree of adam+trust-ratio ops)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedLAMB

    params = params_like
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 1e-3, params)

    fused = FusedLAMB(params, lr=1e-4, weight_decay=0.01)
    fused.step(grads)  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fused.step(grads)
    jax.block_until_ready(out)
    fused_dt = (time.perf_counter() - t0) / steps

    # naive: per-param adam + per-tensor trust ratio, jitted as one fn
    def naive_update(params, grads, m, v, count):
        b1, b2, eps, lr, wd = 0.9, 0.999, 1e-6, 1e-4, 0.01
        gsq = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                  for g in jax.tree.leaves(grads))
        gnorm = jnp.sqrt(gsq)
        clip = jnp.where(gnorm > 1.0, 1.0 / gnorm, 1.0)
        count = count + 1
        rbc1 = 1.0 / (1.0 - b1 ** count)
        rbc2 = 1.0 / (1.0 - b2 ** count)

        def upd(p, g, m, v):
            g = g.astype(jnp.float32) * clip
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            u = (m * rbc1) / (jnp.sqrt(v * rbc2) + eps) + wd * p
            pn = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
            un = jnp.sqrt(jnp.sum(u ** 2))
            ratio = jnp.where((pn > 0) & (un > 0), pn / un, 1.0)
            return p - lr * ratio * u, m, v

        out = jax.tree.map(upd, params, grads, m, v)
        # leaves are 3-tuples: select tuple elements, not array rows
        is_tup = lambda x: isinstance(x, tuple)  # noqa: E731
        new_p = jax.tree.map(lambda t: t[0], out, is_leaf=is_tup)
        new_m = jax.tree.map(lambda t: t[1], out, is_leaf=is_tup)
        new_v = jax.tree.map(lambda t: t[2], out, is_leaf=is_tup)
        return new_p, new_m, new_v, count

    naive = jax.jit(naive_update, donate_argnums=(0, 2, 3))
    m = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    v = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    count = jnp.zeros((), jnp.int32)
    p = params
    p, m, v, count = naive(p, grads, m, v, count)  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        p, m, v, count = naive(p, grads, m, v, count)
    jax.block_until_ready(p)
    naive_dt = (time.perf_counter() - t0) / steps
    log(f"optimizer step: fused {fused_dt*1e3:.2f}ms  "
        f"naive {naive_dt*1e3:.2f}ms  speedup {naive_dt/fused_dt:.2f}x")
    return naive_dt / fused_dt


def run_workload(devs, batch_per_chip: int, seq_len: int, steps: int):
    """Build + shard + compile + time one measurement. Raises on transient
    backend failures — the caller owns retry policy."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from apex_tpu.models import (BertForPreTraining, bert_large_config,
                                 bert_tiny_config, make_pretrain_step,
                                 synthetic_batch)
    from apex_tpu.optimizers import FusedLAMB

    n_chips = len(devs)
    batch_size = batch_per_chip * n_chips

    if os.environ.get("APEX_TPU_BENCH_CONFIG") == "tiny":
        cfg = bert_tiny_config(max_position_embeddings=max(128, seq_len))
    else:
        cfg = bert_large_config(max_position_embeddings=max(512, seq_len))
    # remat trades backward FLOPs for activation memory — required for the
    # larger escalated batches. Env wins; the tuned record's choice applies
    # ONLY when the batch also came from the record (an explicit
    # APEX_TPU_BENCH_BATCH override must not inherit a mismatched remat).
    remat_env = os.environ.get("APEX_TPU_BENCH_REMAT")
    batch_overridden = bool(int(os.environ.get("APEX_TPU_BENCH_BATCH", "0")))
    if remat_env is not None:
        remat = remat_env == "1"
    elif batch_overridden:
        remat = False
    else:
        remat = bool(_tuned_record().get("remat", False))
    if remat:
        import dataclasses

        cfg = dataclasses.replace(cfg, remat=True)
        log("remat enabled")
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    batch = synthetic_batch(rng, cfg, batch_size, seq_len)

    # data-parallel mesh over every local device; batch sharded over it,
    # params/opt-state replicated — XLA inserts the grad psum (SURVEY §3.3:
    # apex DDP's bucketed allreduce disappears into GSPMD)
    mesh = Mesh(np.asarray(devs), ("data",))
    data_sh = {k: NamedSharding(mesh, P("data", *[None] * (v.ndim - 1)))
               for k, v in batch.items()}
    repl = NamedSharding(mesh, P())
    batch = {k: jax.device_put(v, data_sh[k]) for k, v in batch.items()}

    log("initializing BERT params...")
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"], batch["attention_mask"])["params"]
    params = jax.device_put(params, repl)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    log(f"params: {n_params/1e6:.1f}M  batch={batch_size} ({batch_per_chip}/chip"
        f" x {n_chips} chips)  seq={seq_len}")

    step = make_pretrain_step(model)
    opt = FusedLAMB(
        params, lr=1e-4, weight_decay=0.01,
        exclude_from_weight_decay=lambda n: "bias" in n or "norm" in n.lower())
    opt.master = jax.device_put(opt.master, repl)
    opt.state = {k: jax.device_put(v, repl) for k, v in opt.state.items()}

    def train_step(p, i):
        loss, grads = step(p, batch, i)
        return loss, opt.step(grads)

    log("compiling + warmup...")
    t0 = time.perf_counter()
    loss, params = train_step(params, 0)
    jax.block_until_ready(params)
    log(f"first step (compile) {time.perf_counter()-t0:.1f}s loss={float(loss):.3f}")
    loss, params = train_step(params, 1)
    jax.block_until_ready(params)

    # verify the step really ran sharded (the smoke test asserts this key)
    x = batch["input_ids"]
    n_shards = len({s.device.id for s in x.addressable_shards})

    log(f"timing {steps} steps...")
    t0 = time.perf_counter()
    for i in range(steps):
        loss, params = train_step(params, 2 + i)
    jax.block_until_ready(params)
    dt = (time.perf_counter() - t0) / steps

    tokens = batch_size * seq_len
    tok_per_sec_chip = tokens / dt / n_chips
    mlm_k = (batch["mlm_positions"].shape[1]
             if "mlm_positions" in batch else None)
    flops = model_flops_per_token(cfg, seq_len, mlm_k) * tokens
    mfu = flops / dt / (peak_flops(devs[0]) * n_chips)
    log(f"step {dt*1e3:.1f}ms  loss={float(loss):.3f}  "
        f"tokens/s/chip={tok_per_sec_chip:.0f}  MFU={mfu*100:.1f}%")
    return dict(tok_per_sec_chip=tok_per_sec_chip, mfu=mfu, dt=dt,
                params=params, n_shards=n_shards, n_chips=n_chips,
                device=devs[0])


def _tuned_record() -> dict:
    """The measured winner from run_tpu_round.sh's batch escalation
    (bench_batch.json, committed once a window has compared 8/16/32)."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_batch.json")) as f:
            return json.load(f)
    except Exception:
        return {}


def _tuned_batch() -> int:
    return int(_tuned_record().get("batch_per_chip", 8))


def main():
    retries = int(os.environ.get("APEX_TPU_BENCH_RETRIES", "4"))
    wait_s = float(os.environ.get("APEX_TPU_BENCH_RETRY_WAIT", "30"))
    devs = init_backend(retries, wait_s)

    batch_per_chip = int(os.environ.get("APEX_TPU_BENCH_BATCH", "0")) \
        or _tuned_batch()
    seq_len = int(os.environ.get("APEX_TPU_BENCH_SEQ", "512"))
    steps = int(os.environ.get("APEX_TPU_BENCH_STEPS", "10"))
    compile_retries = int(os.environ.get("APEX_TPU_BENCH_COMPILE_RETRIES", "5"))
    platform = os.environ.get("APEX_TPU_BENCH_PLATFORM")
    init_timeout = int(os.environ.get("APEX_TPU_BENCH_INIT_TIMEOUT", "420"))

    # round-2 failure mode: probe ok, then the first compile hit a transient
    # `remote_compile: Connection refused`. Retry the whole workload with
    # exponential backoff, re-probing the tunnel between attempts.
    result = None
    last = None
    for attempt in range(1, compile_retries + 1):
        try:
            result = run_workload(devs, batch_per_chip, seq_len, steps)
            break
        except Exception as e:  # noqa: BLE001
            if not _is_transient(e):
                raise
            last = e
            backoff = min(wait_s * (2 ** (attempt - 1)), 240.0)
            log(f"workload attempt {attempt}/{compile_retries} hit transient "
                f"backend error: {type(e).__name__}: {e}\n"
                f"backing off {backoff:.0f}s then re-probing...")
            if attempt < compile_retries:
                time.sleep(backoff)
                try:
                    probe_backend(2, wait_s, platform, init_timeout)
                except RuntimeError as pe:
                    log(f"re-probe failed ({pe}); retrying anyway")
    if result is None:
        raise RuntimeError(
            f"workload failed after {compile_retries} attempts: {last}")

    try:
        opt_speedup = bench_optimizer_speedup(result["params"])
    except Exception:  # noqa: BLE001
        log("optimizer microbench failed:", traceback.format_exc())
        opt_speedup = None

    emit(result["tok_per_sec_chip"], "tokens/s/chip", result["mfu"] / 0.45,
         mfu=round(result["mfu"], 4), step_ms=round(result["dt"] * 1e3, 2),
         device=result["device"].device_kind, n_chips=result["n_chips"],
         n_data_shards=result["n_shards"],
         optimizer_speedup=(round(opt_speedup, 3)
                            if opt_speedup is not None else None))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001
        log(traceback.format_exc())
        emit(error=f"{type(e).__name__}: {e}")
        sys.exit(0)  # the JSON line IS the result; don't fail the driver
