"""Flagship benchmark: BERT-Large pretraining step (BASELINE.md config #2).

Runs the full training step — bf16 forward/backward with Pallas flash
attention + FusedLayerNorm, fused softmax-xentropy loss, FusedLAMB flat-buffer
optimizer — on the available device(s) and reports tokens/sec/chip and MFU.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline = measured MFU / 0.45 (the BASELINE.json north-star MFU target).
All diagnostics go to stderr.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# bf16 peak FLOPs/s per chip by device kind (public TPU specs)
PEAK_FLOPS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def peak_flops(device) -> float:
    kind = device.device_kind.lower()
    for token, f in PEAK_FLOPS:
        if token in kind:
            return f
    log(f"unknown device kind {device.device_kind!r}; assuming v5e peak")
    return 197e12


def model_flops_per_token(cfg, seq_len: int) -> float:
    """Matmul FLOPs per token, fwd+bwd (bwd = 2x fwd), BERT-Large shape."""
    e, i, L, v = (cfg.hidden_size, cfg.intermediate_size, cfg.num_layers,
                  cfg.vocab_size)
    per_layer = 8 * e * e + 4 * seq_len * e + 4 * e * i
    head = 2 * e * e + 2 * e * v
    return 3.0 * (L * per_layer + head)


def main():
    from apex_tpu.models import (BertForPreTraining, bert_large_config,
                                 make_pretrain_step, synthetic_batch)
    from apex_tpu.optimizers import FusedLAMB

    batch_size = int(os.environ.get("APEX_TPU_BENCH_BATCH", "8"))
    seq_len = int(os.environ.get("APEX_TPU_BENCH_SEQ", "512"))
    steps = int(os.environ.get("APEX_TPU_BENCH_STEPS", "10"))

    dev = jax.devices()[0]
    n_chips = len(jax.devices())
    log(f"devices: {n_chips} x {dev.device_kind} ({dev.platform})")

    cfg = bert_large_config(max_position_embeddings=max(512, seq_len))
    model = BertForPreTraining(cfg)
    rng = np.random.default_rng(0)
    batch = synthetic_batch(rng, cfg, batch_size, seq_len)

    log("initializing BERT-Large params...")
    params = model.init(jax.random.PRNGKey(0), batch["input_ids"],
                        batch["token_type_ids"], batch["attention_mask"])["params"]
    n_params = sum(x.size for x in jax.tree.leaves(params))
    log(f"params: {n_params/1e6:.1f}M")

    step = make_pretrain_step(model)
    opt = FusedLAMB(
        params, lr=1e-4, weight_decay=0.01,
        exclude_from_weight_decay=lambda n: "bias" in n or "norm" in n.lower())

    def train_step(p, i):
        loss, grads = step(p, batch, i)
        return loss, opt.step(grads)

    log("compiling + warmup...")
    t0 = time.perf_counter()
    loss, params = train_step(params, 0)
    jax.block_until_ready(params)
    log(f"first step (compile) {time.perf_counter()-t0:.1f}s loss={float(loss):.3f}")
    loss, params = train_step(params, 1)
    jax.block_until_ready(params)

    log(f"timing {steps} steps...")
    t0 = time.perf_counter()
    for i in range(steps):
        loss, params = train_step(params, 2 + i)
    jax.block_until_ready(params)
    dt = (time.perf_counter() - t0) / steps

    tokens = batch_size * seq_len
    tok_per_sec_chip = tokens / dt / n_chips
    flops = model_flops_per_token(cfg, seq_len) * tokens
    mfu = flops / dt / (peak_flops(dev) * n_chips)
    log(f"step {dt*1e3:.1f}ms  loss={float(loss):.3f}  "
        f"tokens/s/chip={tok_per_sec_chip:.0f}  MFU={mfu*100:.1f}%")

    print(json.dumps({
        "metric": "bert_large_pretrain_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
