"""Exit 0 iff the given bench artifact exists and has value > 0.

Shared predicate for run_tpu_round.sh / tpu_watch.sh — the single place
that knows what a 'done' bench artifact looks like.
"""
import json
import sys

try:
    with open(sys.argv[1]) as f:
        sys.exit(0 if json.load(f).get("value", 0) > 0 else 1)
except Exception:
    sys.exit(1)
