"""Logging helpers mirroring apex/transformer/log_util.py."""

import logging

_LOGGER_NAME = "apex_tpu"


def get_transformer_logger(name: str = _LOGGER_NAME) -> logging.Logger:
    return logging.getLogger(name)


def set_logging_level(verbosity) -> None:
    """Reference: apex/transformer/log_util.py:set_logging_level."""
    logging.getLogger(_LOGGER_NAME).setLevel(verbosity)
