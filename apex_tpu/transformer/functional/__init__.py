"""Reference: apex/transformer/functional/__init__.py."""

from apex_tpu.transformer.functional.fused_softmax import (  # noqa: F401
    FusedScaleMaskSoftmax,
    ScaledMaskedSoftmax,
    ScaledSoftmax,
    ScaledUpperTriangMaskedSoftmax,
)
from apex_tpu.transformer.functional.fused_rope import (  # noqa: F401
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
)
