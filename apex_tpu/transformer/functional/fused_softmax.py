"""FusedScaleMaskSoftmax — the kernel-dispatch facade.

Reference: apex/transformer/functional/fused_softmax.py:~30-200 — a module
that picks between three CUDA softmax kernels and an unfused torch fallback
based on dtype/shape/mask-type. Here every path lands on the one Pallas
scaled-softmax kernel (apex_tpu/ops/scaled_softmax.py); the dispatch logic is
preserved (``is_kernel_available`` mirrors the reference's constraints so
callers can introspect it) but there is no seqlen cap to fall back around —
the fallback exists only for ``softmax_in_fp32 + scale`` pre-casting
semantics.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.scaled_softmax import (
    MASK_FILL,
    scaled_masked_softmax as _scaled_masked_softmax,
    scaled_softmax as _plain_scaled_softmax,
    scaled_upper_triang_masked_softmax as _scaled_upper_triang,
)
from apex_tpu.transformer.enums import AttnMaskType


class ScaledUpperTriangMaskedSoftmax:
    """Reference: ScaledUpperTriangMaskedSoftmax autograd fn (causal, 3D input)."""

    @staticmethod
    def apply(x, scale):
        return _scaled_upper_triang(x, scale)


class ScaledMaskedSoftmax:
    """Reference: ScaledMaskedSoftmax autograd fn (4D input + bool mask)."""

    @staticmethod
    def apply(x, mask, scale):
        return _scaled_masked_softmax(x, mask, scale)


class ScaledSoftmax:
    """Reference: ScaledSoftmax autograd fn (no mask)."""

    @staticmethod
    def apply(x, scale):
        return _plain_scaled_softmax(x, scale)


class FusedScaleMaskSoftmax:
    """fused operation: scaling + mask + softmax.

    Mirrors the reference ctor exactly (apex/transformer/functional/
    fused_softmax.py:FusedScaleMaskSoftmax):

    Args:
      input_in_fp16 / input_in_bf16: declared activation dtype (validated
        against actual inputs like the reference asserts).
      attn_mask_type: AttnMaskType.{padding,causal}.
      scaled_masked_softmax_fusion: use the fused kernel when possible.
      mask_func: callable(x, mask) -> masked x, used on the unfused path
        (the reference's torch fallback).
      softmax_in_fp32: upcast before softmax on the unfused path.
      scale: optional scale factor (requires softmax_in_fp32 when set,
        same assertion as the reference).
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = False,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags cannot be active at the same time.")
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """The reference gates on dtype/seqlen/alignment (16 < sk <= 4096,
        sq % 4 == 0, ...); the Pallas kernel has none of those limits, so
        availability reduces to the fusion flag."""
        return self.scaled_masked_softmax_fusion

    def __call__(self, input, mask=None):
        assert input.ndim == 4
        b, np_, sq, sk = input.shape
        if self.is_kernel_available(mask, b, np_, sq, sk):
            return self.forward_fused_softmax(input, mask)
        return self.forward_torch_softmax(input, mask)

    # reference method names kept for parity
    def forward_fused_softmax(self, input, mask):
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            assert input.shape[2] == input.shape[3], (
                "causal mask is only for self attention")
            x = input.reshape(-1, input.shape[2], input.shape[3])
            probs = ScaledUpperTriangMaskedSoftmax.apply(x, scale)
            return probs.reshape(input.shape)
        return ScaledMaskedSoftmax.apply(input, mask, scale)

    def forward_torch_softmax(self, input, mask):
        orig_dtype = input.dtype
        if self.input_in_float16 and self.softmax_in_fp32:
            input = input.astype(jnp.float32)
        if self.scale is not None:
            input = input * self.scale
        if self.attn_mask_type == AttnMaskType.causal and mask is None:
            sq, sk = input.shape[2], input.shape[3]
            mask = ~jnp.tril(jnp.ones((1, 1, sq, sk), bool))
        if mask is not None and self.mask_func is not None:
            input = self.mask_func(input, mask)
        elif mask is not None:
            input = jnp.where(mask, MASK_FILL, input)
        probs = jax.nn.softmax(input, axis=-1)
        if self.input_in_float16 and self.softmax_in_fp32:
            probs = probs.astype(orig_dtype)
        return probs
