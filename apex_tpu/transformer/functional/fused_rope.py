"""Fused rotary positional embedding.

Reference: ``fused_rotary_positional_embedding`` extension
(csrc/megatron/fused_rotary_positional_embedding.h/.cpp/_cuda.cu — RoPE apply
fwd/bwd, cached cos/sin variant). On TPU this is a pure elementwise rewrite
that XLA fuses into the surrounding matmuls, so there is deliberately no
Pallas kernel: a hand kernel would only block fusion (SURVEY.md §2.2 row
"fused_rotary_positional_embedding"). Gradients come from autodiff of the
same expression, which matches the reference backward (rotation transposed).

Layout matches the reference: t [sq, b, np, hn], freqs [sq, 1, 1, hn2<=hn];
only the first hn2 features are rotated (partial-rotary supported).
"""

from __future__ import annotations

import jax.numpy as jnp


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate((-x2, x1), axis=-1)


def fused_apply_rotary_pos_emb(t, freqs):
    """Apply RoPE with freqs in radians (reference fused_apply_rotary_pos_emb).

    t: [sq, b, np, hn]; freqs: [sq, 1, 1, hn2], hn2 <= hn, hn2 even.
    """
    hn2 = freqs.shape[-1]
    rot, pass_through = t[..., :hn2], t[..., hn2:]
    cos = jnp.cos(freqs).astype(t.dtype)
    sin = jnp.sin(freqs).astype(t.dtype)
    rot = rot * cos + _rotate_half(rot) * sin
    if pass_through.shape[-1] == 0:
        return rot
    return jnp.concatenate((rot, pass_through), axis=-1)


def fused_apply_rotary_pos_emb_cached(t, cos_, sin_):
    """Cached-cos/sin variant (reference fused_apply_rotary_pos_emb_cached)."""
    hn2 = cos_.shape[-1]
    rot, pass_through = t[..., :hn2], t[..., hn2:]
    rot = rot * cos_.astype(t.dtype) + _rotate_half(rot) * sin_.astype(t.dtype)
    if pass_through.shape[-1] == 0:
        return rot
    return jnp.concatenate((rot, pass_through), axis=-1)
