"""Megatron-style model parallelism on a named TPU mesh.

Reference: apex/transformer/ — parallel_state process groups, tensor_parallel
layers/mappings/cross_entropy/random, pipeline_parallel schedules,
functional.FusedScaleMaskSoftmax. Rebuilt here over jax.shard_map + XLA
collectives (SURVEY.md §2.4).
"""

from apex_tpu.transformer import moe  # noqa: F401
from apex_tpu.transformer import parallel_state  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType  # noqa: F401
