"""Batch broadcast across the tensor-parallel group.

Reference: apex/transformer/tensor_parallel/data.py:broadcast_data — TP rank 0
loads the batch and torch-broadcasts each named tensor to the other TP ranks
(they must not each read the dataloader).

TPU design: in SPMD the input pipeline feeds every device coherently via
sharding (a replicated-over-``model`` sharding IS the broadcast), so the
common path is a no-op. The explicit collective survives for shard_map loops
where each TP rank computed/loaded its own copy and rank 0's must win.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu import collectives as coll
from apex_tpu.mesh import MODEL_AXIS


def broadcast_data(keys: Sequence[str], data: Dict[str, jax.Array], datatype=None,
                   axis_name: str = MODEL_AXIS) -> Dict[str, jax.Array]:
    """Return ``{k: rank-0's data[k]}`` for k in keys.

    Matches the reference signature (``datatype`` kept for parity; JAX arrays
    carry their dtype). Inside shard_map the values are replaced by TP rank
    0's via collective broadcast; outside (axis unbound) the data is already
    coherent and is returned as-is.
    """
    out = {}
    for k in keys:
        v = data[k]
        if datatype is not None:
            v = v.astype(datatype)
        try:
            lax.axis_size(axis_name)
        except NameError:
            out[k] = v
            continue
        out[k] = coll.broadcast(v, axis_name, src_index=0)
    return out
