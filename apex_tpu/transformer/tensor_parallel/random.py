"""Model-parallel RNG state management + activation checkpointing.

Reference: apex/transformer/tensor_parallel/random.py:~50-300 —
``CudaRNGStatesTracker`` keeps named CUDA RNG streams so dropout draws
*differently* across TP ranks inside TP regions (stream seeded
``seed + 2718 + tp_rank``) but *identically* outside them;
``model_parallel_cuda_manual_seed`` wires the two streams;
``checkpoint()``/``CheckpointFunction`` recompute activations in backward,
restoring both RNG streams so recomputed dropout masks match.

TPU design: JAX RNG is functional, so a "stream" is a key + a fold counter.
The tracker hands out keys; the model-parallel stream folds in the TP axis
index (``lax.axis_index``) when bound, reproducing per-rank decorrelation
without any device state. ``checkpoint`` maps to ``jax.checkpoint`` — XLA
replays the same functional keys during recompute BY CONSTRUCTION, which is
the property the reference needs two saved CUDA states to get.
"""

from __future__ import annotations

import contextlib
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import MODEL_AXIS

# reference: _MODEL_PARALLEL_RNG_TRACKER_NAME
_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


class RNGStatesTracker:
    """Named functional RNG streams (reference: CudaRNGStatesTracker).

    ``add(name, seed)`` registers a stream; ``fork(name)`` is a context
    manager inside which ``get_key()`` returns fresh keys from that stream.
    Streams registered as model-parallel fold the TP axis index into every
    key so ranks decorrelate (the reference's per-rank seed offset).
    """

    def __init__(self):
        self.reset()

    def reset(self):
        self._seeds: Dict[str, int] = {}
        self._counters: Dict[str, int] = {}
        self._model_parallel: Dict[str, bool] = {}
        self._active: list = []
        self._step = None

    def get_states(self):
        """Checkpointable state (reference: get_states returns CUDA states)."""
        return {"seeds": dict(self._seeds), "counters": dict(self._counters),
                "model_parallel": dict(self._model_parallel)}

    def set_states(self, states):
        self._seeds = dict(states["seeds"])
        self._counters = dict(states["counters"])
        self._model_parallel = dict(states["model_parallel"])

    def add(self, name: str, seed: int, model_parallel: bool = False):
        if name in self._seeds:
            raise RuntimeError(f"RNG stream {name} already exists")
        self._seeds[name] = int(seed)
        self._counters[name] = 0
        self._model_parallel[name] = model_parallel

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        if name not in self._seeds:
            raise RuntimeError(f"RNG stream {name} is not registered "
                               "(call model_parallel_seed first)")
        self._active.append(name)
        try:
            yield
        finally:
            self._active.pop()

    @contextlib.contextmanager
    def with_step(self, step):
        """Bind a (traced) training-step value folded into every key.

        ``get_key``'s Python-side call counter distinguishes call *sites*
        within one trace, but a jitted train step is traced ONCE and
        re-executed — without a traced step value every executed step would
        replay identical keys (and so identical dropout masks). Wrap the
        jitted body in ``tracker.with_step(step)`` with ``step`` a traced
        int to decorrelate steps (the analog of the reference's CUDA RNG
        state advancing between steps).
        """
        prev, self._step = self._step, step
        try:
            yield
        finally:
            self._step = prev

    def get_key(self, axis_name: str = MODEL_AXIS, step=None):
        """Next key of the active (or default) stream.

        ``step``: optional traced step value (overrides ``with_step``).
        Inside a reused jitted step one of the two MUST be supplied — the
        call counter alone is baked into the trace (see ``with_step``).
        """
        name = self._active[-1] if self._active else None
        if name is None:
            raise RuntimeError("get_key() called outside tracker.fork(...)")
        key = jax.random.PRNGKey(self._seeds[name])
        key = jax.random.fold_in(key, self._counters[name])
        self._counters[name] += 1
        step = step if step is not None else self._step
        if step is not None:
            key = jax.random.fold_in(key, step)
        if self._model_parallel.get(name):
            try:
                key = jax.random.fold_in(key, lax.axis_index(axis_name))
            except NameError:
                pass
        return key


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    """Reference: get_cuda_rng_tracker."""
    return _TRACKER


# torch-named alias for drop-in ports
get_cuda_rng_tracker = get_rng_state_tracker


def model_parallel_seed(seed: int) -> None:
    """Reference: model_parallel_cuda_manual_seed — default stream seeded
    ``seed`` (same on all TP ranks), model-parallel stream ``seed + 2718``
    with the rank folded in per key."""
    _TRACKER.reset()
    _TRACKER.add("default", seed, model_parallel=False)
    _TRACKER.add(_MODEL_PARALLEL_RNG_TRACKER_NAME, seed + 2718,
                 model_parallel=True)


model_parallel_cuda_manual_seed = model_parallel_seed


def checkpoint(function, distribute_saved_activations: bool = False,
               *args, **kwargs):
    """Activation checkpointing (reference: random.py:checkpoint /
    CheckpointFunction — recompute in backward with RNG streams restored).

    ``jax.checkpoint`` replays the traced function in backward; functional
    RNG keys are part of the trace, so recomputed dropout masks are
    bit-identical without any state save/restore.
    ``distribute_saved_activations`` (reference: shard the saved input over
    TP ranks to save memory) has no explicit mechanism here — XLA SPMD keeps
    residuals sharded per the activation shardings already.
    """
    return jax.checkpoint(function)(*args, **kwargs)
