"""Vocab-parallel cross entropy.

Reference: apex/transformer/tensor_parallel/cross_entropy.py
(_VocabParallelCrossEntropy) — logits sharded over vocab on the TP axis:
local max -> all-reduce MAX -> local sum-exp -> all-reduce SUM -> each rank
contributes the target logit iff the target falls in its vocab range
(all-reduced too); backward scales the local softmax and subtracts the
one-hot where owned. Autodiff through the psums reproduces that backward
exactly, so no custom vjp is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    reduce_from_tensor_model_parallel_region as _allreduce,
)


def vocab_parallel_cross_entropy(vocab_parallel_logits, target,
                                 label_smoothing: float = 0.0,
                                 axis_name: str = MODEL_AXIS):
    """Per-token loss for logits sharded over the last (vocab) dim.

    Args:
      vocab_parallel_logits: [..., vocab/tp] local shard (inside shard_map).
      target: [...] int32 GLOBAL vocab ids.
    Returns per-token losses [...] (fp32), matching the reference's
    ``vocab_parallel_cross_entropy`` call surface.
    """
    logits = vocab_parallel_logits.astype(jnp.float32)
    per = logits.shape[-1]
    rank = lax.axis_index(axis_name)
    start = rank * per

    # numerically-stable global logsumexp: psum-max then psum-sumexp
    local_max = jnp.max(logits, axis=-1)
    # stop_gradient: the max shift is for numerical stability only and its
    # gradient contribution cancels analytically (pmax has no diff rule;
    # the reference likewise treats logits_max as a constant in backward)
    global_max = lax.pmax(lax.stop_gradient(local_max), axis_name)
    shifted = logits - global_max[..., None]
    sum_exp = _allreduce(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)
    lse = jnp.log(sum_exp)

    # target logit: owned by exactly one rank, psum combines
    local_t = target - start
    owned = (local_t >= 0) & (local_t < per)
    local_t = jnp.clip(local_t, 0, per - 1)
    t_logit = jnp.take_along_axis(shifted, local_t[..., None], axis=-1)[..., 0]
    t_logit = _allreduce(jnp.where(owned, t_logit, 0.0), axis_name)

    loss = lse - t_logit
    if label_smoothing > 0.0:
        # reference: smoothed loss mixes in the mean log-prob over the full
        # vocab, with the smoothing rescaled by vocab/(vocab-1) because the
        # uniform mass excludes the target class; mean over a global-vocab
        # sum of (shifted - lse), psum'd
        vocab = per * lax.axis_size(axis_name)
        smoothing = label_smoothing * vocab / (vocab - 1)
        mean_logprob = (_allreduce(jnp.sum(shifted, axis=-1), axis_name)
                        / vocab - lse)
        loss = (1.0 - smoothing) * loss - smoothing * mean_logprob
    return loss


# reference exposes the autograd Function under this name too
_VocabParallelCrossEntropy = vocab_parallel_cross_entropy
