"""Megatron-style tensor-parallel layers.

Reference: apex/transformer/tensor_parallel/layers.py:~200-700 —
``ColumnParallelLinear`` (weight split along output features),
``RowParallelLinear`` (split along input features), ``VocabParallelEmbedding``
(embedding table split along vocab), each issuing explicit collectives via the
mappings-region functions in fwd/bwd.

TPU design: flax modules whose parameters are the PER-SHARD weights; they run
inside ``shard_map`` with the ``model`` axis bound (the collectives come from
apex_tpu/transformer/tensor_parallel/mappings.py, whose custom-vjp pairs
reproduce the reference's autograd Functions). Per-shard initialization folds
the shard index into the RNG key so shards draw independent values — the
functional restatement of the reference's
``_initialize_affine_weight_gpu(..., partition_dim)`` per-rank init.

Reference knobs with no TPU mechanism (``no_async_tensor_model_parallel_
allreduce`` — XLA's latency-hiding scheduler owns collective/compute overlap;
``use_cpu_initialization``; ``params_dtype`` handled by ``param_dtype``)
are accepted for API parity and recorded.
``gradient_accumulation_fusion`` IS mechanized: it routes the GEMM through
``fp32_wgrad_matmul`` (single fp32-accumulating wgrad GEMM) and pairs with
``apex_tpu.optimizers.grad_accum.MainGradBuffer`` for the persistent fp32
main-grad across microbatches.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import MODEL_AXIS
from apex_tpu.transformer.tensor_parallel import mappings
from apex_tpu.transformer.utils import divide


# public guard lives next to the collectives; kept under the old name for
# intra-package use
_axis_bound = mappings.axis_is_bound


@jax.custom_vjp
def fp32_wgrad_matmul(x, w):
    """``y = x @ w.T`` (w torch-layout (out, in), fp32) whose backward
    computes the weight grad as ONE fp32-accumulating GEMM from the 16-bit
    operands — the ``gradient_accumulation_fusion`` mechanism (reference:
    csrc/megatron/fused_weight_gradient_dense.cpp, wgrad GEMM accumulating
    into a persistent fp32 ``main_grad``). On the MXU bf16xbf16->fp32 is the
    native mode, so the fp32 wgrad costs nothing extra; the persistent
    accumulation across microbatches is ``MainGradBuffer``
    (apex_tpu/optimizers/grad_accum.py)."""
    return x @ w.astype(x.dtype).T


def _fp32_wgrad_fwd(x, w):
    return fp32_wgrad_matmul(x, w), (x, w)


def _fp32_wgrad_bwd(res, g):
    x, w = res
    dx = (g @ w.astype(g.dtype)).astype(x.dtype)
    # collapse all leading (batch/seq) dims; fp32 accumulation on the MXU
    g2 = g.reshape(-1, g.shape[-1])
    x2 = x.reshape(-1, x.shape[-1])
    dw = jax.lax.dot_general(
        g2, x2, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return dx, dw.astype(w.dtype)


fp32_wgrad_matmul.defvjp(_fp32_wgrad_fwd, _fp32_wgrad_bwd)


def _shard_init(base_init: Callable, axis_name: str) -> Callable:
    """Wrap an initializer so each model-parallel shard draws independent
    values (reference: _initialize_affine_weight_gpu seeds per TP rank via
    the model-parallel RNG tracker)."""

    def init(key, shape, dtype):
        try:
            idx = lax.axis_index(axis_name)
            key = jax.random.fold_in(key, idx)
        except NameError:
            pass  # axis unbound: single-shard init
        return base_init(key, shape, dtype)

    return init


def _quantized_params(mod, qkind: str, out_features: int, in_features: int,
                      group_size: int, scale_init: Callable):
    """Declare the (weight, scale) param pair of a quantized linear.

    int8/fp8: weight ``(out, in)`` at the storage dtype, per-channel
    scale ``(out,)``. int4: weight ``(out, in//2)`` uint8 (two nibbles
    per byte, group-local packing — ops/quant.py), scale
    ``(in//group_size, out)`` — group axis major so row-parallel shards
    slice whole groups; out axis minor so it shards with the output
    channels. All inits are placeholders (zeros weight / ones scale);
    real values come from models/quantize.quantize_params_like."""
    from apex_tpu.ops.quant import validate_int4_group, weight_storage_dtype

    if qkind == "int4":
        validate_int4_group(in_features, group_size)
        w = mod.param("weight", nn.initializers.zeros,
                      (out_features, in_features // 2), jnp.uint8)
        scale = mod.param("scale", scale_init,
                          (in_features // group_size, out_features),
                          jnp.float32)
    else:
        w = mod.param("weight", nn.initializers.zeros,
                      (out_features, in_features),
                      weight_storage_dtype(qkind))
        scale = mod.param("scale", scale_init, (out_features,), jnp.float32)
    return w, scale


class ColumnParallelLinear(nn.Module):
    """Y = X A^T + b with A split along its OUTPUT dim over ``model``.

    Reference: layers.py ColumnParallelLinear — fwd: copy-to-region (or SP
    all-gather) then local GEMM; bwd: input-grad all-reduce (or SP
    reduce-scatter). ``gather_output`` all-gathers the output shards;
    ``skip_bias_add`` returns (output, bias) for the caller to fuse.
    """

    input_size: int
    output_size: int
    bias: bool = True
    gather_output: bool = True
    init_method: Optional[Callable] = None
    stride: int = 1
    keep_master_weight_for_test: bool = False
    skip_bias_add: bool = False
    no_async_tensor_model_parallel_allreduce: bool = False
    params_dtype: Any = jnp.float32
    use_cpu_initialization: bool = False
    gradient_accumulation_fusion: bool = False
    sequence_parallel_enabled: bool = False
    world_size: Optional[int] = None      # default: tp size of the global mesh
    axis_name: str = MODEL_AXIS
    # quantized weight streaming (ops/quant.py): False/None fp, True/"int8"
    # per-channel int8, "fp8" e4m3, "int4" packed nibbles with
    # per-(out-channel, group) scales. The matmul runs the fused
    # dequant-matmul Pallas kernel (weight-only quantization — dequant in
    # VMEM next to the contraction). Inference-only (round has zero
    # gradient)
    quantize: Any = False
    quantize_group_size: int = 128        # int4 grouping (power of two)

    def _world(self) -> int:
        if self.world_size is not None:
            return self.world_size
        from apex_tpu.transformer import parallel_state

        return parallel_state.get_tensor_model_parallel_world_size()

    @nn.compact
    def __call__(self, x):
        world = self._world()
        out_local = divide(self.output_size, world)
        init = self.init_method or nn.initializers.lecun_normal()
        from apex_tpu.ops.quant import resolve_weight_dtype

        qkind = resolve_weight_dtype(self.quantize)
        if qkind:
            if self.gradient_accumulation_fusion:
                raise ValueError(
                    "quantize is an inference path; it cannot combine with "
                    "gradient_accumulation_fusion")
            # init is a placeholder: real values come from
            # models/quantize.quantize_params_like on a trained checkpoint
            w, w_scale = _quantized_params(
                self, qkind, out_local, self.input_size,
                self.quantize_group_size,
                _shard_init(nn.initializers.ones, self.axis_name))
        else:
            # weight layout matches the reference: (out_local, in)
            w = self.param("weight", _shard_init(init, self.axis_name),
                           (out_local, self.input_size), self.params_dtype)
        b = (self.param("bias", _shard_init(nn.initializers.zeros,
                                            self.axis_name),
                        (out_local,), self.params_dtype)
             if self.bias else None)

        bound = _axis_bound(self.axis_name)
        if bound:
            if self.sequence_parallel_enabled:
                x = mappings.gather_from_sequence_parallel_region(
                    x, self.axis_name, True)
            else:
                x = mappings.copy_to_tensor_model_parallel_region(
                    x, self.axis_name)
        if qkind:
            from apex_tpu.ops.quant import fused_dequant_matmul

            y = fused_dequant_matmul(x, w, w_scale)
        elif self.gradient_accumulation_fusion:
            y = fp32_wgrad_matmul(x, w)
        else:
            y = x @ w.astype(x.dtype).T
        bias_out = None
        if b is not None:
            if self.skip_bias_add:
                bias_out = b
            else:
                y = y + b.astype(y.dtype)
        if self.gather_output:
            if self.sequence_parallel_enabled:
                raise RuntimeError(
                    "gather_output is incompatible with "
                    "sequence_parallel_enabled (same as the reference)")
            if bound:
                y = mappings.gather_from_tensor_model_parallel_region(
                    y, self.axis_name)
        return (y, bias_out) if self.skip_bias_add else y

    forward = __call__


class RowParallelLinear(nn.Module):
    """Y = X A^T + b with A split along its INPUT dim over ``model``.

    Reference: layers.py RowParallelLinear — fwd: local GEMM then all-reduce
    (or SP reduce-scatter); ``input_is_parallel`` skips the input scatter
    (outputs of a preceding ColumnParallelLinear are already sharded).
    Bias is added AFTER the reduction, on the full output.
    """

    input_size: int
    output_size: int
    bias: bool = True
    input_is_parallel: bool = False
    init_method: Optional[Callable] = None
    stride: int = 1
    keep_master_weight_for_test: bool = False
    skip_bias_add: bool = False
    params_dtype: Any = jnp.float32
    use_cpu_initialization: bool = False
    gradient_accumulation_fusion: bool = False
    sequence_parallel_enabled: bool = False
    world_size: Optional[int] = None
    axis_name: str = MODEL_AXIS
    # quantized weight streaming — see ColumnParallelLinear.quantize.
    # Dequant happens inside each rank's fused kernel BEFORE the
    # partial-sum reduction, so per-channel (int8/fp8) scales span the
    # full row and int4 group scales slice with the input shard —
    # either way the reduction sums already-dequantized partials
    quantize: Any = False
    quantize_group_size: int = 128

    def _world(self) -> int:
        if self.world_size is not None:
            return self.world_size
        from apex_tpu.transformer import parallel_state

        return parallel_state.get_tensor_model_parallel_world_size()

    @nn.compact
    def __call__(self, x):
        world = self._world()
        in_local = divide(self.input_size, world)
        init = self.init_method or nn.initializers.lecun_normal()
        from apex_tpu.ops.quant import resolve_weight_dtype

        qkind = resolve_weight_dtype(self.quantize)
        if qkind:
            if self.gradient_accumulation_fusion:
                raise ValueError(
                    "quantize is an inference path; it cannot combine with "
                    "gradient_accumulation_fusion")
            w, w_scale = _quantized_params(
                self, qkind, self.output_size, in_local,
                self.quantize_group_size, nn.initializers.ones)
        else:
            w = self.param("weight", _shard_init(init, self.axis_name),
                           (self.output_size, in_local), self.params_dtype)
        # bias is replicated (applied post-reduce), not sharded
        b = (self.param("bias", nn.initializers.zeros, (self.output_size,),
                        self.params_dtype)
             if self.bias else None)

        bound = _axis_bound(self.axis_name)
        if not self.input_is_parallel:
            if self.sequence_parallel_enabled:
                raise RuntimeError(
                    "sequence_parallel_enabled requires input_is_parallel "
                    "(same as the reference)")
            if bound:
                x = mappings.scatter_to_tensor_model_parallel_region(
                    x, self.axis_name)
        if qkind:
            from apex_tpu.ops.quant import fused_dequant_matmul

            y = fused_dequant_matmul(x, w, w_scale)
        elif self.gradient_accumulation_fusion:
            y = fp32_wgrad_matmul(x, w)
        else:
            y = x @ w.astype(x.dtype).T
        if bound:
            if self.sequence_parallel_enabled:
                y = mappings.reduce_scatter_to_sequence_parallel_region(
                    y, self.axis_name)
            else:
                y = mappings.reduce_from_tensor_model_parallel_region(
                    y, self.axis_name)
        bias_out = None
        if b is not None:
            if self.skip_bias_add:
                bias_out = b
            else:
                y = y + b.astype(y.dtype)
        return (y, bias_out) if self.skip_bias_add else y

    forward = __call__


class VocabParallelEmbedding(nn.Module):
    """Embedding table split along the vocab dim over ``model``.

    Reference: layers.py VocabParallelEmbedding — each rank owns vocab range
    [rank*per, (rank+1)*per); out-of-range tokens lookup garbage that is
    masked to zero, then an all-reduce combines the shards.
    """

    num_embeddings: int
    embedding_dim: int
    init_method: Optional[Callable] = None
    params_dtype: Any = jnp.float32
    use_cpu_initialization: bool = False
    world_size: Optional[int] = None
    axis_name: str = MODEL_AXIS

    def _world(self) -> int:
        if self.world_size is not None:
            return self.world_size
        from apex_tpu.transformer import parallel_state

        return parallel_state.get_tensor_model_parallel_world_size()

    def setup(self):
        per = divide(self.num_embeddings, self._world())
        init = self.init_method or nn.initializers.normal(0.02)
        self.weight = self.param("weight", _shard_init(init, self.axis_name),
                                 (per, self.embedding_dim), self.params_dtype)

    def __call__(self, input_ids):
        w = self.weight
        per = w.shape[0]
        if not _axis_bound(self.axis_name):
            if self._world() != 1 and not self.is_initializing():
                # with a sharded table and no bound axis we'd silently return
                # wrong embeddings for ids >= vocab/tp — refuse instead
                # (during flax init only shapes matter, so the clamp path is
                # allowed there: eval_shape/init run outside shard_map)
                raise RuntimeError(
                    "VocabParallelEmbedding with world_size>1 must run "
                    f"inside shard_map with the '{self.axis_name}' axis "
                    "bound (the table holds only a vocab shard)")
            return jnp.take(w, jnp.clip(input_ids, 0, per - 1), axis=0)
        rank = lax.axis_index(self.axis_name)
        start = rank * per
        local = input_ids - start
        in_range = (local >= 0) & (local < per)
        local = jnp.clip(local, 0, per - 1)
        emb = jnp.take(w, local, axis=0)
        emb = jnp.where(in_range[..., None], emb, 0.0)
        return mappings.reduce_from_tensor_model_parallel_region(
            emb, self.axis_name)

    def attend(self, x):
        """Tied LM head: logits of x against the LOCAL vocab shard
        (output is vocab-parallel; pair with vocab_parallel_cross_entropy).
        The nn.Embed.attend idiom for Megatron's tied embeddings. The input
        enters a model-parallel region first (reference: Megatron's
        parallel_lm_logits copies x into the TP region) so the backward
        all-reduces the per-rank partial cotangents of x."""
        if _axis_bound(self.axis_name):
            x = mappings.copy_to_tensor_model_parallel_region(x, self.axis_name)
        return x @ self.weight.T.astype(x.dtype)

    forward = __call__
