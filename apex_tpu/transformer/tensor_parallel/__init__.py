"""Tensor (+sequence) parallelism: Megatron-style layers over shard_map.

Reference: apex/transformer/tensor_parallel/ — layers.py, mappings.py,
cross_entropy.py, random.py, data.py, utils.py (SURVEY.md §2.4).
"""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data  # noqa: F401
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    checkpoint,
    get_cuda_rng_tracker,
    get_rng_state_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_seed,
)
from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.utils import divide, split_tensor_along_last_dim  # noqa: F401
