"""Tensor (+sequence) parallelism: Megatron-style layers over shard_map.

Reference: apex/transformer/tensor_parallel/ — layers.py, mappings.py,
cross_entropy.py, random.py, data.py, utils.py (SURVEY.md §2.4).
"""

from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.utils import divide, split_tensor_along_last_dim  # noqa: F401
