"""TP/SP collective regions as differentiable functions.

Reference: apex/transformer/tensor_parallel/mappings.py:~30-250 — the four
model-parallel regions (_CopyToModelParallelRegion,
_ReduceFromModelParallelRegion, _ScatterToModelParallelRegion,
_GatherFromModelParallelRegion) and the three sequence-parallel regions
(_ScatterToSequenceParallelRegion, _GatherFromSequenceParallelRegion,
_ReduceScatterToSequenceParallelRegion), each a torch.autograd.Function whose
forward/backward issue explicit NCCL collectives.

TPU design: the same fwd/bwd collective pairs expressed with ``jax.custom_vjp``
over XLA collectives. All functions must run inside ``shard_map`` with the
given axis bound. Scatter/gather for the *model* region act on the LAST dim
(hidden); sequence-parallel regions act on the FIRST dim (sequence), matching
the reference.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax

from apex_tpu import collectives as coll
from apex_tpu.mesh import MODEL_AXIS


def _split_along(x, axis_name, dim):
    world = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    chunk = x.shape[dim] // world
    return lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=dim)


def axis_is_bound(axis_name) -> bool:
    """True iff ``axis_name`` is bound (we are inside shard_map). Lets layers
    trace outside shard_map (eager init, tp=1 use) with collectives reduced
    to identity."""
    try:
        lax.axis_size(axis_name)
        return True
    except NameError:
        return False


def _ensure_varying(g, axis_name):
    """Cotangents entering a custom-vjp backward may lack the axis in their
    vma (notably under ``shard_map(check_vma=False)``, where cotangents come
    in unmarked); variant->invariant collectives (psum/all_gather/
    reduce_scatter) reject such inputs. pcast-to-varying is a semantic no-op
    that restores the marking."""
    if axis_name not in getattr(jax.typeof(g), "vma", frozenset()):
        try:
            return lax.pcast(g, axis_name, to="varying")
        except NameError:
            return g
    return g


# --- copy: identity fwd / all-reduce bwd -------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name=MODEL_AXIS):
    """Reference: mappings.py:_CopyToModelParallelRegion (fwd identity,
    bwd all-reduce). Forward is ``pcast(..., to='varying')`` — identity on
    data, marks the value as varying over the TP axis; backward psums the
    cotangent, exactly the reference's autograd pair. Explicit custom_vjp
    (rather than relying on pvary's builtin transpose) so the backward also
    works under ``check_vma=False``, where pvary's transpose receives an
    unmarked cotangent and rejects it."""
    return lax.pcast(x, axis_name, to="varying")


def _copy_fwd(x, axis_name):
    return lax.pcast(x, axis_name, to="varying"), None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(_ensure_varying(g, axis_name), axis_name),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# --- reduce: all-reduce fwd / identity bwd -----------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name=MODEL_AXIS):
    """Reference: mappings.py:_ReduceFromModelParallelRegion — all-reduce
    forward, IDENTITY backward (each rank keeps the output cotangent).
    Explicit custom_vjp: relying on ``lax.psum``'s built-in transpose is
    wrong under ``check_vma=False``, where that transpose is itself a psum —
    every rank independently seeds its loss, and the transpose-psum sums the
    seeds, inflating all upstream gradients by the axis size per region
    crossed (measured 4x/16x/64x at tp=4)."""
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    # identity per rank; pcast restores the 'varying' marking the primal
    # input carried (semantic no-op)
    return (_ensure_varying(g, axis_name),)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# --- scatter (last dim): split fwd / all-gather bwd --------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name=MODEL_AXIS):
    """Reference: mappings.py:_ScatterToModelParallelRegion."""
    return _split_along(x, axis_name, x.ndim - 1)


def _scatter_fwd(x, axis_name):
    return _split_along(x, axis_name, x.ndim - 1), None


def _scatter_bwd(axis_name, _, g):
    g = _ensure_varying(g, axis_name)
    return (coll.all_gather(g, axis_name, axis=g.ndim - 1),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


# --- gather (last dim): all-gather fwd / split bwd ---------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name=MODEL_AXIS):
    """Reference: mappings.py:_GatherFromModelParallelRegion."""
    return coll.all_gather(x, axis_name, axis=x.ndim - 1)


def _gather_fwd(x, axis_name):
    return coll.all_gather(x, axis_name, axis=x.ndim - 1), None


def _gather_bwd(axis_name, _, g):
    return (_split_along(_ensure_varying(g, axis_name), axis_name, g.ndim - 1),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# --- sequence-parallel regions (first dim = sequence) ------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis_name=MODEL_AXIS):
    """Reference: mappings.py:_ScatterToSequenceParallelRegion — split the
    sequence dim at SP-region entry (used by VocabParallelEmbedding output
    when sequence_parallel_enabled)."""
    return _split_along(x, axis_name, 0)


def _sp_scatter_fwd(x, axis_name):
    return _split_along(x, axis_name, 0), None


def _sp_scatter_bwd(axis_name, _, g):
    return (coll.all_gather(_ensure_varying(g, axis_name), axis_name, axis=0),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(x, axis_name=MODEL_AXIS, tensor_parallel_output_grad=True):
    """Reference: mappings.py:_GatherFromSequenceParallelRegion — all-gather
    sequence shards at TP-region entry. When the consumer is a TP linear
    (``tensor_parallel_output_grad=True``) the backward is a reduce-scatter;
    otherwise a plain split."""
    return coll.all_gather(x, axis_name, axis=0)


def _sp_gather_fwd(x, axis_name, tpog):
    return coll.all_gather(x, axis_name, axis=0), None


def _sp_gather_bwd(axis_name, tpog, _, g):
    g = _ensure_varying(g, axis_name)
    if tpog:
        return (coll.reduce_scatter(g, axis_name, axis=0),)
    return (_split_along(g, axis_name, 0),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis_name=MODEL_AXIS):
    """Reference: mappings.py:_ReduceScatterToSequenceParallelRegion — the
    TP-region exit under sequence parallelism (replaces the all-reduce)."""
    return coll.reduce_scatter(x, axis_name, axis=0)


def _sp_rs_fwd(x, axis_name):
    return coll.reduce_scatter(x, axis_name, axis=0), None


def _sp_rs_bwd(axis_name, _, g):
    return (coll.all_gather(_ensure_varying(g, axis_name), axis_name, axis=0),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
