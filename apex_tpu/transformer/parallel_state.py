"""Model-parallel state: the apex ``parallel_state`` API over one jax Mesh.

Reference: apex/transformer/parallel_state.py:~100-600 —
``initialize_model_parallel(tp, pp, vpp, pp_split_rank)`` builds NCCL process
groups (_TENSOR_MODEL_PARALLEL_GROUP, _PIPELINE_MODEL_PARALLEL_GROUP,
_DATA_PARALLEL_GROUP, _EMBEDDING_GROUP) with rank order tp-fastest, then pp,
then dp, plus rank/world-size/is-first/last-stage queries.

TPU design: one global ``jax.sharding.Mesh`` with axes
``('data', 'stage', 'context', 'model')`` replaces every process group; a
"group" IS a mesh axis name. World-size queries read the mesh shape on the
host. Rank queries come in two flavors:

- ``get_*_rank()`` — valid **inside** ``shard_map`` (returns a traced
  ``lax.axis_index``). This is where per-rank logic lives under SPMD.
- Host code that needs a static answer (e.g. parameter-shape math) should use
  the ``*_world_size`` getters, which are static.

``virtual_pipeline_model_parallel`` rank/world-size are process-local Python
state exactly as in the reference (set by the interleaved schedule loop).
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from jax.sharding import Mesh

from apex_tpu import mesh as mesh_lib
from apex_tpu.mesh import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS, STAGE_AXIS

# Virtual pipeline state (reference: parallel_state.py virtual pp globals).
_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK: Optional[int] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_PIPELINE_MODEL_PARALLEL_SPLIT_RANK: Optional[int] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    *,
    context_parallel_size_: int = 1,
    devices=None,
    dcn_data_parallel_size_: int = 1,
) -> Mesh:
    """Build and install the global mesh.

    Mirrors the reference signature (trailing underscores included). Returns
    the Mesh so callers can also use it directly with ``pjit``/``shard_map``.
    ``context_parallel_size_`` is a beyond-reference extension (ring
    attention); the reference has no context parallelism (SURVEY.md §2.4).
    ``dcn_data_parallel_size_`` requests hybrid ICI-inner/DCN-outer placement
    for multi-slice pods (see ``apex_tpu.mesh.build_mesh``).
    """
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    m = mesh_lib.build_mesh(
        tensor_model_parallel_size_,
        pipeline_model_parallel_size_,
        context_parallel_size_,
        devices=devices,
        dcn_data_parallel_size=dcn_data_parallel_size_,
    )
    mesh_lib.set_global_mesh(m)
    # reference sets the virtual rank to 0 whenever a virtual pp size is given
    # (parallel_state.py:initialize_model_parallel); also clears any rank
    # leaked from a previous initialization that skipped destroy
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = (
        0 if virtual_pipeline_model_parallel_size_ is not None else None)
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = virtual_pipeline_model_parallel_size_
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = pipeline_model_parallel_split_rank_
    return m


def model_parallel_is_initialized() -> bool:
    return mesh_lib.maybe_global_mesh() is not None


def destroy_model_parallel() -> None:
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    mesh_lib.set_global_mesh(None)
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = None


# --- "groups" = axis names ----------------------------------------------------

def get_tensor_model_parallel_group() -> str:
    return MODEL_AXIS


def get_pipeline_model_parallel_group() -> str:
    return STAGE_AXIS


def get_data_parallel_group() -> str:
    return DATA_AXIS


def get_context_parallel_group() -> str:
    return CONTEXT_AXIS


# --- world sizes (static, from mesh shape) -----------------------------------

def _axis_size(name: str) -> int:
    return mesh_lib.get_global_mesh().shape[name]


def get_tensor_model_parallel_world_size() -> int:
    return _axis_size(MODEL_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(STAGE_AXIS)


def get_data_parallel_world_size() -> int:
    return _axis_size(DATA_AXIS)


def get_context_parallel_world_size() -> int:
    return _axis_size(CONTEXT_AXIS)


def get_world_size() -> int:
    return mesh_lib.get_global_mesh().size


# --- ranks (traced; valid inside shard_map) ----------------------------------

def get_tensor_model_parallel_rank():
    return lax.axis_index(MODEL_AXIS)


def get_pipeline_model_parallel_rank():
    return lax.axis_index(STAGE_AXIS)


def get_data_parallel_rank():
    return lax.axis_index(DATA_AXIS)


def get_context_parallel_rank():
    return lax.axis_index(CONTEXT_AXIS)


def get_tensor_model_parallel_src_rank() -> int:
    """First rank of the TP group; with a named mesh the "source" is simply
    index 0 on the ``model`` axis (reference computes a global rank)."""
    return 0


# --- pipeline stage predicates -----------------------------------------------

def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Traced predicate (inside shard_map). Reference:
    parallel_state.py:is_pipeline_first_stage."""
    if not ignore_virtual:
        vr = get_virtual_pipeline_model_parallel_rank()
        if vr is not None and vr != 0:
            return False
    return lax.axis_index(STAGE_AXIS) == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        vr = get_virtual_pipeline_model_parallel_rank()
        vw = get_virtual_pipeline_model_parallel_world_size()
        if vr is not None and vw is not None and vr != (vw - 1):
            return False
    return lax.axis_index(STAGE_AXIS) == get_pipeline_model_parallel_world_size() - 1


# --- virtual pipeline bookkeeping (host-local ints, as in the reference) -----

def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = rank


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def set_virtual_pipeline_model_parallel_world_size(size: Optional[int]) -> None:
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = size


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def set_pipeline_model_parallel_split_rank(rank: Optional[int]) -> None:
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = rank


def get_mesh() -> Mesh:
    """TPU-native accessor: the mesh behind all of the above."""
    return mesh_lib.get_global_mesh()
