"""Utilities mirroring apex/transformer/utils.py."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Reference: apex/transformer/utils.py:divide."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(tensor, num_partitions: int):
    """Reference: apex/transformer/tensor_parallel/utils.py —
    split along the last dim into equal chunks (returns a tuple)."""
    last = tensor.shape[-1]
    chunk = divide(last, num_partitions)
    return tuple(
        lax.slice_in_dim(tensor, i * chunk, (i + 1) * chunk, axis=tensor.ndim - 1)
        for i in range(num_partitions)
    )


def split_tensor_into_1d_equal_chunks(tensor, axis_name: str = "model"):
    """Flatten and take this rank's 1/world chunk (inside shard_map).
    Reference: apex/transformer/utils.py:split_tensor_into_1d_equal_chunks."""
    flat = tensor.reshape(-1)
    world = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    chunk = flat.shape[0] // world
    return lax.dynamic_slice_in_dim(flat, rank * chunk, chunk)


def gather_split_1d_tensor(tensor, axis_name: str = "model"):
    """Inverse of the above via all-gather.
    Reference: apex/transformer/utils.py:gather_split_1d_tensor."""
    return lax.all_gather(tensor, axis_name, axis=0, tiled=True)
