"""Mixture-of-experts MLP with expert parallelism over a mesh axis.

Beyond-reference extension: apex has no MoE (SURVEY.md §2.4 lists EP as
reference-absent by design), but expert parallelism completes the framework's
parallelism surface (dp/tp/pp/sp/cp/ep). The design is the canonical TPU
formulation (GShard/Switch lineage):

- **Dispatch/combine are einsums over static one-hot masks**, not gathers:
  ``dispatch (T,E,C)`` one-hot in its capacity slot, ``xd = einsum('tec,td->
  ecd')``. Static shapes + MXU-friendly contractions; XLA fuses the mask
  construction into the einsum operands.
- **Expert parallelism = ``lax.all_to_all`` over a named mesh axis** inside
  ``shard_map`` (the same idiom as the TP layers in
  tensor_parallel/layers.py): each rank routes its local tokens to all E
  experts' capacity slots, a tiled all_to_all regroups slots by expert owner,
  local experts run as one batched einsum over (E_local, ep*C, d), and the
  inverse all_to_all brings results home for the combine einsum. On hardware
  the all_to_all rides ICI; under GSPMD jit the same module works with the
  axis unbound and experts replicated/sharded by annotation.
- **Capacity-based token dropping** with per-(token,slot) priority by position
  (GShard's position-in-expert cumsum). ``capacity_factor`` >= num_experts/k
  guarantees droplessness (used by the parity tests).

Default expert axis is ``data`` — the Megatron convention of carving expert
parallelism out of the data-parallel group, so ep needs no fifth mesh axis and
composes with TP (experts can themselves be tensor-parallel over ``model``).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.mesh import DATA_AXIS
from apex_tpu.transformer.moe.router import (TopKRouter, load_balancing_loss,
                                             router_z_loss)
from apex_tpu.transformer.tensor_parallel.mappings import axis_is_bound
from apex_tpu.transformer.utils import divide


@flax.struct.dataclass
class MoEAuxLosses:
    """Raw (un-scaled) auxiliary losses plus the pre-scaled total.

    A registered pytree (flax.struct) so it can cross jit/shard_map/grad-aux
    boundaries — the normal pattern is returning it from a jitted step for
    logging."""

    load_balance: jnp.ndarray
    z_loss: jnp.ndarray
    total: jnp.ndarray  # aux_loss_coeff * load_balance + z_loss_coeff * z_loss


def compute_dispatch_combine(probs: jnp.ndarray, k: int, capacity: int,
                             normalize_gates: bool = False):
    """Build (combine, dispatch, expert_mask) from router probabilities.

    ``probs``: (T, E) fp32. Returns ``combine`` (T, E, C) fp32 gate weights,
    ``dispatch`` (T, E, C) 0/1, ``expert_mask`` (T, E) 0/1 (pre-drop top-k
    assignment, for the balance loss). Slot priority is GShard's: choice rank
    first (all tokens' 1st choices beat any 2nd choice), token order second.

    ``normalize_gates`` renormalizes each token's top-k gates to sum to 1
    BEFORE capacity dropping (Mixtral semantics) — a dropped slot's gate mass
    is lost, like GShard, rather than silently inflating the surviving slots.
    """
    t, num_experts = probs.shape
    top_vals, top_idx = lax.top_k(probs, k)          # (T, k)
    if normalize_gates:
        top_vals = top_vals / jnp.maximum(
            jnp.sum(top_vals, axis=-1, keepdims=True), 1e-9)
    combine = jnp.zeros((t, num_experts, capacity), jnp.float32)
    dispatch = jnp.zeros((t, num_experts, capacity), jnp.float32)
    expert_mask = jnp.zeros((t, num_experts), jnp.float32)
    counts = jnp.zeros((num_experts,), jnp.int32)    # slots already claimed
    for i in range(k):                               # k is tiny and static
        mask_i = jax.nn.one_hot(top_idx[:, i], num_experts,
                                dtype=jnp.int32)     # (T, E)
        pos_i = jnp.cumsum(mask_i, axis=0) - 1 + counts[None, :]
        keep = (pos_i < capacity) & (mask_i > 0)     # (T, E)
        slot = jax.nn.one_hot(jnp.where(keep, pos_i, capacity), capacity,
                              dtype=jnp.float32)     # (T, E, C); drop -> 0s
        dispatch_i = slot * keep[..., None]
        dispatch = dispatch + dispatch_i
        combine = combine + dispatch_i * top_vals[:, i][:, None, None]
        expert_mask = expert_mask + mask_i
        counts = counts + jnp.sum(mask_i, axis=0)
    return combine, dispatch, jnp.minimum(expert_mask, 1.0)


def moe_layer_selected(cfg, layer_idx: int) -> bool:
    """Shared routing predicate for model configs carrying the MoE knobs
    (GPTConfig / LlamaConfig): block ``layer_idx`` is routed iff
    ``num_experts > 0`` and the index lands on the ``moe_layer_freq``
    stride (last block of each stride group, Switch convention)."""
    return (cfg.num_experts > 0
            and layer_idx % cfg.moe_layer_freq == cfg.moe_layer_freq - 1)


def make_moe_mlp(cfg, hidden_size: int, ffn_hidden_size: int,
                 activation: str, name: str = "moe_mlp") -> "MoEMLP":
    """Build the routed MLP for a decoder block from a model config's MoE
    knobs — ONE place owns the expert-parallel opt-in wiring (use_ep /
    expert_world_size / axis_name) for every model family."""
    from apex_tpu.transformer.tensor_parallel.mappings import axis_is_bound

    use_ep = cfg.expert_parallel and axis_is_bound(DATA_AXIS)
    return MoEMLP(
        hidden_size=hidden_size, ffn_hidden_size=ffn_hidden_size,
        num_experts=cfg.num_experts, k=cfg.moe_k,
        capacity_factor=cfg.moe_capacity_factor,
        aux_loss_coeff=cfg.moe_aux_loss_coeff,
        z_loss_coeff=cfg.moe_z_loss_coeff,
        activation=activation,
        params_dtype=cfg.param_dtype,
        expert_world_size=None if use_ep else 1,
        axis_name=DATA_AXIS if use_ep else "unbound_ep",
        name=name)


def collect_sown_aux(intermediates) -> jnp.ndarray:
    """Sum ONLY the ``moe_aux`` entries of a flax ``intermediates``
    collection (other sown diagnostics must not leak into the loss) —
    shared by the GPT and Llama loss tails."""
    total = jnp.zeros((), jnp.float32)

    def _collect(path, leaf):
        nonlocal total
        if any(str(getattr(k, "key", k)) == "moe_aux" for k in path):
            total = total + leaf
        return leaf

    jax.tree_util.tree_map_with_path(_collect, intermediates)
    return total


def slice_expert_shards(params, e_local: int, axis_name: str = DATA_AXIS,
                        tensor_world_size: int = 1):
    """Per-rank view of a FULL-expert-stack param tree: inside shard_map,
    dynamic-slice every MoE expert leaf (``moe_mlp``'s w1/b1/w2/b2) down to
    this rank's ``e_local`` experts; all other leaves pass through. The
    slice's transpose scatters grads back to the right expert rows, so a
    host-side full tree + ``pmean`` over ``axis_name`` is an exact
    data+expert-parallel step (see examples/moe/train_moe_ep.py).

    Expert-TP (``MoEMLP.tensor_world_size > 1``) is NOT composed here:
    slicing the FFN dim needs the activation layout ([gate|up] fused for
    swiglu) — pass ``tensor_world_size`` so the mismatch fails loud."""
    if tensor_world_size != 1:
        raise NotImplementedError(
            "slice_expert_shards emits full-FFN expert shards; expert "
            "tensor parallelism needs activation-aware FFN slicing (see "
            "tests/test_moe.py::test_expert_tensor_parallel_... for the "
            "manual layout)")

    def f(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        if "moe_mlp" in names and names[-1] in ("w1", "b1", "w2", "b2"):
            r = lax.axis_index(axis_name)
            return lax.dynamic_slice_in_dim(leaf, r * e_local, e_local,
                                            axis=0)
        return leaf

    return jax.tree_util.tree_map_with_path(f, params)


class MoEMLP(nn.Module):
    """Top-k routed mixture-of-experts FFN (GELU two-layer experts).

    Parameters hold the PER-RANK expert shards (``num_experts /
    expert_world_size`` experts each), mirroring the per-shard convention of
    ``ColumnParallelLinear``. Run inside ``shard_map`` with ``axis_name``
    bound for real expert parallelism; with the axis unbound it degrades to a
    single-rank dense-dispatch MoE (and ``expert_world_size`` must be 1).

    Returns ``(y, MoEAuxLosses)`` — callers add ``aux.total`` to their loss.
    """

    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    k: int = 2
    capacity_factor: float = 1.25
    normalize_gates: bool = True          # Mixtral-style renormalize top-k
    aux_loss_coeff: float = 1e-2
    z_loss_coeff: float = 0.0
    activation: str = "gelu"              # "gelu" | "swiglu" (Mixtral experts)
    params_dtype: jnp.dtype = jnp.float32
    expert_world_size: Optional[int] = None   # default: axis size if bound
    axis_name: str = DATA_AXIS
    # expert TENSOR parallelism (opt-in — default keeps experts replicated
    # across the model axis, the GPT/Llama block behavior): each (ep, tp)
    # rank holds (E/ep) experts with their FFN dim split tp ways; the w2
    # partial sums psum over ``tensor_parallel_axis`` (RowParallel
    # convention, bias added after the reduction)
    tensor_world_size: int = 1
    tensor_parallel_axis: str = "model"

    def _world(self) -> int:
        if self.expert_world_size is not None:
            return self.expert_world_size
        if axis_is_bound(self.axis_name):
            return lax.axis_size(self.axis_name)
        return 1

    @nn.compact
    def __call__(self, x: jnp.ndarray):
        orig_shape = x.shape
        d = self.hidden_size
        assert orig_shape[-1] == d, (orig_shape, d)
        x = x.reshape(-1, d)                       # (T_local, d)
        t = x.shape[0]
        ep = self._world()
        if ep > 1 and not axis_is_bound(self.axis_name):
            raise RuntimeError(
                f"expert_world_size={ep} but axis '{self.axis_name}' is not "
                "bound — run inside shard_map (same contract as the TP "
                "layers) or set expert_world_size=1")
        if ep > 1 and ep != lax.axis_size(self.axis_name):
            raise RuntimeError(
                f"expert_world_size={ep} != size of bound axis "
                f"'{self.axis_name}' ({lax.axis_size(self.axis_name)})")
        e_local = divide(self.num_experts, ep)
        tw = self.tensor_world_size
        if tw > 1 and not axis_is_bound(self.tensor_parallel_axis):
            raise RuntimeError(
                f"tensor_world_size={tw} but axis "
                f"'{self.tensor_parallel_axis}' is not bound")
        if tw > 1 and tw != lax.axis_size(self.tensor_parallel_axis):
            # a mismatch would psum the wrong number of partials --
            # silently wrong output, not a shape error
            raise RuntimeError(
                f"tensor_world_size={tw} != size of bound axis "
                f"'{self.tensor_parallel_axis}' "
                f"({lax.axis_size(self.tensor_parallel_axis)})")
        ff_local = divide(self.ffn_hidden_size, tw)
        dt = resolve_compute_dtype(x.dtype)

        probs, logits = TopKRouter(self.num_experts,
                                   params_dtype=self.params_dtype,
                                   name="router")(x)
        capacity = max(int(self.capacity_factor * self.k * t
                           / self.num_experts), 1)
        combine, dispatch, expert_mask = compute_dispatch_combine(
            probs, self.k, capacity, normalize_gates=self.normalize_gates)

        # --- dispatch: (T,E,C) x (T,d) -> (E,C,d), bf16 on the MXU
        xd = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x.astype(dt))

        bound = axis_is_bound(self.axis_name) and ep > 1
        if bound:
            # regroup capacity slots under their owning rank's experts:
            # (E, C, d) --all_to_all--> (E_local, ep*C, d)
            xd = lax.all_to_all(xd, self.axis_name, split_axis=0,
                                concat_axis=1, tiled=True)

        # --- local experts: one batched einsum over the expert dim
        init = nn.initializers.lecun_normal()

        def shard_init(base, fold_tensor=True):
            def f(key, shape, dtype):
                if axis_is_bound(self.axis_name):
                    key = jax.random.fold_in(
                        key, lax.axis_index(self.axis_name))
                if fold_tensor and tw > 1:
                    key = jax.random.fold_in(
                        key, lax.axis_index(self.tensor_parallel_axis))
                return base(key, shape, dtype)
            return f

        if self.activation not in ("gelu", "swiglu"):
            raise ValueError(f"unsupported expert activation "
                             f"{self.activation!r} (gelu | swiglu)")
        swiglu = self.activation == "swiglu"
        # swiglu experts fuse gate+up in w1 (same [gate|up] layout as the
        # Llama block's gate_up_proj) and are BIAS-FREE like Mixtral's
        # w1/w3/w2 — no extra tensors vs the upstream expert format.
        # Under expert-TP the local layout is [gate_r | up_r].
        w1_cols = (2 if swiglu else 1) * ff_local
        w1 = self.param("w1", shard_init(init),
                        (e_local, d, w1_cols), self.params_dtype)
        w2 = self.param("w2", shard_init(init),
                        (e_local, ff_local, d), self.params_dtype)
        h = jnp.einsum("ecd,edf->ecf", xd, w1.astype(dt))
        if swiglu:
            gate, up = jnp.split(h, 2, axis=-1)
            h = jax.nn.silu(gate) * up
        else:
            b1 = self.param("b1", shard_init(nn.initializers.zeros),
                            (e_local, w1_cols), self.params_dtype)
            h = nn.gelu(h + b1[:, None].astype(dt))
        yd = jnp.einsum("ecf,efd->ecd", h, w2.astype(dt))
        if tw > 1:
            # RowParallel reduction over the experts' split FFN dim
            yd = lax.psum(yd, self.tensor_parallel_axis)
        if not swiglu:
            # b2 is REPLICATED across the tensor axis (added once to the
            # post-psum replicated output) — fold only the expert axis so
            # tp replicas stay identical
            b2 = self.param("b2",
                            shard_init(nn.initializers.zeros,
                                       fold_tensor=False),
                            (e_local, d), self.params_dtype)
            yd = yd + b2[:, None].astype(dt)

        if bound:
            # inverse: (E_local, ep*C, d) -> (E, C, d) back on token owners
            yd = lax.all_to_all(yd, self.axis_name, split_axis=1,
                                concat_axis=0, tiled=True)

        # --- combine: gates cast to the compute dtype (bf16 under bf16
        # models — the MXU truncates f32 operands to bf16 at default matmul
        # precision anyway, so keeping them f32 would only buy an HBM-sized
        # upcast of yd, not precision); the ACCUMULATION is fp32
        y = jnp.einsum("tec,ecd->td", combine.astype(dt), yd,
                       preferred_element_type=jnp.float32)
        y = y.astype(x.dtype).reshape(orig_shape)

        lb = load_balancing_loss(probs, expert_mask)
        zl = router_z_loss(logits)
        if bound:
            lb = lax.pmean(lb, self.axis_name)
            zl = lax.pmean(zl, self.axis_name)
        aux = MoEAuxLosses(
            load_balance=lb, z_loss=zl,
            total=self.aux_loss_coeff * lb + self.z_loss_coeff * zl)
        return y, aux

    forward = __call__
