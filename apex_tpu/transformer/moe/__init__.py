"""Mixture-of-experts with expert parallelism (beyond reference).

The reference apex has no MoE; EP completes this framework's parallelism
surface (SURVEY.md §2.4 footnote). See layer.py for the TPU-first design.
"""

from apex_tpu.transformer.moe.layer import (MoEAuxLosses, MoEMLP,
                                            collect_sown_aux,
                                            compute_dispatch_combine,
                                            make_moe_mlp,
                                            moe_layer_selected,
                                            slice_expert_shards)
from apex_tpu.transformer.moe.router import (TopKRouter, load_balancing_loss,
                                             router_z_loss)

__all__ = [
    "MoEAuxLosses", "MoEMLP", "collect_sown_aux",
    "compute_dispatch_combine", "make_moe_mlp", "moe_layer_selected",
    "slice_expert_shards",
    "TopKRouter", "load_balancing_loss", "router_z_loss",
]
