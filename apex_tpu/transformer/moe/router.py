"""Top-k expert router + auxiliary balancing losses.

Beyond-reference extension (SURVEY.md §2.4 names EP as reference-absent):
the reference apex has no mixture-of-experts machinery, but the driver-facing
parallelism surface (dp/tp/pp/sp/ep) treats expert parallelism as first-class,
so the router/dispatch stack lives here under ``apex_tpu.transformer`` next to
the other Megatron-shaped pieces.

Design notes (TPU-first):
- Routing math is fp32 regardless of the compute dtype: top-k gating and the
  softmax over experts are tiny (T x E) but numerically load-bearing — bf16
  logits visibly perturb expert choice near ties.
- Everything is static-shape: top_k, one_hot and cumsum over a fixed expert
  count; no data-dependent shapes, so the whole router traces into one XLA
  program (no host round-trips per step).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def router_z_loss(logits: jnp.ndarray) -> jnp.ndarray:
    """Mean squared logsumexp of the router logits (ST-MoE z-loss).

    Penalizes drifting logit scale, which otherwise pushes the fp32 softmax
    toward saturation. ``logits``: (tokens, experts) fp32.
    """
    lse = jax.nn.logsumexp(logits, axis=-1)
    return jnp.mean(lse * lse)


def load_balancing_loss(probs: jnp.ndarray,
                        expert_mask: jnp.ndarray) -> jnp.ndarray:
    """Switch-Transformer load-balance loss: ``E * sum_e f_e * P_e``.

    ``probs``: (tokens, E) fp32 router probabilities.
    ``expert_mask``: (tokens, E) 0/1 — token t routed to expert e (any of its
    top-k slots). ``f_e`` is the fraction of routed (token, slot) assignments
    landing on e; ``P_e`` the mean router probability for e. Minimized (=1.0)
    at a uniform assignment; differentiable through ``P_e`` only, like the
    original.
    """
    num_experts = probs.shape[-1]
    f = jnp.mean(expert_mask.astype(jnp.float32), axis=0)
    f = f / jnp.maximum(jnp.sum(f), 1e-9)          # normalize over k slots
    p = jnp.mean(probs, axis=0)
    return num_experts * jnp.sum(lax.stop_gradient(f) * p)


class TopKRouter(nn.Module):
    """Linear gate -> fp32 softmax over experts.

    Returns ``(probs, logits)`` both fp32, shape (tokens, num_experts). The
    dispatch/combine construction lives in
    :mod:`apex_tpu.transformer.moe.layer` so the router stays reusable for
    dropless variants.
    """

    num_experts: int
    params_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.num_experts, x.shape[-1]), self.params_dtype)
        # router GEMM in fp32: (T, d) x (d, E) is negligible FLOPs but the
        # probabilities steer everything downstream
        logits = x.astype(jnp.float32) @ w.astype(jnp.float32).T
        probs = nn.softmax(logits, axis=-1)
        return probs, logits
