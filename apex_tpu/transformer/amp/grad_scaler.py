"""Model-parallel-aware gradient scaler.

Reference: apex/transformer/amp/grad_scaler.py — a ``torch.cuda.amp.GradScaler``
subclass whose ``_unscale_grads_`` all-reduces (MAX) the found-inf flag over
the model-parallel group, so TP/PP ranks agree on whether to skip a step.

TPU restatement: the same agreement is ``lax.pmax`` of the found-inf scalar
over every bound model-parallel mesh axis. The fused optimizers apply it
automatically inside their jitted step
(apex_tpu/optimizers/common.py:_agree_found_inf_across_model_parallel), so
this class exists as (a) the API-parity surface, and (b) the functional
helper for hand-rolled training loops.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS, STAGE_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import axis_is_bound


def agree_found_inf(found_inf,
                    axes=(MODEL_AXIS, STAGE_AXIS, CONTEXT_AXIS)):
    """pmax ``found_inf`` over every bound axis in ``axes`` (the reference's
    torch.distributed.all_reduce(MAX, group=model_parallel_group))."""
    for ax in axes:
        if axis_is_bound(ax):
            found_inf = lax.pmax(found_inf, ax)
    return found_inf


class GradScaler(LossScaler):
    """Drop-in for apex.transformer.amp.GradScaler.

    Same ctor surface as torch.cuda.amp.GradScaler; ``update(state,
    found_inf)`` agrees the flag across model-parallel axes first.
    """

    def __init__(self, init_scale: float = 2.0 ** 16, growth_factor: float = 2.0,
                 backoff_factor: float = 0.5, growth_interval: int = 2000,
                 enabled: bool = True, hysteresis: int = 1):
        if growth_factor != 1.0 / backoff_factor:
            # the flat LossScaler uses one factor both ways; the reference's
            # defaults (2.0, 0.5) satisfy this
            raise NotImplementedError(
                "GradScaler requires growth_factor == 1/backoff_factor")
        super().__init__(loss_scale="dynamic" if enabled else 1.0,
                         init_scale=init_scale, scale_factor=growth_factor,
                         scale_window=growth_interval, hysteresis=hysteresis)

    def update(self, state: ScalerState, found_inf) -> ScalerState:
        return super().update(state, agree_found_inf(found_inf))
