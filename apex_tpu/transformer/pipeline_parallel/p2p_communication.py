"""Stage-to-stage activation/grad transfer.

Reference: apex/transformer/pipeline_parallel/p2p_communication.py:~50-400 —
``send_forward``/``recv_forward``/``send_backward``/``recv_backward`` and the
fused ``send_forward_recv_backward`` variants over
``torch.distributed.batch_isend_irecv`` / ``ring_exchange``.

On TPU every transfer is ``lax.ppermute`` on the ``stage`` axis (XLA
collective-permute, riding ICI between neighbor chips). "send" and "recv"
collapse into one collective: what rank s sends forward IS what rank s+1
receives, so each reference send/recv pair maps to a single shift. The fused
send/recv combos are two independent shifts that XLA schedules concurrently.
All functions must run inside shard_map with the stage axis bound.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from apex_tpu.mesh import STAGE_AXIS


def _shift(x, axis_name: str, offset: int, wrap: bool):
    n = lax.axis_size(axis_name)
    if wrap:
        perm = [(i, (i + offset) % n) for i in range(n)]
    else:
        perm = [(i, i + offset) for i in range(n) if 0 <= i + offset < n]
    # pytree payloads supported (e.g. (activation, moe_aux) tuples): one
    # ppermute per leaf, scheduled concurrently by XLA
    return jax.tree.map(lambda t: lax.ppermute(t, axis_name, perm), x)


def send_forward_recv_forward(x, axis_name: str = STAGE_AXIS, wrap: bool = False):
    """Shift activations one stage downstream: rank s's value arrives at
    s+1 (reference: send_forward on s + recv_forward on s+1). Ranks with no
    upstream receive zeros (the reference's recv into a fresh buffer)."""
    return _shift(x, axis_name, +1, wrap)


def send_backward_recv_backward(g, axis_name: str = STAGE_AXIS, wrap: bool = False):
    """Shift gradients one stage upstream (reference: send_backward +
    recv_backward)."""
    return _shift(g, axis_name, -1, wrap)


# reference-named aliases: in SPMD the send and the recv are the same op
send_forward = send_forward_recv_forward
recv_forward = send_forward_recv_forward
send_backward = send_backward_recv_backward
recv_backward = send_backward_recv_backward


def send_forward_recv_backward(x, g, axis_name: str = STAGE_AXIS):
    """Fused steady-state 1F1B exchange (reference:
    send_forward_recv_backward): activations go downstream while grads come
    back upstream; XLA overlaps the two permutes."""
    return (_shift(x, axis_name, +1, False), _shift(g, axis_name, -1, False))


def send_backward_recv_forward(g, x, axis_name: str = STAGE_AXIS):
    """Fused counterpart of the above."""
    return (_shift(g, axis_name, -1, False), _shift(x, axis_name, +1, False))
