"""Pipeline parallelism over the ``stage`` mesh axis.

Reference: apex/transformer/pipeline_parallel/ — schedules/__init__.py
(get_forward_backward_func dispatch), fwd_bwd_no_pipelining.py,
fwd_bwd_pipelining_without_interleaving.py (1F1B), p2p_communication.py
(NCCL batch_isend_irecv), microbatches.py (num-microbatch calculators).

TPU design (SURVEY.md §3.5): the microbatch loop is a ``lax.scan`` INSIDE
``shard_map``; activations/grads move between adjacent stages with
``ppermute`` (XLA collective-permute over ICI) instead of NCCL P2P; the
backward schedule comes from autodiff of the scanned forward (scan transpose
= reverse-scan, ppermute transpose = inverse ppermute), so warmup/steady/
cooldown and per-microbatch grad accumulation need no hand bookkeeping.
``deallocate_output_tensor`` has no analog (XLA liveness); memory is managed
with ``jax.checkpoint`` on the stage body.
"""

from apex_tpu.transformer.pipeline_parallel.microbatches import (  # noqa: F401
    ConstantNumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatchesCalculator,
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    pipeline_apply,
    pipeline_apply_interleaved,
)
from apex_tpu.transformer.pipeline_parallel import p2p_communication  # noqa: F401
