"""Pipeline schedules: scan-over-microbatches + ppermute.

Reference: apex/transformer/pipeline_parallel/schedules/ —
``get_forward_backward_func`` dispatching to ``forward_backward_no_pipelining``
or the 1F1B schedules (fwd_bwd_pipelining_without_interleaving.py: warmup
forwards -> steady 1F1B -> cooldown backwards, with hand-rolled P2P and
``deallocate_output_tensor``).

TPU restatement: the whole schedule is ONE differentiable program. Forward is
``lax.scan`` over T = M + S - 1 ticks inside ``shard_map``; at each tick every
stage runs its block on the activation that arrived, then the activations
shift one stage downstream via ppermute. Autodiff of that program IS the
pipelined backward: scan transposes to a reverse-time scan and ppermute to
its inverse permute, so gradient ticks flow upstream exactly like the
reference's cooldown/steady backward phases — no explicit warmup/steady/
cooldown bookkeeping, and per-microbatch grad accumulation falls out of the
scan transpose. Activation memory is bounded with ``jax.checkpoint`` around
the stage body (the reference's deallocate_output_tensor + recompute).

The stage function signature is functional (params explicit), so the
reference's ``forward_step_func(batch, model) -> (output, loss_func)``
callback becomes ``stage_fn(stage_params, x) -> y`` plus a terminal
``loss_fn(y, microbatch_aux) -> scalar``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import STAGE_AXIS
from apex_tpu.transformer.log_util import get_transformer_logger
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
from apex_tpu.transformer.tensor_parallel.mappings import (
    axis_is_bound,
    reduce_from_tensor_model_parallel_region as _allreduce,
)


def _index_mb(microbatches, t, m):
    """Pytree-aware microbatch pickup (clamped)."""
    idx = jnp.clip(t, 0, m - 1)
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, idx, 0, keepdims=False),
        microbatches)


def _mb_count(microbatches) -> int:
    return jax.tree.leaves(microbatches)[0].shape[0]


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str = STAGE_AXIS,
                   checkpoint_stage: bool = True,
                   first_fn: Optional[Callable] = None):
    """Run microbatches through the S-stage pipeline; returns last-stage
    outputs per microbatch.

    Args:
      stage_fn: ``(stage_params, x) -> y`` — ONE stage's computation; every
        stage must map the same activation shape to itself (the reference's
        fixed ``tensor_shape`` contract in p2p_communication).
      stage_params: THIS stage's parameter pytree (per-device, varying over
        ``axis_name``).
      microbatches: ``[M, ...]`` pytree of microbatch inputs (used by stage 0).
      checkpoint_stage: recompute the stage body in backward
        (deallocate_output_tensor analog).
      first_fn: optional ``(stage_params, mb) -> x`` transforming the raw
        microbatch into the stage-0 activation (e.g. a token embedding —
        Megatron's preprocess on the first stage). When None the microbatch
        must already have the activation shape.

    Returns ``[M, ...]`` outputs, valid on the LAST stage (other stages hold
    in-flight garbage, as with the reference where only the last stage sees
    outputs).
    """
    s = lax.axis_index(axis_name)
    n_stages = lax.axis_size(axis_name)
    m = _mb_count(microbatches)
    t_total = m + n_stages - 1

    body = stage_fn
    if checkpoint_stage:
        body = jax.checkpoint(stage_fn)
    entry = first_fn if first_fn is not None else (lambda p, mb: mb)

    def tick(buf, t):
        # stage 0 picks up microbatch t (clamped; beyond M it computes
        # garbage that never reaches a valid output slot)
        x0 = entry(stage_params, _index_mb(microbatches, t, m))
        x = jax.tree.map(
            lambda a, b: jnp.where(s == 0, a.astype(b.dtype), b), x0, buf)
        y = body(stage_params, x)
        return p2p.send_forward_recv_forward(y, axis_name), y

    # activation shape probe: traced (so collectives see the bound axes —
    # jax.eval_shape would drop the shard_map axis env) but DCE'd, since only
    # its static shape is used. Stages map the activation STRUCTURE to
    # itself (the reference's fixed tensor_shape contract); the payload may
    # be any pytree (e.g. (activation, moe_aux)) — every leaf rides the
    # scan carry and the per-tick ppermute.
    x0_probe = entry(stage_params, _index_mb(microbatches, 0, m))
    buf0 = jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype), x0_probe)
    _, ys = lax.scan(tick, buf0, jnp.arange(t_total))
    # last stage emits microbatch mb at tick mb + (S-1)
    return jax.tree.map(lambda t: t[n_stages - 1:], ys)


def _jaxpr_has_ppermute(jaxpr) -> bool:
    from jax.extend import core as jex_core

    jaxpr_types = (jex_core.ClosedJaxpr, jex_core.Jaxpr)

    def as_jaxpr(v):
        return v.jaxpr if isinstance(v, jex_core.ClosedJaxpr) else v

    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "ppermute":
            return True
        for val in eqn.params.values():
            subs = []
            if isinstance(val, jaxpr_types):
                subs = [as_jaxpr(val)]
            elif isinstance(val, (tuple, list)):
                subs = [as_jaxpr(v) for v in val
                        if isinstance(v, jaxpr_types)]
            if any(_jaxpr_has_ppermute(s) for s in subs):
                return True
    return False


def _use_explicit_schedule(stage_fn, params_for_probe, first_fn, loss_fn,
                           loss_aux, loss_with_params, microbatches) -> bool:
    """Shared dispatch gate for both 1F1B schedules: does one full
    stage step (entry preprocess + stage body + loss head, forward AND
    backward) emit a collective-permute (ring attention, halo exchange)?

    ppermute lowers as a GLOBAL collective over every mesh device, so it
    cannot sit inside the explicit 1F1B's per-device dead-slot branches —
    devices whose slot is dead would never join the rendezvous (observed as
    an XLA CPU rendezvous abort; on real hardware, a hang). Such programs
    need the uniform autodiff schedule, which runs every stage every tick.
    Sub-axis collectives (psum/all_gather over ``model``/``context``
    subgroups) are fine in branches because every subgroup member shares
    the branch predicate.

    The probe traces grad-wrt-params of entry -> stage -> loss, so it
    covers first_fn/loss_fn (they too run inside branches) and custom_vjp
    rules whose ppermute lives only in the hand-written backward, while
    avoiding grads of integer activations. Detection failure routes to the
    SAFE autodiff schedule (a false "explicit" would deadlock; a false
    "autodiff" merely costs memory). Cost: one extra abstract trace per
    compilation — fwd_bwd only ever runs inside shard_map, so the probe
    evaluates on tracers, never on real data.
    """
    entry = first_fn if first_fn is not None else (lambda p, mb: mb)
    mb0 = _index_mb(microbatches, 0, _mb_count(microbatches))
    aux0 = (_index_mb(loss_aux, 0, _mb_count(microbatches))
            if loss_aux is not None else None)
    head_loss = _make_head_loss(loss_fn, loss_with_params,
                                loss_aux is not None)

    def full_step(p):
        y = stage_fn(p, entry(p, mb0))
        return head_loss(p, y, aux0).astype(jnp.float32)

    try:
        jaxpr = jax.make_jaxpr(jax.grad(full_step))(params_for_probe)
    except Exception as e:  # noqa: BLE001 — fail toward the deadlock-free path
        # Correct failure direction (autodiff cannot deadlock), but a probe
        # that crashes for an unrelated stage bug must not downgrade memory
        # silently: the same error usually resurfaces when the schedule
        # itself traces, and if it doesn't, this is the only signal.
        get_transformer_logger(__name__).warning(
            "1F1B dispatch probe failed (%s: %s); falling back to the "
            "uniform autodiff schedule, which holds all M microbatch "
            "activations live (O(M) memory) instead of O(S).",
            type(e).__name__, e)
        return False
    return not _jaxpr_has_ppermute(jaxpr.jaxpr)


def _make_head_loss(loss_fn, loss_with_params, has_aux):
    """Uniform last-stage loss call over the (params?, aux?) signatures."""
    def head_loss(p, y, aux):
        if loss_with_params:
            return loss_fn(p, y, aux) if has_aux else loss_fn(p, y)
        return loss_fn(y, aux) if has_aux else loss_fn(y)
    return head_loss


def _make_bwd_branches(stage_fn, entry, head_loss, zero_dp, zero_dx,
                       act_dtype):
    """The four per-tick backward branches shared by both 1F1B schedules.

    Uniform signature ``(pb, x_saved, dy, mb_raw, aux) -> (dp, dx, loss)``
    where ``pb`` is the params the slot differentiates against (the full
    stage tree for the non-interleaved schedule; one chunk's tree for the
    interleaved one). Each branch re-linearizes the stage from its saved
    input (``jax.vjp`` on the spot — the reference's
    deallocate_output_tensor + recompute discipline).
    """
    def bwd_dead(pb, x_saved, dy, mb_raw, aux):
        return zero_dp, zero_dx, jnp.zeros((), jnp.float32)

    def bwd_first(pb, x_saved, dy, mb_raw, aux):
        # the first (virtual) stage recomputes through the embedding/
        # preprocess so entry's param grads flow; its input cotangent has
        # nowhere to go
        y, vjp = jax.vjp(lambda p: stage_fn(p, entry(p, mb_raw)), pb)
        (dp,) = vjp(dy.astype(y.dtype))
        return dp, zero_dx, jnp.zeros((), jnp.float32)

    def bwd_mid(pb, x_saved, dy, mb_raw, aux):
        y, vjp = jax.vjp(stage_fn, pb, x_saved)
        dp, dx = vjp(dy.astype(y.dtype))
        return dp, dx.astype(act_dtype), jnp.zeros((), jnp.float32)

    def bwd_last(pb, x_saved, dy, mb_raw, aux):
        # fwd + loss head + bwd in one vjp, seeded by the scalar loss
        def f(p, x):
            return head_loss(p, stage_fn(p, x), aux)
        loss, (dp, dx) = jax.value_and_grad(f, argnums=(0, 1))(pb, x_saved)
        return dp, dx.astype(act_dtype), loss.astype(jnp.float32)

    return (bwd_dead, bwd_first, bwd_mid, bwd_last)


def _fwd_bwd_1f1b(stage_fn: Callable, loss_fn: Callable, stage_params,
                  microbatches, loss_aux, axis_name: str,
                  first_fn: Optional[Callable], loss_with_params: bool):
    """True 1F1B: explicit interleaved forward/backward ticks, O(S) memory.

    Reference semantics (fwd_bwd_pipelining_without_interleaving.py: warmup
    fwds -> steady 1F1B -> cooldown bwds) restated as ONE lock-step scan:
    at global tick t, stage s forwards microbatch ``t - s`` and backwards
    microbatch ``t - 2(S-1) + s`` (each when in range). Activations shift
    downstream and cotangents upstream by one ppermute per tick, exactly the
    reference's send_forward / send_backward pairing; warmup and cooldown
    are simply the ticks where one of the two slots is out of range (the
    per-device ``lax.cond``/``switch`` skips the dead work, reproducing the
    1F1B bubble shape). Total ticks: M + 2(S-1) — the reference 1F1B's
    fill+steady+drain length.

    Memory: this function never differentiates through the tick scan —
    gradients are produced INSIDE each tick by re-linearizing the stage from
    a saved input (``jax.vjp`` on the spot = the reference's
    deallocate_output_tensor + recompute discipline). The only O(>1)
    activation state is a ``[2(S-1)+1, act]`` ring buffer of in-flight stage
    inputs in the scan carry — stage s holds at most 2(S-1)-2s+1 live
    entries (the lock-step analog of 1F1B's "stage s keeps S-s activation
    sets") — so peak activation memory is O(S), independent of M
    (tests/test_pipeline_memory.py asserts this against the XLA-reported
    peak at M=8 vs M=32).
    """
    s = lax.axis_index(axis_name)
    n_stages = lax.axis_size(axis_name)
    m_count = _mb_count(microbatches)
    entry = first_fn if first_fn is not None else (lambda p, mb: mb)
    ring_depth = 2 * (n_stages - 1) + 1
    t_total = m_count + 2 * (n_stages - 1)

    # traced-but-DCE'd activation shape probe (see pipeline_apply)
    x0_probe = entry(stage_params, _index_mb(microbatches, 0, m_count))
    act_shape, act_dtype = x0_probe.shape, x0_probe.dtype

    zero_dp = jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype), stage_params)
    zero_dx = jnp.zeros(act_shape, act_dtype)

    head_loss = _make_head_loss(loss_fn, loss_with_params,
                                loss_aux is not None)
    bwd_branches = _make_bwd_branches(stage_fn, entry, head_loss, zero_dp,
                                      zero_dx, act_dtype)

    def tick(carry, t):
        ring, buf_f, buf_b, gacc, lacc = carry

        # ---- forward slot: microbatch t - s ----
        m_f = t - s
        fwd_live = (m_f >= 0) & (m_f < m_count)
        mb_f = _index_mb(microbatches, m_f, m_count)
        # only stage 0 runs the embedding/preprocess (cond, not where: the
        # other S-1 stages must not pay the gather every tick)
        x_in = lax.cond(
            fwd_live & (s == 0),
            lambda: entry(stage_params, mb_f).astype(act_dtype),
            lambda: buf_f)
        slot_f = jnp.mod(m_f, ring_depth)
        ring = lax.cond(fwd_live,
                        lambda r: lax.dynamic_update_index_in_dim(
                            r, x_in, slot_f, 0),
                        lambda r: r, ring)
        # the last stage consumes its own forward inside bwd_last's vjp —
        # computing y there too would double its work
        y = lax.cond(fwd_live & (s < n_stages - 1),
                     lambda x: stage_fn(stage_params, x).astype(act_dtype),
                     lambda x: zero_dx, x_in)

        # ---- backward slot: microbatch t - 2(S-1) + s ----
        m_b = t - 2 * (n_stages - 1) + s
        bwd_live = (m_b >= 0) & (m_b < m_count)
        x_saved = lax.dynamic_index_in_dim(
            ring, jnp.mod(m_b, ring_depth), 0, keepdims=False)
        mb_b = _index_mb(microbatches, m_b, m_count)
        aux_b = (_index_mb(loss_aux, m_b, m_count)
                 if loss_aux is not None else jnp.zeros(()))
        branch = jnp.where(
            bwd_live,
            jnp.where(s == 0, 1, jnp.where(s == n_stages - 1, 3, 2)),
            0)
        dp, dx, lval = lax.switch(branch, bwd_branches,
                                  stage_params, x_saved, buf_b, mb_b, aux_b)
        gacc = jax.tree.map(jnp.add, gacc, dp)
        lacc = lacc + lval

        # ---- one downstream + one upstream shift per tick (reference:
        # send_forward / send_backward of the steady 1F1B loop) ----
        buf_f = p2p.send_forward_recv_forward(y, axis_name)
        buf_b = p2p.send_backward_recv_backward(dx, axis_name)
        return (ring, buf_f, buf_b, gacc, lacc), None

    carry0 = (
        jnp.zeros((ring_depth,) + tuple(act_shape), act_dtype),
        jnp.zeros(act_shape, act_dtype),
        jnp.zeros(act_shape, act_dtype),
        zero_dp,
        jnp.zeros((), jnp.float32),
    )
    (ring, buf_f, buf_b, gacc, lacc), _ = lax.scan(
        tick, carry0, jnp.arange(t_total))
    # only the last stage accumulated loss; psum broadcasts it (reference
    # reduces losses on the last stage — the broadcast spares callers a
    # special case, same contract as the autodiff formulation)
    mean_loss = lax.psum(lacc, axis_name) / m_count
    grads = jax.tree.map(lambda g: g / m_count, gacc)
    return mean_loss, grads


def forward_backward_pipelining_without_interleaving(
        stage_fn: Callable, loss_fn: Callable, stage_params, microbatches,
        loss_aux=None, forward_only: bool = False,
        axis_name: str = STAGE_AXIS, checkpoint_stage: bool = True,
        first_fn: Optional[Callable] = None,
        loss_with_params: bool = False,
        implementation: str = "1f1b"):
    """The 1F1B schedule (reference:
    fwd_bwd_pipelining_without_interleaving.py).

    ``loss_fn(y, aux_m) -> scalar`` runs on the last stage per microbatch
    (aux_m = ``loss_aux[m]``, e.g. labels); with ``loss_with_params=True``
    the signature is ``loss_fn(stage_params, y, aux_m)`` so a terminal head
    (final norm + tied LM head — Megatron's postprocess) differentiates too.
    ``first_fn(stage_params, mb)`` is the stage-0 preprocess (embedding).
    Returns ``(mean_loss, stage_grads)`` — each device gets grads of ITS
    stage's params, accumulated over microbatches, with the loss broadcast to
    every stage. With ``forward_only=True`` returns ``(mean_loss, None)``.

    ``implementation`` selects the gradient formulation:

    - ``"1f1b"`` (default): explicit interleaved fwd/bwd ticks with O(S)
      activation memory — the reference's warmup/steady/cooldown memory
      contract (see ``_fwd_bwd_1f1b``).
    - ``"autodiff"``: differentiate through the forward scan. Simpler
      program, but retains one stage-input residual per tick — O(M)
      activation memory; fine for small microbatch counts, kept as the
      cross-check oracle (tests assert the two implementations agree).

    ``checkpoint_stage`` applies to ``forward_only`` and the ``"autodiff"``
    path only: the 1F1B implementation ALWAYS rematerializes the stage from
    its saved input in backward (that recompute discipline is what bounds
    its memory — the reference's deallocate_output_tensor contract), so the
    flag has no effect there.
    """
    if not axis_is_bound(axis_name):
        raise RuntimeError(
            "pipeline schedules must run inside shard_map with the "
            f"'{axis_name}' axis bound (reference: requires "
            "parallel_state pipeline group)")
    n_stages = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)

    def mean_loss_of(params):
        outs = pipeline_apply(stage_fn, params, microbatches,
                              axis_name=axis_name,
                              checkpoint_stage=checkpoint_stage,
                              first_fn=first_fn)
        lf = (functools.partial(loss_fn, params) if loss_with_params
              else loss_fn)
        if loss_aux is not None:
            per_mb = jax.vmap(lf)(outs, loss_aux)
        else:
            per_mb = jax.vmap(lf)(outs)
        local = jnp.where(s == n_stages - 1, per_mb.mean(), 0.0)
        # identity-backward all-reduce: every stage sees the loss, backward
        # seeds only the last stage's real output path
        return _allreduce(local, axis_name)

    if forward_only:
        return mean_loss_of(stage_params), None
    # the explicit 1F1B's ring buffer and zero-cotangent plumbing assume a
    # SINGLE-array activation; pytree payloads (e.g. MoE's
    # (activation, aux) tuples) route to the uniform autodiff schedule
    entry0 = first_fn if first_fn is not None else (lambda p, mb: mb)
    payload0 = entry0(stage_params,
                      _index_mb(microbatches, 0, _mb_count(microbatches)))
    single_array_payload = not isinstance(payload0, (tuple, list, dict))
    # pp=1 has no pipeline to interleave: the autodiff scan handles it (the
    # pre-round-3 behavior for direct callers on a size-1 stage axis).
    # Ring-attention/halo stages (they emit ppermute, a GLOBAL collective)
    # also route to autodiff — see _stage_issues_ppermute.
    if (implementation == "1f1b" and n_stages >= 2
            and single_array_payload
            and _use_explicit_schedule(stage_fn, stage_params, first_fn,
                                       loss_fn, loss_aux, loss_with_params,
                                       microbatches)):
        return _fwd_bwd_1f1b(stage_fn, loss_fn, stage_params,
                             microbatches, loss_aux, axis_name, first_fn,
                             loss_with_params)
    if implementation not in ("1f1b", "autodiff"):
        raise ValueError(f"unknown implementation {implementation!r}")
    loss, grads = jax.value_and_grad(mean_loss_of)(stage_params)
    return loss, grads


def pipeline_apply_interleaved(stage_fn: Callable, chunk_params, microbatches,
                               axis_name: str = STAGE_AXIS,
                               checkpoint_stage: bool = True,
                               first_fn: Optional[Callable] = None):
    """Interleaved (virtual-pipeline) forward: V model chunks per stage.

    ``chunk_params`` leaves carry a leading ``[V]`` axis — chunk v on stage s
    implements global virtual stage ``v*S + s`` (Megatron's round-robin
    chunk assignment in parallel_state.get_virtual_pipeline_model_parallel_
    rank). Each tick every device advances ALL V of its chunks one step and
    the activations shift one stage down the ring; a chunk-(V-1)->(0) wrap
    on stage 0 rolls the chunk slot (the reference's cross-chunk handoff in
    fwd_bwd_pipelining_with_interleaving.py). An activation therefore
    traverses the V*S virtual stages in V*S ticks; outputs emerge on the
    LAST stage from chunk V-1.

    Cost-model note: this all-chunks-per-tick forward has fill/drain
    fraction (V*S-1)/(M+V*S-1) — larger than non-interleaved. It remains
    the forward_only path and the autodiff-gradient oracle; the schedule
    that actually delivers the reference's VPP bubble reduction is
    ``_fwd_bwd_interleaved_1f1b`` (one chunk-fwd + one chunk-bwd per tick),
    which ``forward_backward_pipelining_with_interleaving`` now uses by
    default when M % S == 0.
    """
    s = lax.axis_index(axis_name)
    n_stages = lax.axis_size(axis_name)
    v_chunks = jax.tree.leaves(chunk_params)[0].shape[0]
    m = _mb_count(microbatches)
    t_total = m + v_chunks * n_stages - 1

    body = stage_fn
    if checkpoint_stage:
        body = jax.checkpoint(stage_fn)
    chunk0 = jax.tree.map(lambda t: t[0], chunk_params)
    entry = first_fn if first_fn is not None else (lambda p, mb: mb)

    def tick(bufs, t):
        # stage 0 chunk 0 picks up microbatch t
        x0 = entry(chunk0, _index_mb(microbatches, t, m))
        xs = jax.tree.map(
            lambda b: b.at[0].set(
                jnp.where(s == 0, x0.astype(b.dtype), b[0])), bufs)

        def chunk_step(_, pv_xv):
            pv, xv = pv_xv
            return None, body(pv, xv)

        _, ys = lax.scan(chunk_step, None, (chunk_params, xs))
        # every chunk slot shifts one stage down the ring (wrap); on stage 0
        # the wrapped value belongs to the NEXT chunk -> roll the chunk axis
        permuted = p2p.send_forward_recv_forward(ys, axis_name, wrap=True)
        rolled = jax.tree.map(lambda a: jnp.roll(a, 1, axis=0), permuted)
        new_bufs = jax.tree.map(
            lambda r, p: jnp.where(s == 0, r, p), rolled, permuted)
        return new_bufs, jax.tree.map(lambda a: a[v_chunks - 1], ys)

    # traced-but-DCE'd shape probe (see pipeline_apply)
    x0_probe = entry(chunk0, _index_mb(microbatches, 0, m))
    bufs0 = jnp.zeros((v_chunks,) + tuple(x0_probe.shape), x0_probe.dtype)
    _, ys = lax.scan(tick, bufs0, jnp.arange(t_total))
    # microbatch mb exits chunk V-1 of the last stage at tick mb + V*S - 1
    return ys[v_chunks * n_stages - 1:]


def _fwd_bwd_interleaved_1f1b(stage_fn, loss_fn, chunk_params, microbatches,
                              loss_aux, axis_name, first_fn,
                              loss_with_params):
    """Lock-step interleaved 1F1B: V chunks per stage, ONE chunk-forward and
    ONE chunk-backward per device per tick — the genuine VPP bubble
    reduction (reference: fwd_bwd_pipelining_with_interleaving.py).

    Virtual stage ``vs = v*S + s`` lives on device s. Forward of microbatch
    m through chunk v occupies per-device slot ``i = g*V*S + v*S + p``
    (m = g*S + p, requiring M % S == 0 — the reference's interleaving
    divisibility constraint) at tick ``i + s``; its backward occupies slot
    ``j = g*V*S + (V-1-v)*S + p`` at tick ``V*S + j + (S-1-s)``. Both
    neighbor dependencies then line up exactly one tick apart — including
    the chunk-boundary wraps (device S-1 -> 0 forward, 0 -> S-1 backward),
    which is why both ppermutes run with ``wrap=True``.

    Bubble accounting (per-device tick cost = one chunk fwd + one chunk
    bwd = (tf+tb)/V of a full stage): total ticks = V*M + V*S + S - 1, so
    time = (M + S + (S-1)/V)*(tf+tb) — fill/drain overhead S + (S-1)/V
    full-stage units vs the non-interleaved schedule's 2(S-1), i.e. the
    bubble genuinely shrinks for S >= 4 and approaches half as V grows.
    The price is the reference's own trade: ~2V*S in-flight chunk inputs
    per device (ring buffer) vs 2S for non-interleaved.
    """
    s = lax.axis_index(axis_name)
    n_stages = lax.axis_size(axis_name)
    v_chunks = jax.tree.leaves(chunk_params)[0].shape[0]
    m_count = _mb_count(microbatches)
    entry = first_fn if first_fn is not None else (lambda p, mb: mb)
    vs_total = v_chunks * n_stages
    vm = v_chunks * m_count
    t_total = vs_total + vm + n_stages - 1
    ring_depth = 2 * vs_total + n_stages

    chunk0 = jax.tree.map(lambda t: t[0], chunk_params)
    x0_probe = entry(chunk0, _index_mb(microbatches, 0, m_count))
    act_shape, act_dtype = x0_probe.shape, x0_probe.dtype

    zero_dp = jax.tree.map(lambda p: jnp.zeros(p.shape[1:], p.dtype),
                           chunk_params)
    zero_dx = jnp.zeros(act_shape, act_dtype)

    def pick(v):
        return jax.tree.map(
            lambda t: lax.dynamic_index_in_dim(t, v, 0, keepdims=False),
            chunk_params)

    head_loss = _make_head_loss(loss_fn, loss_with_params,
                                loss_aux is not None)
    bwd_branches = _make_bwd_branches(stage_fn, entry, head_loss, zero_dp,
                                      zero_dx, act_dtype)

    def tick(carry, t):
        ring, buf_f, buf_b, gacc, lacc = carry

        # ---- forward: chunk slot i = t - s ----
        i = t - s
        fwd_live = (i >= 0) & (i < vm)
        i_c = jnp.clip(i, 0, vm - 1)
        v_f = (i_c // n_stages) % v_chunks
        m_f = (i_c // vs_total) * n_stages + i_c % n_stages
        pf = pick(v_f)
        mb_f = _index_mb(microbatches, m_f, m_count)
        x_in = lax.cond(
            fwd_live & (s == 0) & (v_f == 0),
            lambda: entry(pf, mb_f).astype(act_dtype),
            lambda: buf_f)
        slot_f = jnp.mod(i_c, ring_depth)
        ring = lax.cond(fwd_live,
                        lambda r: lax.dynamic_update_index_in_dim(
                            r, x_in, slot_f, 0),
                        lambda r: r, ring)
        # the last VIRTUAL stage's forward happens inside bwd_last's vjp
        y = lax.cond(
            fwd_live & ~((s == n_stages - 1) & (v_f == v_chunks - 1)),
            lambda: stage_fn(pf, x_in).astype(act_dtype),
            lambda: zero_dx)

        # ---- backward: chunk slot j = t - V*S - (S-1-s) ----
        j = t - vs_total - (n_stages - 1 - s)
        bwd_live = (j >= 0) & (j < vm)
        j_c = jnp.clip(j, 0, vm - 1)
        v_b = v_chunks - 1 - (j_c // n_stages) % v_chunks
        g_b = j_c // vs_total
        p_b = j_c % n_stages
        m_b = g_b * n_stages + p_b
        i_b = g_b * vs_total + v_b * n_stages + p_b   # fwd slot of (m_b, v_b)
        x_saved = lax.dynamic_index_in_dim(
            ring, jnp.mod(i_b, ring_depth), 0, keepdims=False)
        pb = pick(v_b)
        mb_b = _index_mb(microbatches, m_b, m_count)
        aux_b = (_index_mb(loss_aux, m_b, m_count)
                 if loss_aux is not None else jnp.zeros(()))
        is_first_virt = (s == 0) & (v_b == 0)
        is_last_virt = (s == n_stages - 1) & (v_b == v_chunks - 1)
        branch = jnp.where(
            bwd_live,
            jnp.where(is_first_virt, 1, jnp.where(is_last_virt, 3, 2)),
            0)
        dp, dx, lval = lax.switch(branch, bwd_branches,
                                  pb, x_saved, buf_b, mb_b, aux_b)
        gacc = jax.tree.map(lambda G, d: G.at[v_b].add(d), gacc, dp)
        lacc = lacc + lval

        # both shifts wrap: the ring carries chunk-boundary handoffs
        buf_f = p2p.send_forward_recv_forward(y, axis_name, wrap=True)
        buf_b = p2p.send_backward_recv_backward(dx, axis_name, wrap=True)
        return (ring, buf_f, buf_b, gacc, lacc), None

    carry0 = (
        jnp.zeros((ring_depth,) + tuple(act_shape), act_dtype),
        jnp.zeros(act_shape, act_dtype),
        jnp.zeros(act_shape, act_dtype),
        jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype), chunk_params),
        jnp.zeros((), jnp.float32),
    )
    (ring, buf_f, buf_b, gacc, lacc), _ = lax.scan(
        tick, carry0, jnp.arange(t_total))
    mean_loss = lax.psum(lacc, axis_name) / m_count
    grads = jax.tree.map(lambda g: g / m_count, gacc)
    return mean_loss, grads


def forward_backward_pipelining_with_interleaving(
        stage_fn: Callable, loss_fn: Callable, chunk_params, microbatches,
        loss_aux=None, forward_only: bool = False,
        axis_name: str = STAGE_AXIS, checkpoint_stage: bool = True,
        first_fn: Optional[Callable] = None,
        loss_with_params: bool = False,
        implementation: str = "1f1b"):
    """Interleaved/VPP schedule (reference:
    fwd_bwd_pipelining_with_interleaving.py). Same contract as the
    non-interleaved schedule except ``chunk_params`` leaves carry a leading
    ``[V]`` chunk axis; grads come back with the same layout. ``first_fn``
    runs on chunk 0 of stage 0, ``loss_fn`` (with ``loss_with_params=True``
    receiving chunk V-1's params) on the last stage.

    ``implementation="1f1b"`` (default, requires M % S == 0 like the
    reference's interleaving constraint — falls back to autodiff otherwise):
    the lock-step schedule of ``_fwd_bwd_interleaved_1f1b``, whose
    fill/drain cost S + (S-1)/V full-stage units genuinely undercuts the
    non-interleaved schedule's 2(S-1) — the reference's VPP bubble
    reduction, delivered. ``"autodiff"`` differentiates through
    ``pipeline_apply_interleaved`` (O(V*M) memory and a LARGER bubble than
    non-interleaved — kept as the oracle and the M % S != 0 fallback).
    """
    if not axis_is_bound(axis_name):
        raise RuntimeError(
            "pipeline schedules must run inside shard_map with the "
            f"'{axis_name}' axis bound")
    if first_fn is not None:
        # probe with chunk 0's params — exactly what the schedule itself
        # feeds first_fn, so a raising first_fn here is a REAL error and
        # propagates (no blanket except that could mute the guard)
        _entry0 = first_fn(
            jax.tree.map(lambda t: t[0], chunk_params),
            _index_mb(microbatches, 0, _mb_count(microbatches)))
        if isinstance(_entry0, (tuple, list, dict)):
            raise NotImplementedError(
                "the interleaved schedule takes a single-array activation; "
                "pytree payloads (MoE aux) are only supported by the "
                "non-interleaved schedules")
    n_stages = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)

    def mean_loss_of(params):
        outs = pipeline_apply_interleaved(
            stage_fn, params, microbatches, axis_name=axis_name,
            checkpoint_stage=checkpoint_stage, first_fn=first_fn)
        if loss_with_params:
            last_chunk = jax.tree.map(lambda t: t[-1], params)
            lf = functools.partial(loss_fn, last_chunk)
        else:
            lf = loss_fn
        if loss_aux is not None:
            per_mb = jax.vmap(lf)(outs, loss_aux)
        else:
            per_mb = jax.vmap(lf)(outs)
        local = jnp.where(s == n_stages - 1, per_mb.mean(), 0.0)
        return _allreduce(local, axis_name)

    if forward_only:
        return mean_loss_of(chunk_params), None
    m_count = _mb_count(microbatches)
    wants_1f1b = implementation == "1f1b" and n_stages > 1
    divisible = m_count % n_stages == 0
    if wants_1f1b and not divisible:
        # the reference raises on its divisibility constraint
        # (fwd_bwd_pipelining_with_interleaving.py); we keep training but
        # must not degrade memory/bubble silently
        get_transformer_logger(__name__).warning(
            "interleaved 1F1B needs num_microbatches %% pipeline_size == 0 "
            "(got M=%d, S=%d); falling back to the autodiff schedule "
            "(O(V*M) activation memory and a larger bubble).",
            m_count, n_stages)
    if (wants_1f1b and divisible
            and _use_explicit_schedule(
                stage_fn, jax.tree.map(lambda t: t[0], chunk_params),
                first_fn, loss_fn, loss_aux, loss_with_params,
                microbatches)):
        return _fwd_bwd_interleaved_1f1b(
            stage_fn, loss_fn, chunk_params, microbatches, loss_aux,
            axis_name, first_fn, loss_with_params)
    if implementation not in ("1f1b", "autodiff"):
        raise ValueError(f"unknown implementation {implementation!r}")
    loss, grads = jax.value_and_grad(mean_loss_of)(chunk_params)
    return loss, grads


def forward_backward_no_pipelining(
        stage_fn: Callable, loss_fn: Callable, params, microbatches,
        loss_aux=None, forward_only: bool = False, axis_name: str = STAGE_AXIS,
        checkpoint_stage: bool = False):
    """Reference: fwd_bwd_no_pipelining.py — sequential microbatch loop on a
    single stage (pp=1), grads accumulated across microbatches.

    A ``lax.scan`` runs the microbatches strictly sequentially, accumulating
    loss and grads in the carry — so only ONE microbatch's activations are
    live at a time, matching the reference's grad-accumulation memory
    profile (a vmap would materialize all M microbatch activations at once).
    """

    def one(p, mb_and_aux):
        if loss_aux is not None:
            mb, aux = mb_and_aux
            return loss_fn(stage_fn(p, mb), aux)
        return loss_fn(stage_fn(p, mb_and_aux))

    if checkpoint_stage:
        one = jax.checkpoint(one)
    xs = (microbatches, loss_aux) if loss_aux is not None else microbatches
    m = microbatches.shape[0]

    if forward_only:
        def fwd_body(acc, mb_and_aux):
            return acc + one(params, mb_and_aux), None
        total, _ = lax.scan(fwd_body, jnp.zeros(()), xs)
        return total / m, None

    def body(acc, mb_and_aux):
        acc_loss, acc_g = acc
        loss, g = jax.value_and_grad(one)(params, mb_and_aux)
        return (acc_loss + loss,
                jax.tree.map(jnp.add, acc_g, g)), None

    g0 = jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype), params)
    (total, grads), _ = lax.scan(body, (jnp.zeros(()), g0), xs)
    return total / m, jax.tree.map(lambda g: g / m, grads)


def get_forward_backward_func(
        virtual_pipeline_model_parallel_size: Optional[int] = None,
        pipeline_model_parallel_size: int = 1) -> Callable:
    """Reference: schedules/__init__.py:get_forward_backward_func — dispatch
    on (vpp, pp)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
