"""Pipeline schedules: scan-over-microbatches + ppermute.

Reference: apex/transformer/pipeline_parallel/schedules/ —
``get_forward_backward_func`` dispatching to ``forward_backward_no_pipelining``
or the 1F1B schedules (fwd_bwd_pipelining_without_interleaving.py: warmup
forwards -> steady 1F1B -> cooldown backwards, with hand-rolled P2P and
``deallocate_output_tensor``).

TPU restatement: the whole schedule is ONE differentiable program. Forward is
``lax.scan`` over T = M + S - 1 ticks inside ``shard_map``; at each tick every
stage runs its block on the activation that arrived, then the activations
shift one stage downstream via ppermute. Autodiff of that program IS the
pipelined backward: scan transposes to a reverse-time scan and ppermute to
its inverse permute, so gradient ticks flow upstream exactly like the
reference's cooldown/steady backward phases — no explicit warmup/steady/
cooldown bookkeeping, and per-microbatch grad accumulation falls out of the
scan transpose. Activation memory is bounded with ``jax.checkpoint`` around
the stage body (the reference's deallocate_output_tensor + recompute).

The stage function signature is functional (params explicit), so the
reference's ``forward_step_func(batch, model) -> (output, loss_func)``
callback becomes ``stage_fn(stage_params, x) -> y`` plus a terminal
``loss_fn(y, microbatch_aux) -> scalar``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import STAGE_AXIS
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
from apex_tpu.transformer.tensor_parallel.mappings import (
    axis_is_bound,
    reduce_from_tensor_model_parallel_region as _allreduce,
)


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str = STAGE_AXIS,
                   checkpoint_stage: bool = True):
    """Run microbatches through the S-stage pipeline; returns last-stage
    outputs per microbatch.

    Args:
      stage_fn: ``(stage_params, x) -> y`` — ONE stage's computation; every
        stage must map the same activation shape to itself (the reference's
        fixed ``tensor_shape`` contract in p2p_communication).
      stage_params: THIS stage's parameter pytree (per-device, varying over
        ``axis_name``).
      microbatches: ``[M, ...]`` array of microbatch inputs (used by stage 0).
      checkpoint_stage: recompute the stage body in backward
        (deallocate_output_tensor analog).

    Returns ``[M, ...]`` outputs, valid on the LAST stage (other stages hold
    in-flight garbage, as with the reference where only the last stage sees
    outputs).
    """
    s = lax.axis_index(axis_name)
    n_stages = lax.axis_size(axis_name)
    m = microbatches.shape[0]
    t_total = m + n_stages - 1

    body = stage_fn
    if checkpoint_stage:
        body = jax.checkpoint(stage_fn)

    def tick(buf, t):
        # stage 0 picks up microbatch t (clamped; beyond M it computes
        # garbage that never reaches a valid output slot)
        x0 = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), 0, keepdims=False)
        x = jnp.where(s == 0, x0.astype(buf.dtype), buf)
        y = body(stage_params, x)
        return p2p.send_forward_recv_forward(y, axis_name), y

    buf0 = jnp.zeros_like(
        jax.eval_shape(lambda mb: stage_fn(stage_params, mb[0]), microbatches),
    )
    _, ys = lax.scan(tick, buf0, jnp.arange(t_total))
    # last stage emits microbatch mb at tick mb + (S-1)
    return ys[n_stages - 1:]


def forward_backward_pipelining_without_interleaving(
        stage_fn: Callable, loss_fn: Callable, stage_params, microbatches,
        loss_aux=None, forward_only: bool = False,
        axis_name: str = STAGE_AXIS, checkpoint_stage: bool = True):
    """The 1F1B-equivalent schedule (reference:
    fwd_bwd_pipelining_without_interleaving.py).

    ``loss_fn(y, aux_m) -> scalar`` runs on the last stage per microbatch
    (aux_m = ``loss_aux[m]``, e.g. labels). Returns
    ``(mean_loss, stage_grads)`` — each device gets grads of ITS stage's
    params, accumulated over microbatches, with the loss broadcast to every
    stage (the reference reduces losses on the last stage only; here the
    broadcast costs one scalar psum and spares the caller a special case).
    With ``forward_only=True`` returns ``(mean_loss, None)``.
    """
    if not axis_is_bound(axis_name):
        raise RuntimeError(
            "pipeline schedules must run inside shard_map with the "
            f"'{axis_name}' axis bound (reference: requires "
            "parallel_state pipeline group)")
    n_stages = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    m = microbatches.shape[0]

    def mean_loss_of(params):
        outs = pipeline_apply(stage_fn, params, microbatches,
                              axis_name=axis_name,
                              checkpoint_stage=checkpoint_stage)
        if loss_aux is not None:
            per_mb = jax.vmap(loss_fn)(outs, loss_aux)
        else:
            per_mb = jax.vmap(loss_fn)(outs)
        local = jnp.where(s == n_stages - 1, per_mb.mean(), 0.0)
        # identity-backward all-reduce: every stage sees the loss, backward
        # seeds only the last stage's real output path
        return _allreduce(local, axis_name)

    if forward_only:
        return mean_loss_of(stage_params), None
    loss, grads = jax.value_and_grad(mean_loss_of)(stage_params)
    return loss, grads


def forward_backward_no_pipelining(
        stage_fn: Callable, loss_fn: Callable, params, microbatches,
        loss_aux=None, forward_only: bool = False, axis_name: str = STAGE_AXIS,
        checkpoint_stage: bool = False):
    """Reference: fwd_bwd_no_pipelining.py — sequential microbatch loop on a
    single stage (pp=1), grads accumulated across microbatches. Here a scan
    (the grad accumulation is the scan transpose)."""

    def mean_loss_of(p):
        def one(mb_and_aux):
            if loss_aux is not None:
                mb, aux = mb_and_aux
                return loss_fn(stage_fn(p, mb), aux)
            return loss_fn(stage_fn(p, mb_and_aux))

        xs = (microbatches, loss_aux) if loss_aux is not None else microbatches
        losses = jax.vmap(one)(xs) if not checkpoint_stage else \
            jax.vmap(jax.checkpoint(one))(xs)
        return losses.mean()

    if forward_only:
        return mean_loss_of(params), None
    return jax.value_and_grad(mean_loss_of)(params)


def get_forward_backward_func(
        virtual_pipeline_model_parallel_size: Optional[int] = None,
        pipeline_model_parallel_size: int = 1) -> Callable:
    """Reference: schedules/__init__.py:get_forward_backward_func — dispatch
    on (vpp, pp). Interleaved VPP is not yet implemented (reference optional
    milestone; SURVEY.md §7 M8)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            raise NotImplementedError(
                "interleaved (virtual) pipeline schedule is not implemented "
                "yet; use virtual_pipeline_model_parallel_size=None")
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
