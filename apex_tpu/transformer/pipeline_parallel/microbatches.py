"""Number-of-microbatches bookkeeping.

Reference: apex/transformer/pipeline_parallel/microbatches.py —
``build_num_microbatches_calculator`` returning
``ConstantNumMicroBatchesCalculator`` or
``RampupBatchsizeNumMicroBatchesCalculator`` (linear global-batch ramp for
BERT/GPT pretraining). Pure host-side arithmetic; ported semantics, not code.
"""

from __future__ import annotations

from typing import Optional, Sequence


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        raise NotImplementedError


class ConstantNumMicroBatchesCalculator(NumMicroBatchesCalculator):
    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        per_step = micro_batch_size * data_parallel_size
        if global_batch_size % per_step != 0:
            raise RuntimeError(
                f"global batch size ({global_batch_size}) is not divisible by"
                f" micro batch size ({micro_batch_size}) times data parallel"
                f" size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // per_step
        if self.num_micro_batches < 1:
            raise RuntimeError("number of microbatches must be at least 1")
        self.current_global_batch_size = global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatchesCalculator(NumMicroBatchesCalculator):
    """Linear global-batch-size ramp: start -> global over ramp_samples."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        if batch_size_increment <= 0 or start_batch_size <= 0:
            raise RuntimeError("batch size and increment must be positive")
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        per_step = micro_batch_size * data_parallel_size
        diff = global_batch_size - start_batch_size
        if diff < 0 or diff % batch_size_increment != 0:
            raise RuntimeError(
                "global batch size must be start + k * increment")
        if start_batch_size % per_step != 0 or batch_size_increment % per_step != 0:
            raise RuntimeError(
                "start batch size / increment must be divisible by micro "
                "batch size * data parallel size")
        # samples consumed per increment step of the ramp
        self.rampup_samples_per_increment = (
            self.ramup_samples / (diff / batch_size_increment) if diff else 0)
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool):
        if consumed_samples > self.ramup_samples or self.rampup_samples_per_increment == 0:
            bs = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            bs = min(self.global_batch_size,
                     self.start_batch_size + steps * self.batch_size_increment)
        per_step = self.micro_batch_size * self.data_parallel_size
        if consistency_check and bs % per_step != 0:
            raise RuntimeError(
                f"current global batch size ({bs}) is not divisible by "
                f"micro-batch-size ({self.micro_batch_size}) times "
                f"data parallel size ({self.data_parallel_size})")
        self.current_global_batch_size = bs
        self.num_micro_batches = max(1, bs // per_step)


def build_num_microbatches_calculator(
        rank: int = 0, rampup_batch_size: Optional[Sequence[int]] = None,
        global_batch_size: int = 1, micro_batch_size: int = 1,
        data_parallel_size: int = 1) -> NumMicroBatchesCalculator:
    """Reference signature (args come from Megatron-style global args)."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatchesCalculator(
            global_batch_size, micro_batch_size, data_parallel_size)
    if len(rampup_batch_size) != 3:
        raise RuntimeError(
            "rampup batch size must be: <start> <increment> <ramp samples>")
    start, inc, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatchesCalculator(
        start, inc, samples, global_batch_size, micro_batch_size,
        data_parallel_size)
