"""FusedNovoGrad — reference: apex/optimizers/fused_novograd.py
(csrc/multi_tensor_novograd.cu analog: per-tensor second moments)."""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops import optim_kernels
from apex_tpu.optimizers.common import FusedOptimizerBase


class FusedNovoGrad(FusedOptimizerBase):
    STATE_BUFFERS = ("m",)

    def __init__(self, params, lr=1e-3, bias_correction=True, betas=(0.95, 0.98),
                 eps=1e-8, weight_decay=0.0, amsgrad=False, reg_inside_moment=False,
                 grad_averaging=True, norm_type=2, init_zero=False,
                 set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        if norm_type != 2:
            raise ValueError("FusedNovoGrad only supports norm_type=2")
        defaults = dict(lr=lr, beta1=betas[0], beta2=betas[1], eps=eps,
                        weight_decay=weight_decay)
        self.init_zero = init_zero
        self.grad_averaging = grad_averaging
        super().__init__(params, defaults)
        # per-tensor second moment (one float per tensor, as in the reference)
        self.state["v_per_tensor"] = jnp.zeros((self.spec.num_tensors,), jnp.float32)

    def _update(self, g_flat, master, state, step, hyper):
        p, m, v = optim_kernels.novograd_update(
            g_flat, master, state["m"], state["v_per_tensor"],
            self.seg_rows, self.spec.num_tensors,
            beta1=hyper["beta1"], beta2=hyper["beta2"], eps=hyper["eps"],
            weight_decay=hyper["weight_decay"], lr=hyper["lr"], step=step,
            grad_scale=hyper.get("grad_scale"), noop=hyper.get("noop"),
            grad_averaging=self.grad_averaging, init_zero=self.init_zero,
        )
        return p, dict(m=m, v_per_tensor=v)
