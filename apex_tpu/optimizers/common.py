"""Shared machinery for the fused optimizer facades.

The reference optimizers (apex/optimizers/fused_adam.py etc.) are
``torch.optim.Optimizer`` subclasses that mutate ``p.data`` in place via
multi-tensor CUDA launches. JAX state is immutable, so the facade here:

- holds the fp32 **master copy** of all parameters as ONE flat buffer
  (amp-O2-style master weights are therefore the default, as in apex when
  driven by amp), plus flat optimizer state buffers;
- ``step(grads)`` flattens the incoming grad pytree (one fused concat),
  runs the Pallas update kernel(s), and returns the updated params unflattened
  into the original dtypes/shapes;
- the whole step is jitted once with donated state buffers — zero reallocation
  per step.

Weight-decay masks (apex param_groups with wd=0 on bias/LayerNorm) are
expressed as a predicate over pytree paths mapped to a per-segment wd vector.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import flat_buffer
from apex_tpu.ops.flat_buffer import LANE, FlatSpec, build_spec


def _agree_found_inf_across_model_parallel(found_inf):
    """pmax the found-inf flag over every bound model-parallel mesh axis.

    Reference: apex/transformer/amp/grad_scaler.py — GradScaler's found_inf
    is all-reduced (MAX) over the model-parallel group so TP/PP ranks agree
    on whether to skip the step. Outside shard_map this is the identity.
    """
    from jax import lax

    from apex_tpu.mesh import CONTEXT_AXIS, MODEL_AXIS, STAGE_AXIS
    from apex_tpu.transformer.tensor_parallel.mappings import axis_is_bound

    for ax in (MODEL_AXIS, STAGE_AXIS, CONTEXT_AXIS):
        if axis_is_bound(ax):
            found_inf = lax.pmax(found_inf, ax)
    return found_inf


def path_name(path) -> str:
    """'/'-joined key path for a pytree leaf (for wd-exclusion predicates)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


class FusedOptimizerBase:
    """Common state handling for FusedAdam/FusedLAMB/FusedSGD/FusedNovoGrad."""

    #: names of flat (rows, LANE) fp32 state buffers, e.g. ("m", "v")
    STATE_BUFFERS: tuple = ()

    def __init__(self, params, defaults: dict,
                 exclude_from_weight_decay: Optional[Callable[[str], bool]] = None):
        self.defaults = dict(defaults)
        self.spec: FlatSpec = build_spec(params)
        # host-side constant: staying numpy means jit embeds it as a literal
        # without a device round-trip (a device-array closure constant
        # requires a D2H copy at trace time — the bench_r03 failure mode)
        self.seg_rows = self.spec.segment_rows()
        self.master = flat_buffer.flatten(params, self.spec)
        self.state = {
            name: jnp.zeros((self.spec.total_rows, LANE), jnp.float32)
            for name in self.STATE_BUFFERS
        }
        self.step_count = jnp.zeros((), jnp.int32)

        wd = float(self.defaults.get("weight_decay", 0.0))
        if exclude_from_weight_decay is not None:
            paths, _ = jax.tree_util.tree_flatten_with_path(params)
            wd_list = [
                0.0 if exclude_from_weight_decay(path_name(p)) else wd
                for p, _ in paths
            ]
            self.wd_per_segment = jnp.asarray(wd_list, jnp.float32)
        else:
            self.wd_per_segment = None
        self._jit_step = None
        self._amp_scaler = None
        self._out_dtypes = None

    def attach_amp_scaler(self, scaler) -> None:
        """Called by amp.initialize: fuses unscale + found-inf skip + dynamic
        scale update into this optimizer's jitted step."""
        self._amp_scaler = scaler
        self._jit_step = None  # re-trace with the scaler path

    def set_output_dtypes(self, dtypes) -> None:
        """Called by amp.initialize under O2/O3: step() must return params in
        the policy-cast dtypes (master->model half copy of the reference),
        not the dtypes the optimizer was constructed with."""
        self._out_dtypes = list(dtypes)
        self._jit_step = None

    # -- torch-API parity shims ------------------------------------------------
    def zero_grad(self, set_to_none: bool = True):
        """No-op: JAX grads are values, not buffers (kept for API parity)."""

    @property
    def param_groups(self):
        """Minimal parity: one group carrying the defaults."""
        return [dict(self.defaults, params=None)]

    # -- state dict ------------------------------------------------------------
    def state_dict(self):
        return {
            "master": self.master,
            "state": dict(self.state),
            "step": self.step_count,
            "defaults": dict(self.defaults),
        }

    def load_state_dict(self, sd):
        self.master = jnp.asarray(sd["master"])
        self.state = {k: jnp.asarray(v) for k, v in sd["state"].items()}
        self.step_count = jnp.asarray(sd["step"])
        self.defaults.update(sd.get("defaults", {}))

    # -- stepping --------------------------------------------------------------
    def _update(self, g_flat, master, state, step, hyper):
        """Pure update: returns (new_master, new_state). Implemented by
        subclasses via the Pallas kernels."""
        raise NotImplementedError

    def step(self, grads, grad_scale=None, noop=None):
        """Apply one optimizer step for the given grad pytree; returns the
        updated parameter pytree (original shapes/dtypes).

        ``grad_scale`` multiplies grads inside the kernel (amp unscale + clip
        folded in); ``noop`` (0/1) skips the step (dynamic-loss-scale
        overflow), matching the reference's noop_flag semantics.
        """
        gdef = jax.tree.structure(grads)
        if gdef != self.spec.treedef:
            raise ValueError(
                f"grad pytree structure {gdef} does not match the parameter "
                f"structure this optimizer was built with ({self.spec.treedef})"
            )
        if getattr(self, "_amp_require_noop", False) and noop is None:
            # amp multi-loss dynamic mode: grads MUST come through
            # amp.unscale_and_combine (per-loss unscale + union found-inf);
            # its noop flag is the receipt — without it the grads are still
            # multiplied by the per-loss scales
            raise RuntimeError(
                "this optimizer was initialized by amp with multiple "
                "dynamically-scaled losses: combine grads with "
                "amp.unscale_and_combine and call "
                "step(grads, noop=noop)")
        if self._jit_step is None:
            spec = self.spec
            seg_rows = self.seg_rows
            scaler = self._amp_scaler
            out_dtypes = self._out_dtypes

            def _pure(g_tree, master, state, step, hyper, gs, noop_,
                      scaler_state, wd_seg):
                g_flat = flat_buffer.flatten(g_tree, spec)
                if scaler is not None:
                    # fused unscale + overflow skip (reference: scaler.py
                    # unscale + _process_optimizer's skip-on-overflow)
                    from apex_tpu.ops import optim_kernels

                    _, finite, _ = optim_kernels.global_grad_norm_and_finite(
                        g_flat, seg_rows, spec.num_tensors
                    )
                    found_inf = 1.0 - finite.astype(jnp.float32)
                    # model-parallel agreement: an inf on ONE tp/pp rank must
                    # skip the step on ALL ranks or shards diverge (reference:
                    # apex/transformer/amp/grad_scaler.py allreduces found_inf
                    # over the model-parallel group)
                    found_inf = _agree_found_inf_across_model_parallel(found_inf)
                    gs = gs / scaler_state.scale
                    noop_ = jnp.maximum(noop_, found_inf)
                    scaler_state = scaler.update(scaler_state, found_inf)
                # a skipped step must not advance the count (the reference
                # skips optimizer.step() entirely, so Adam bias correction
                # sees only applied steps)
                new_step = step + jnp.where(noop_ > 0.0, 0, 1).astype(step.dtype)
                # wd_seg rides as a traced argument (NOT a closure constant):
                # LARC temporarily nulls wd_per_segment around its inner step,
                # and a baked-in value would survive the jit cache
                new_master, new_state = self._update(
                    g_flat, master, state, new_step,
                    dict(hyper, grad_scale=gs, noop=noop_,
                         wd_per_segment=wd_seg)
                )
                params = flat_buffer.unflatten(new_master, spec, dtypes=out_dtypes)
                return params, new_master, new_state, new_step, scaler_state

            self._jit_step = jax.jit(_pure, donate_argnums=(1, 2))

        hyper = {k: jnp.asarray(v, jnp.float32)
                 for k, v in self.defaults.items()
                 if isinstance(v, (int, float))}
        gs = jnp.asarray(1.0 if grad_scale is None else grad_scale, jnp.float32)
        noop_ = jnp.asarray(0.0 if noop is None else noop, jnp.float32)
        sstate = self._amp_scaler.state if self._amp_scaler is not None else None
        params, self.master, self.state, self.step_count, sstate = self._jit_step(
            grads, self.master, self.state, self.step_count, hyper, gs, noop_,
            sstate, self.wd_per_segment
        )
        if self._amp_scaler is not None:
            self._amp_scaler.state = sstate
        return params
