"""Persistent fp32 main-grad accumulation across microbatches.

Reference: csrc/megatron/fused_weight_gradient_dense.cpp — with
``gradient_accumulation_fusion`` the TP linears' wgrad GEMM accumulates
directly into each param's persistent fp32 ``main_grad`` buffer, so
16-bit-per-microbatch rounding never touches the accumulated gradient.

TPU split of the same mechanism:
  1. the wgrad GEMM itself is fp32-accumulating
     (``fp32_wgrad_matmul`` in tensor_parallel/layers.py — MXU-native), and
  2. THIS buffer holds the across-microbatch fp32 sum in the optimizer's
     flat ``(rows, LANE)`` master-grad layout, donated on every add (zero
     reallocation — the "persistent buffer" property), feeding
     ``FusedOptimizerBase.step`` via ``grads()`` (or directly via
     ``step_flat`` consumers) with ``grad_scale=1/num_microbatches``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops import flat_buffer
from apex_tpu.ops.flat_buffer import LANE, FlatSpec, build_spec


class MainGradBuffer:
    """fp32 grad accumulator in the fused optimizers' flat layout."""

    def __init__(self, params_or_spec):
        self.spec: FlatSpec = (params_or_spec
                               if isinstance(params_or_spec, FlatSpec)
                               else build_spec(params_or_spec))
        self.buf = jnp.zeros((self.spec.total_rows, LANE), jnp.float32)
        self._jit_add = jax.jit(
            lambda buf, g: buf + flat_buffer.flatten(g, self.spec),
            donate_argnums=(0,))
        self.num_accumulated = 0

    def accumulate(self, grads) -> None:
        """buf += flatten(grads) — one fused donated add per microbatch."""
        gdef = jax.tree.structure(grads)
        if gdef != self.spec.treedef:
            raise ValueError(
                f"grad pytree {gdef} does not match the buffer's parameter "
                f"structure {self.spec.treedef}")
        self.buf = self._jit_add(self.buf, grads)
        self.num_accumulated += 1

    def grads(self, mean: bool = True):
        """The accumulated grad pytree (fp32), optionally averaged."""
        g = self.buf
        if mean and self.num_accumulated > 1:
            g = g / self.num_accumulated
        fp32 = [jnp.float32] * self.spec.num_tensors
        return flat_buffer.unflatten(g, self.spec, dtypes=fp32)

    def zero(self) -> None:
        self.buf = jnp.zeros_like(self.buf)
        self.num_accumulated = 0
