"""Optax-style pure transforms over the fused flat-buffer kernels.

The idiomatic-JAX entry point: ``tx = fused_adam(1e-3); state = tx.init(p);
updates, state = tx.update(g, state, p)``. The transform flattens grads (and
params where the rule needs them) into the lane-aligned buffer, runs the
single-launch Pallas kernel, and returns deltas as a pytree. State (m/v) stays
flat between steps — no per-step re-layout.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.ops import flat_buffer, optim_kernels
from apex_tpu.ops.flat_buffer import LANE


class FlatOptState(NamedTuple):
    count: jax.Array
    m: jax.Array
    v: jax.Array  # (rows, LANE) for adam/lamb; (num_tensors,) for novograd; () for sgd


def _prep(params_or_grads):
    spec = flat_buffer.build_spec(params_or_grads)
    seg = jnp.asarray(spec.segment_rows())
    return spec, seg


def fused_adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
               adam_w_mode=True, bias_correction=True) -> optax.GradientTransformation:
    def init_fn(params):
        spec, _ = _prep(params)
        z = jnp.zeros((spec.total_rows, LANE), jnp.float32)
        return FlatOptState(count=jnp.zeros((), jnp.int32), m=z, v=z)

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        spec, _ = _prep(updates)
        g = flat_buffer.flatten(updates, spec)
        p = flat_buffer.flatten(params, spec)
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        p_new, m, v = optim_kernels.adam_update(
            g, p, state.m, state.v,
            beta1=b1, beta2=b2, eps=eps, weight_decay=weight_decay, lr=lr,
            step=count, adam_w_mode=adam_w_mode, bias_correction=bias_correction,
        )
        deltas = flat_buffer.unflatten(p_new - p, spec)
        return deltas, FlatOptState(count=count, m=m, v=v)

    return optax.GradientTransformation(init_fn, update_fn)


def fused_lamb(learning_rate, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.01,
               max_grad_norm=1.0, grad_averaging=True,
               bias_correction=True) -> optax.GradientTransformation:
    def init_fn(params):
        spec, _ = _prep(params)
        z = jnp.zeros((spec.total_rows, LANE), jnp.float32)
        return FlatOptState(count=jnp.zeros((), jnp.int32), m=z, v=z)

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        spec, seg = _prep(updates)
        g = flat_buffer.flatten(updates, spec)
        p = flat_buffer.flatten(params, spec)
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        gnorm, finite, _ = optim_kernels.global_grad_norm_and_finite(
            g, seg, spec.num_tensors
        )
        clip = jnp.where(
            (max_grad_norm > 0.0) & (gnorm > max_grad_norm),
            max_grad_norm / gnorm, jnp.float32(1.0),
        )
        noop = 1.0 - finite.astype(jnp.float32)
        p_new, m, v = optim_kernels.lamb_update(
            g, p, state.m, state.v, seg, spec.num_tensors,
            beta1=b1, beta2=b2, eps=eps, weight_decay=weight_decay, lr=lr,
            step=count, grad_scale=clip, noop=noop,
            bias_correction=bias_correction, grad_averaging=grad_averaging,
        )
        deltas = flat_buffer.unflatten(p_new - p, spec)
        return deltas, FlatOptState(count=count, m=m, v=v)

    return optax.GradientTransformation(init_fn, update_fn)


def fused_sgd(learning_rate, momentum=0.0, dampening=0.0, weight_decay=0.0,
              nesterov=False) -> optax.GradientTransformation:
    def init_fn(params):
        spec, _ = _prep(params)
        z = jnp.zeros((spec.total_rows, LANE), jnp.float32)
        return FlatOptState(count=jnp.zeros((), jnp.int32), m=z, v=jnp.zeros((), jnp.float32))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params")
        spec, _ = _prep(updates)
        g = flat_buffer.flatten(updates, spec)
        p = flat_buffer.flatten(params, spec)
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        p_new, m = optim_kernels.sgd_update(
            g, p, state.m, lr=lr, momentum=momentum, dampening=dampening,
            weight_decay=weight_decay, nesterov=nesterov,
        )
        deltas = flat_buffer.unflatten(p_new - p, spec)
        return deltas, FlatOptState(count=count, m=m, v=state.v)

    return optax.GradientTransformation(init_fn, update_fn)


def fused_novograd(learning_rate, b1=0.95, b2=0.98, eps=1e-8, weight_decay=0.0,
                   grad_averaging=True) -> optax.GradientTransformation:
    def init_fn(params):
        spec, _ = _prep(params)
        z = jnp.zeros((spec.total_rows, LANE), jnp.float32)
        return FlatOptState(
            count=jnp.zeros((), jnp.int32), m=z,
            v=jnp.zeros((spec.num_tensors,), jnp.float32),
        )

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params")
        spec, seg = _prep(updates)
        g = flat_buffer.flatten(updates, spec)
        p = flat_buffer.flatten(params, spec)
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        p_new, m, v = optim_kernels.novograd_update(
            g, p, state.m, state.v, seg, spec.num_tensors,
            beta1=b1, beta2=b2, eps=eps, weight_decay=weight_decay, lr=lr,
            step=count, grad_averaging=grad_averaging,
        )
        deltas = flat_buffer.unflatten(p_new - p, spec)
        return deltas, FlatOptState(count=count, m=m, v=v)

    return optax.GradientTransformation(init_fn, update_fn)
