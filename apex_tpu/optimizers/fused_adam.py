"""FusedAdam — reference: apex/optimizers/fused_adam.py:~15.

Same knobs as the reference ctor (lr, bias_correction, betas, eps,
adam_w_mode, weight_decay, amsgrad unsupported — reference raises too).
One Pallas launch updates every parameter (csrc/multi_tensor_adam.cu analog).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops import optim_kernels
from apex_tpu.optimizers.common import FusedOptimizerBase


class FusedAdam(FusedOptimizerBase):
    STATE_BUFFERS = ("m", "v")

    def __init__(self, params, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, adam_w_mode=True, weight_decay=0.0, amsgrad=False,
                 set_grad_none=True, exclude_from_weight_decay=None):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        defaults = dict(lr=lr, beta1=betas[0], beta2=betas[1], eps=eps,
                        weight_decay=weight_decay)
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        super().__init__(params, defaults,
                         exclude_from_weight_decay=exclude_from_weight_decay)

    def _update(self, g_flat, master, state, step, hyper):
        # traced per-call (common.py passes it through the jit boundary so
        # LARC's temporary None isn't defeated by the trace cache)
        wd = hyper.get("wd_per_segment")
        if wd is None:
            wd = hyper["weight_decay"]
        p, m, v = optim_kernels.adam_update(
            g_flat, master, state["m"], state["v"],
            beta1=hyper["beta1"], beta2=hyper["beta2"], eps=hyper["eps"],
            weight_decay=wd, lr=hyper["lr"],
            step=step, grad_scale=hyper.get("grad_scale"),
            noop=hyper.get("noop"),
            adam_w_mode=self.adam_w_mode, bias_correction=self.bias_correction,
            seg_rows=self.seg_rows, num_segments=self.spec.num_tensors,
        )
        return p, dict(m=m, v=v)
