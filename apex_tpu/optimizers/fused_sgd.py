"""FusedSGD — reference: apex/optimizers/fused_sgd.py
(csrc/multi_tensor_sgd_kernel.cu analog)."""

from __future__ import annotations

from apex_tpu.ops import optim_kernels
from apex_tpu.optimizers.common import FusedOptimizerBase


class FusedSGD(FusedOptimizerBase):
    STATE_BUFFERS = ("momentum_buffer",)

    def __init__(self, params, lr, momentum=0.0, dampening=0.0, weight_decay=0.0,
                 nesterov=False, wd_after_momentum=False,
                 materialize_master_grads=True, set_grad_none=False):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero dampening")
        if wd_after_momentum:
            raise NotImplementedError("wd_after_momentum=True not implemented")
        defaults = dict(lr=lr, momentum=momentum, dampening=dampening,
                        weight_decay=weight_decay)
        self.nesterov = nesterov
        self.momentum = momentum
        super().__init__(params, defaults)

    def _update(self, g_flat, master, state, step, hyper):
        p, m = optim_kernels.sgd_update(
            g_flat, master, state["momentum_buffer"],
            lr=hyper["lr"], momentum=self.momentum,
            dampening=hyper["dampening"], weight_decay=hyper["weight_decay"],
            nesterov=self.nesterov, noop=hyper.get("noop"), step=step,
        )
        return p, dict(momentum_buffer=m)
