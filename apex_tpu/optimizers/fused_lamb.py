"""FusedLAMB — reference: apex/optimizers/fused_lamb.py:~15.

Two fused Pallas phases (direction + per-tensor norms; trust-ratio apply),
mirroring csrc/multi_tensor_lamb.cu. Global-grad-norm clipping
(``max_grad_norm``) is folded into the grad scale, computed by the fused
stats pass (csrc/multi_tensor_l2norm_kernel.cu analog). Per-tensor
weight-decay exclusion replaces the reference's param groups.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops import optim_kernels
from apex_tpu.optimizers.common import FusedOptimizerBase


class FusedLAMB(FusedOptimizerBase):
    STATE_BUFFERS = ("m", "v")

    def __init__(self, params, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-6, weight_decay=0.01, amsgrad=False, adam_w_mode=True,
                 grad_averaging=True, set_grad_none=True, max_grad_norm=1.0,
                 use_nvlamb=False, exclude_from_weight_decay=None):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        if not adam_w_mode:
            raise NotImplementedError("FusedLAMB: only adam_w_mode=True is implemented "
                                      "(reference default).")
        defaults = dict(lr=lr, beta1=betas[0], beta2=betas[1], eps=eps,
                        weight_decay=weight_decay, max_grad_norm=max_grad_norm)
        self.bias_correction = bias_correction
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        super().__init__(params, defaults,
                         exclude_from_weight_decay=exclude_from_weight_decay)

    def _update(self, g_flat, master, state, step, hyper):
        # fused global grad norm (+ finite check) — one pass over g
        gnorm, finite, _ = optim_kernels.global_grad_norm_and_finite(
            g_flat, self.seg_rows, self.spec.num_tensors
        )
        gs = hyper.get("grad_scale")
        gs = jnp.float32(1.0) if gs is None else gs
        gnorm = gnorm * gs
        max_norm = hyper["max_grad_norm"]
        clip = jnp.where(
            (max_norm > 0.0) & (gnorm > max_norm), max_norm / gnorm, jnp.float32(1.0)
        )
        noop = hyper.get("noop")
        noop = jnp.zeros((), jnp.float32) if noop is None else noop
        noop = jnp.maximum(noop, 1.0 - finite.astype(jnp.float32))

        wd = hyper.get("wd_per_segment")
        if wd is None:
            wd = hyper["weight_decay"]
        p, m, v = optim_kernels.lamb_update(
            g_flat, master, state["m"], state["v"],
            self.seg_rows, self.spec.num_tensors,
            beta1=hyper["beta1"], beta2=hyper["beta2"], eps=hyper["eps"],
            weight_decay=wd, lr=hyper["lr"], step=step,
            grad_scale=gs * clip, noop=noop,
            bias_correction=self.bias_correction,
            grad_averaging=self.grad_averaging,
            use_nvlamb=self.use_nvlamb,
        )
        return p, dict(m=m, v=v)
