"""Fused optimizers (reference: apex/optimizers/__init__.py).

Torch-like classes over flat-buffer Pallas update kernels, plus optax-style
pure transforms (``adam``/``lamb``/``sgd``/``novograd``) for idiomatic JAX
training loops.
"""

from apex_tpu.optimizers.fused_adam import FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_lamb import FusedLAMB  # noqa: F401
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad  # noqa: F401
from apex_tpu.optimizers.fused_sgd import FusedSGD  # noqa: F401
from apex_tpu.optimizers.transforms import (  # noqa: F401
    fused_adam,
    fused_lamb,
    fused_novograd,
    fused_sgd,
)

# reference: apex/optimizers/fused_mixed_precision_lamb.py — LAMB variant whose
# state/master handling is mixed precision; our FusedLAMB already keeps fp32
# masters over arbitrary-dtype params, so it is the same class here.
FusedMixedPrecisionLamb = FusedLAMB
