"""apex_tpu — a TPU-native training-acceleration library.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of NVIDIA Apex
(reference: kevinstephano/apex, surveyed in /root/repo/SURVEY.md):

- ``apex_tpu.optimizers``     — fused optimizers (FusedAdam/FusedLAMB/FusedSGD/
  FusedNovoGrad) as flattened-buffer Pallas multi-tensor update kernels behind a
  torch-like ``step()`` facade and optax-style pure transforms.
  (reference: apex/optimizers/*, csrc/multi_tensor_*.cu)
- ``apex_tpu.normalization``  — FusedLayerNorm / FusedRMSNorm Pallas kernels.
  (reference: apex/normalization/fused_layer_norm.py, csrc/layer_norm_cuda_kernel.cu)
- ``apex_tpu.amp``            — mixed-precision opt-levels (O0-O3) as bf16
  precision policies; ``scale_loss`` kept for API parity.
  (reference: apex/amp/*)
- ``apex_tpu.parallel``       — DistributedDataParallel facade, SyncBatchNorm via
  mesh psum, LARC. (reference: apex/parallel/*)
- ``apex_tpu.transformer``    — Megatron-style tensor/sequence/pipeline parallelism
  over a named ``jax.sharding.Mesh``. (reference: apex/transformer/*)
- ``apex_tpu.contrib``        — multihead_attn, xentropy, clip_grad, distributed
  (ZeRO) optimizers, sparsity (ASP), and the long tail.
  (reference: apex/contrib/*)
- ``apex_tpu.ops``            — the Pallas kernel layer (the CUDA ``csrc/``
  equivalent): layer_norm, rms_norm, flash attention, softmax-xentropy,
  multi-tensor optimizer updates.
- ``apex_tpu.collectives``    — the NCCL-equivalent: thin wrappers over XLA
  collectives (psum/all_gather/psum_scatter/ppermute/all_to_all) on mesh axes.
- ``apex_tpu.models``         — model zoo used by benchmarks/examples (BERT, GPT,
  ResNet). (reference: examples/, apex/transformer/testing/standalone_*.py)
"""

__version__ = "0.1.0"

from apex_tpu import _compat  # noqa: F401  (jax version shims; must be first)
from apex_tpu import collectives  # noqa: F401
from apex_tpu import mesh  # noqa: F401

# Subpackages are imported lazily by users:
#   from apex_tpu import amp, optimizers, normalization, parallel, transformer
