"""Profiling/tracing utilities.

Reference: none — apex removed its profiler (apex.pyprof, README points to
the archived repo); what remains is nvtx-friendly kernel naming and
``torch.cuda.synchronize()`` timing discipline in examples
(examples/imagenet/main_amp.py). SURVEY.md §5 prescribes jax.profiler
annotation + block_until_ready timing from day one as a gap to EXCEED.

- ``annotate(name)``: decorator adding a jax.profiler/XLA named scope, so
  kernels and modules show up as labeled spans in TensorBoard/xprof traces
  (the nvtx-range analog).
- ``trace(logdir)``: context manager around jax.profiler.trace.
- ``time_fn(fn, *args)``: wall-time with block_until_ready (the
  cuda-synchronize discipline) — used by bench.py.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Callable

import jax


def annotate(name: str) -> Callable:
    """Decorator: run the function under a named profiler scope."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.named_scope(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Capture a profiler trace of the enclosed block to ``logdir``."""
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def time_fn(fn: Callable, *args, iters: int = 10, warmup: int = 2, **kwargs):
    """Mean wall-seconds per call, synchronized (block_until_ready).

    Returns (seconds_per_iter, last_output).
    """
    out = None
    for _ in range(warmup):
        out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out
