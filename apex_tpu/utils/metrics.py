"""Scalar metrics registry + meters (SURVEY.md §5 metrics row).

The reference has no metrics subsystem — its only observability is the
``AverageMeter`` stdout meter inside examples (examples/imagenet/
main_amp.py:~420) and amp's ``maybe_print``. This module is the prescribed
"small metrics.py (host-callback scalars), already beyond reference":

- ``AverageMeter`` — exact analog of the example's meter (val/avg/sum/count).
- ``record(name, value)`` — usable INSIDE jitted/sharded code: a
  ``jax.debug.callback`` ships the scalar to the host registry when the step
  actually executes (so recording does not force a sync; values arrive in
  execution order).
- ``get``/``mean``/``summary``/``clear`` — host-side registry access. Call
  ``jax.effects_barrier()`` (or block on step outputs) before reading if you
  need every in-flight step's values.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List

import jax

__all__ = ["AverageMeter", "record", "get", "mean", "summary", "clear",
           "StepTimer"]

_REGISTRY: Dict[str, List[float]] = collections.defaultdict(list)


class AverageMeter:
    """Reference: examples/imagenet/main_amp.py AverageMeter — running
    val/sum/count/avg."""

    def __init__(self, name: str = ""):
        self.name = name
        self.reset()

    def reset(self):
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self):
        return f"{self.name} {self.val:.4f} ({self.avg:.4f})"


def _append(name: str, value) -> None:
    _REGISTRY[name].append(float(value))


def record(name: str, value) -> None:
    """Record a scalar from anywhere — including inside jit/shard_map.

    ``name`` must be a static Python string; ``value`` may be a traced
    scalar (a host callback delivers it at execution time) or a plain
    number (recorded immediately).
    """
    if isinstance(value, (int, float)):
        _append(name, value)
        return
    jax.debug.callback(lambda v, _n=name: _append(_n, v), value)


def get(name: str) -> List[float]:
    return list(_REGISTRY.get(name, []))


def mean(name: str) -> float:
    vals = _REGISTRY.get(name)
    if not vals:
        raise KeyError(f"no recorded values for metric {name!r}")
    return sum(vals) / len(vals)


def summary() -> Dict[str, dict]:
    """{name: {count, mean, last}} for every recorded metric."""
    return {
        name: {"count": len(v), "mean": sum(v) / len(v), "last": v[-1]}
        for name, v in _REGISTRY.items() if v
    }


def clear(name: str = None) -> None:
    if name is None:
        _REGISTRY.clear()
    else:
        _REGISTRY.pop(name, None)


class StepTimer:
    """Wall-clock step meter with device-sync discipline (the examples'
    ``torch.cuda.synchronize()``-before-timing analog): ``observe`` blocks on
    the step's outputs so the recorded time covers real device work."""

    def __init__(self, name: str = "step_time_ms"):
        self.name = name
        self.meter = AverageMeter(name)
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()
        return self

    def observe(self, outputs=None):
        if self._t0 is None:
            raise RuntimeError("StepTimer.observe() before start()")
        if outputs is not None:
            jax.block_until_ready(outputs)
        dt_ms = (time.perf_counter() - self._t0) * 1e3
        self.meter.update(dt_ms)
        _append(self.name, dt_ms)
        self._t0 = None
        return dt_ms
