"""Typed metric instruments + scalar registry (docs/observability.md).

The reference has no metrics subsystem — its only observability is the
``AverageMeter`` stdout meter inside examples (examples/imagenet/
main_amp.py:~420) and amp's ``maybe_print``. This module grew from the
prescribed "small metrics.py (host-callback scalars)" into the instrument
layer the serving/observability tier (``apex_tpu.obs``) exports from:

- **Typed instruments** — :class:`Counter` (monotonic), :class:`Gauge`
  (last-value), and :class:`Histogram` (log-bucketed, p50/p90/p99), each
  with optional labels, interned in a process-wide registry via
  :func:`counter` / :func:`gauge` / :func:`histogram` (same
  ``(name, labels)`` always returns the same object).
- ``record(name, value)`` — usable INSIDE jitted/sharded code: a
  ``jax.debug.callback`` ships the scalar to the host registry when the
  step actually executes (recording does not force a sync; values arrive
  in execution order). The callback is a module-level callable cached per
  name, so repeated traces of the same instrumented program share one
  callback object instead of baking a fresh closure into every jaxpr.
- ``get``/``mean``/``summary``/``snapshot``/``clear`` — host-side registry
  access. Call ``jax.effects_barrier()`` (or block on step outputs) before
  reading if you need every in-flight step's values. Callbacks can arrive
  on runtime threads, so every registry mutation takes the module lock.
- ``AverageMeter`` — exact analog of the example's meter (val/avg/sum/
  count), kept as a standalone convenience.
- ``StepTimer`` — wall-clock step meter; ``observe`` feeds a
  :class:`Histogram` (percentiles) plus the raw ``record()`` series.

Export (Prometheus text exposition, JSON snapshots, an optional HTTP
endpoint) lives in ``apex_tpu.obs.export`` and reads :func:`snapshot`.
"""

from __future__ import annotations

import collections
import functools
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax

__all__ = ["AverageMeter", "Counter", "Gauge", "Histogram", "StepTimer",
           "counter", "gauge", "histogram", "instruments", "record", "get",
           "mean", "summary", "snapshot", "clear"]

# one re-entrant lock guards the raw series, the instrument table, and
# every instrument's internal state: jax.debug.callback may deliver on
# XLA runtime threads while the scheduler thread reads a summary
_LOCK = threading.RLock()
_REGISTRY: Dict[str, List[float]] = collections.defaultdict(list)

LabelsKey = Tuple[Tuple[str, str], ...]
_INSTRUMENTS: "Dict[Tuple[str, LabelsKey], Instrument]" = {}
_JIT_CALLBACKS: Dict[str, Callable] = {}


class AverageMeter:
    """Reference: examples/imagenet/main_amp.py AverageMeter — running
    val/sum/count/avg."""

    def __init__(self, name: str = ""):
        self.name = name
        self.reset()

    def reset(self):
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self):
        return f"{self.name} {self.val:.4f} ({self.avg:.4f})"


# --------------------------------------------------------------------------
# typed instruments
# --------------------------------------------------------------------------

def _labels_key(labels: Optional[Dict[str, str]]) -> LabelsKey:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


class Instrument:
    """Base: a named, optionally-labeled metric. Subclasses define the
    measurement semantics; construction goes through :func:`counter` /
    :func:`gauge` / :func:`histogram` so equal ``(name, labels)`` pairs
    share one instance process-wide."""

    kind = "untyped"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels or {})

    def key(self) -> Tuple[str, LabelsKey]:
        return (self.name, _labels_key(self.labels))

    def config(self) -> Dict[str, object]:
        """Layout parameters that must agree across every label set of a
        name (one Prometheus family, one layout)."""
        return {}


class Counter(Instrument):
    """Monotonically non-decreasing count (requests admitted, pages
    evicted). Per-interval rates/deltas are the READER's job (the
    scheduler derives per-run stats from start/end values)."""

    kind = "counter"

    def __init__(self, name, labels=None):
        super().__init__(name, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"Counter.inc({n}): counters only go up — "
                             "use a Gauge for signed deltas")
        with _LOCK:
            self._value += float(n)

    @property
    def value(self) -> float:
        with _LOCK:
            return self._value


class Gauge(Instrument):
    """Last-written value (free pages, slots in use)."""

    kind = "gauge"

    def __init__(self, name, labels=None):
        super().__init__(name, labels)
        self._value = 0.0

    def set(self, v) -> None:
        with _LOCK:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with _LOCK:
            self._value += float(n)

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with _LOCK:
            return self._value


class Histogram(Instrument):
    """Log-bucketed histogram with quantile estimation.

    Bucket ``i`` covers ``(base * growth**(i-1), base * growth**i]``
    (bucket 0 covers ``(0, base]``; the last bucket is open-ended), so a
    fixed, small bucket array spans microseconds to hours — the standard
    latency-histogram layout. :meth:`quantile` walks the cumulative
    counts and interpolates linearly inside the target bucket, clamped to
    the observed ``[min, max]`` (a single-observation histogram reports
    that exact value at every quantile; errors are bounded by one bucket's
    width, i.e. a factor of ``growth``).
    """

    kind = "histogram"

    def __init__(self, name, labels=None, *, base: float = 1e-2,
                 growth: float = 2.0, n_buckets: int = 48):
        super().__init__(name, labels)
        if base <= 0 or growth <= 1 or n_buckets < 2:
            raise ValueError("Histogram needs base > 0, growth > 1, "
                             "n_buckets >= 2")
        self.base = float(base)
        self.growth = float(growth)
        self.n_buckets = n_buckets
        self._lg = math.log(growth)
        self._counts = [0] * n_buckets
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    # -- write ----------------------------------------------------------

    def _bucket_index(self, v: float) -> int:
        if v <= self.base:
            return 0
        # le semantics at boundaries: v == base*growth**i lands in bucket
        # i (the 1e-9 slack absorbs log() round-off at exact powers)
        i = int(math.ceil(math.log(v / self.base) / self._lg - 1e-9))
        return min(max(i, 0), len(self._counts) - 1)

    def observe(self, v) -> None:
        v = float(v)
        with _LOCK:
            self._counts[self._bucket_index(v)] += 1
            self._count += 1
            self._sum += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)

    # -- read -----------------------------------------------------------

    @property
    def count(self) -> int:
        with _LOCK:
            return self._count

    @property
    def sum(self) -> float:
        with _LOCK:
            return self._sum

    def bucket_le(self, i: int) -> float:
        """Upper bound of bucket ``i`` (inf for the last bucket)."""
        if i >= len(self._counts) - 1:
            return math.inf
        return self.base * self.growth ** i

    def buckets(self) -> List[Tuple[float, int]]:
        """``[(le, cumulative_count), ...]`` — the Prometheus layout."""
        out, cum = [], 0
        with _LOCK:
            for i, c in enumerate(self._counts):
                cum += c
                out.append((self.bucket_le(i), cum))
        return out

    def quantile(self, q: float) -> float:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with _LOCK:
            if self._count == 0:
                return 0.0
            target = q * self._count
            cum = 0.0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                if cum + c >= target:
                    lo = 0.0 if i == 0 else self.bucket_le(i - 1)
                    hi = self.bucket_le(i)
                    if math.isinf(hi):
                        hi = self._max
                    frac = (target - cum) / c
                    v = lo + frac * (hi - lo)
                    return min(max(v, self._min), self._max)
                cum += c
            return self._max

    def percentiles(self) -> Dict[str, float]:
        return {"p50": self.quantile(0.50), "p90": self.quantile(0.90),
                "p99": self.quantile(0.99)}

    def config(self) -> Dict[str, object]:
        return {"base": self.base, "growth": self.growth,
                "n_buckets": self.n_buckets}


def _instrument(cls, name: str, labels: Optional[Dict[str, str]], **kw):
    key = (name, _labels_key(labels))
    with _LOCK:
        inst = _INSTRUMENTS.get(key)
        if inst is None:
            # kind AND layout are properties of the NAME (the Prometheus
            # data model: one family, one type, one bucket layout) — a
            # sibling label set must agree on both
            sibling = next((i for (n, _), i in _INSTRUMENTS.items()
                            if n == name), None)
            if sibling is not None and not isinstance(sibling, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{sibling.kind} (labels {sibling.labels}), not "
                    f"{cls.kind}")
            inst = cls(name, labels, **kw)
            if sibling is not None and inst.config() != sibling.config():
                raise ValueError(
                    f"metric {name!r} label set {inst.labels} asks for "
                    f"config {inst.config()} but the family is "
                    f"registered with {sibling.config()}")
            _INSTRUMENTS[key] = inst
        elif not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} already registered as {inst.kind}, "
                f"not {cls.kind}")
        elif kw:
            # a histogram() call asking for different buckets than the
            # registered instance must fail loudly — silently returning
            # the old layout would mis-bucket every observation
            drift = {k: (v, getattr(inst, k)) for k, v in kw.items()
                     if getattr(inst, k, v) != v}
            if drift:
                raise ValueError(
                    f"metric {name!r} already registered with different "
                    f"config: {drift} (requested, registered)")
        return inst


def counter(name: str, labels: Optional[Dict[str, str]] = None) -> Counter:
    return _instrument(Counter, name, labels)


def gauge(name: str, labels: Optional[Dict[str, str]] = None) -> Gauge:
    return _instrument(Gauge, name, labels)


def histogram(name: str, labels: Optional[Dict[str, str]] = None,
              **kw) -> Histogram:
    return _instrument(Histogram, name, labels, **kw)


def instruments() -> List[Instrument]:
    """Every registered instrument, sorted by (name, labels)."""
    with _LOCK:
        return [_INSTRUMENTS[k] for k in sorted(_INSTRUMENTS)]


# --------------------------------------------------------------------------
# the raw scalar series (jit-safe channel)
# --------------------------------------------------------------------------

def _append(name: str, value) -> None:
    with _LOCK:
        _REGISTRY[name].append(float(value))


def _callback_for(name: str) -> Callable:
    """Module-level host callback for ``record(name, ...)``, cached per
    name: every trace of an instrumented program bakes the SAME callable
    into its jaxpr (a per-call lambda would defeat jaxpr/dispatch caching
    and leak one closure per trace)."""
    with _LOCK:
        cb = _JIT_CALLBACKS.get(name)
        if cb is None:
            cb = functools.partial(_append, name)
            _JIT_CALLBACKS[name] = cb
        return cb


def record(name: str, value) -> None:
    """Record a scalar from anywhere — including inside jit/shard_map.

    ``name`` must be a static Python string; ``value`` may be a traced
    scalar (a host callback delivers it at execution time — non-blocking,
    tpu-lint's host-sync rule knows this channel is exempt) or a plain
    number (recorded immediately).
    """
    if isinstance(value, (int, float)):
        _append(name, value)
        return
    jax.debug.callback(_callback_for(name), value)


def get(name: str) -> List[float]:
    with _LOCK:
        return list(_REGISTRY.get(name, []))


def mean(name: str) -> float:
    with _LOCK:
        vals = _REGISTRY.get(name)
        if not vals:
            raise KeyError(f"no recorded values for metric {name!r}")
        return sum(vals) / len(vals)


def summary() -> Dict[str, dict]:
    """{name: {count, mean, last}} for every recorded raw series."""
    with _LOCK:
        return {
            name: {"count": len(v), "mean": sum(v) / len(v), "last": v[-1]}
            for name, v in _REGISTRY.items() if v
        }


def snapshot() -> Dict[str, object]:
    """Full registry state for exporters (``apex_tpu.obs.export``):
    raw-series summaries plus every typed instrument's current value
    (histograms include cumulative buckets and p50/p90/p99). Inf bucket
    bounds are ``None`` so the dict round-trips through strict JSON."""
    with _LOCK:
        out = {"series": summary(), "counters": [], "gauges": [],
               "histograms": []}
        for inst in instruments():
            entry = {"name": inst.name, "labels": dict(inst.labels)}
            if isinstance(inst, Counter):
                entry["value"] = inst.value
                out["counters"].append(entry)
            elif isinstance(inst, Gauge):
                entry["value"] = inst.value
                out["gauges"].append(entry)
            elif isinstance(inst, Histogram):
                entry.update(count=inst.count, sum=inst.sum,
                             **inst.percentiles())
                if inst.count:
                    entry["min"] = inst._min
                    entry["max"] = inst._max
                entry["buckets"] = [
                    [None if math.isinf(le) else le, cum]
                    for le, cum in inst.buckets()]
                out["histograms"].append(entry)
        return out


def clear(name: Optional[str] = None) -> None:
    """Reset the registry. ``clear()`` drops every raw series AND every
    typed instrument (full process reset — what tests want between
    cases); ``clear(name)`` drops just that series and any instruments
    registered under that name (all label sets)."""
    with _LOCK:
        if name is None:
            _REGISTRY.clear()
            _INSTRUMENTS.clear()
            return
        _REGISTRY.pop(name, None)
        for key in [k for k in _INSTRUMENTS if k[0] == name]:
            del _INSTRUMENTS[key]


class StepTimer:
    """Wall-clock step meter with device-sync discipline (the examples'
    ``torch.cuda.synchronize()``-before-timing analog): ``observe`` blocks
    on the step's outputs so the recorded time covers real device work.

    Each observation lands exactly once in each store: the raw ``record``
    series under ``name`` (ordered per-step values) and a log-bucketed
    :class:`Histogram` under the same name (percentiles). The old
    ``AverageMeter`` member double-wrote the same value; mean/last now
    come from ``summary()`` or ``hist``."""

    def __init__(self, name: str = "step_time_ms"):
        self.name = name
        histogram(name)                  # register up front
        self._t0 = None

    @property
    def hist(self) -> Histogram:
        """The timer's histogram, re-interned per access so a
        ``metrics.clear()`` between observations cannot orphan it (the
        timer would otherwise keep feeding an instrument no snapshot
        sees)."""
        return histogram(self.name)

    def start(self):
        self._t0 = time.perf_counter()
        return self

    def observe(self, outputs=None):
        if self._t0 is None:
            raise RuntimeError("StepTimer.observe() before start()")
        if outputs is not None:
            jax.block_until_ready(outputs)
        dt_ms = (time.perf_counter() - self._t0) * 1e3
        self.hist.observe(dt_ms)
        _append(self.name, dt_ms)
        self._t0 = None
        return dt_ms
