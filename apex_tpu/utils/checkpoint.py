"""Mesh-aware checkpoint/resume.

Reference: checkpointing in apex is ``torch.save`` of state_dicts in examples
(examples/imagenet/main_amp.py:~250 saves model/optimizer/amp) plus
state_dict() on every stateful piece (amp loss scalers, fused optimizers'
step counts, CudaRNGStatesTracker). SURVEY.md §5 prescribes the TPU upgrade:
orbax-backed pytree checkpointing that restores arrays WITH their shardings
(a ZeRO-sharded optimizer restores row-sharded, no host gather).

``save_checkpoint``/``restore_checkpoint`` take a state pytree that may mix
jax Arrays (sharded or not), numpy arrays, and scalars; restore matches the
sharding/structure of an ``like`` pytree when given (the orbax restore-args
pattern). ``CheckpointManager`` adds step-numbered directories + retention.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_checkpoint(path: str, state: Any, force: bool = True) -> None:
    """Write ``state`` (pytree of arrays/scalars) to ``path`` atomically."""
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), state, force=force)


def restore_checkpoint(path: str, like: Optional[Any] = None) -> Any:
    """Read a checkpoint. With ``like`` (a pytree of arrays or
    ShapeDtypeStructs carrying shardings), arrays restore directly into the
    given shardings — the mesh-aware resume path."""
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        if like is None:
            return ckptr.restore(os.path.abspath(path))
        targets = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            like)
        return ckptr.restore(os.path.abspath(path), targets)


class CheckpointManager:
    """Step-numbered checkpoints with retention (orbax CheckpointManager
    facade, apex-free API kept tiny on purpose)."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        ocp = _ocp()
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step: int, state: Any) -> None:
        ocp = _ocp()
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def restore(self, step: Optional[int] = None,
                like: Optional[Any] = None) -> Any:
        ocp = _ocp()
        step = self.latest_step() if step is None else step
        if like is None:
            return self._mgr.restore(step)
        targets = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            like)
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(targets))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def close(self):
        self._mgr.close()


def optimizer_state_dict(optimizer) -> dict:
    """Checkpointable state of a fused optimizer + attached amp scaler +
    the RNG tracker (everything the reference saves: optimizer state_dict,
    amp.state_dict(), CudaRNGStatesTracker.get_states())."""
    from apex_tpu import amp
    from apex_tpu.transformer.tensor_parallel.random import (
        get_rng_state_tracker)

    return {
        "optimizer": optimizer.state_dict(),
        "amp": amp.state_dict(),
        "rng_tracker": get_rng_state_tracker().get_states(),
    }


def load_optimizer_state_dict(optimizer, sd: dict) -> None:
    from apex_tpu import amp
    from apex_tpu.transformer.tensor_parallel.random import (
        get_rng_state_tracker)

    optimizer.load_state_dict(sd["optimizer"])
    amp.load_state_dict(sd.get("amp", {}))
    if sd.get("rng_tracker", {}).get("seeds"):
        get_rng_state_tracker().set_states(sd["rng_tracker"])
