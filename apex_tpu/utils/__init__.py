"""apex_tpu.utils — profiling + timing + checkpoint subsystems.

SURVEY.md §5 marks tracing/profiling and mesh-aware checkpointing as
"gaps to exceed" over the reference (which removed apex.pyprof and delegates
checkpointing to torch.save in examples).
"""

from apex_tpu.utils.profiling import annotate, time_fn, trace
from apex_tpu.utils.checkpoint import (
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)
from apex_tpu.utils import metrics
from apex_tpu.utils.metrics import (AverageMeter, Counter, Gauge,
                                    Histogram, StepTimer)

__all__ = ["annotate", "time_fn", "trace", "save_checkpoint",
           "restore_checkpoint", "CheckpointManager", "metrics",
           "AverageMeter", "Counter", "Gauge", "Histogram", "StepTimer"]
