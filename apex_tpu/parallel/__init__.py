"""Data-parallel utilities — reference: apex/parallel/*.

- ``DistributedDataParallel`` (apex/parallel/distributed.py:~200): bucketed,
  overlapped NCCL allreduce of grads. Under ``pjit`` over a sharded ``data``
  axis the SPMD partitioner inserts (and the latency-hiding scheduler
  overlaps) the gradient all-reduce, so the facade here keeps the API while
  the mechanism is native; a manual-axes path is provided for ``shard_map``
  training loops.
- ``SyncBatchNorm`` (apex/parallel/optimized_sync_batchnorm.py + syncbn CUDA
  ext): batch-norm stats psum'd across the ``data`` axis.
- ``LARC`` (apex/parallel/LARC.py): layer-wise adaptive rate clipping wrapper.
- ``convert_syncbn_model`` (apex/parallel/__init__.py:~20): recursive
  BatchNorm -> SyncBatchNorm conversion.
"""

from apex_tpu.parallel.distributed import DistributedDataParallel  # noqa: F401
from apex_tpu.parallel.LARC import LARC  # noqa: F401
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
)
