"""SyncBatchNorm — cross-replica batch normalization.

Reference: apex/parallel/optimized_sync_batchnorm.py (+ the ``syncbn`` CUDA
extension, csrc/welford.cu): local Welford stats per GPU, allgathered and
combined with ``welford_parallel``, then a fused normalize; backward issues a
second round of reductions for the cross-replica grad terms.

TPU design: compute local sum / sum-of-squares, ``psum`` them over the mesh
axes (one fused XLA all-reduce over both moments), normalize. Autodiff through
``psum`` reproduces the reference's hand-written cross-replica backward
(grad terms require the same reductions) with no custom kernel: XLA fuses the
whole thing. Works inside ``shard_map`` bodies where the axis is bound; when
no axis is bound (single device / pure pjit without manual axes) it degrades
to plain BatchNorm over the local batch, matching the reference's behavior
when torch.distributed isn't initialized.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import DATA_AXIS

AxisName = Union[str, Sequence[str]]


def sync_batch_norm_stats(x, reduce_dims, axis_name: Optional[AxisName]):
    """(mean, var) over local dims + the named mesh axis.

    Reference: csrc/welford.cu welford_parallel — combining per-replica
    (mean, var, count) triples. psum of (sum, sumsq, count) is numerically
    equivalent in fp32 and maps to ONE fused all-reduce.
    """
    x32 = x.astype(jnp.float32)
    n_local = 1
    for d in reduce_dims:
        n_local *= x.shape[d]
    s = jnp.sum(x32, axis=reduce_dims)
    ss = jnp.sum(x32 * x32, axis=reduce_dims)
    n = jnp.float32(n_local)
    if axis_name is not None:
        s, ss, n = lax.psum((s, ss, n), axis_name)
    mean = s / n
    var = ss / n - mean * mean
    return mean, var, n


class SyncBatchNorm(nn.Module):
    """Drop-in for apex.parallel.SyncBatchNorm (NHWC / feature-last).

    Ctor args mirror torch BatchNormNd + the reference's process-group arg
    (here: ``axis_name``, a mesh axis or tuple of axes to sync over; None =
    local-only). ``use_running_average=None`` defers to the call arg, flax
    style.
    """

    num_features: Optional[int] = None   # None: infer from the channel axis
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[AxisName] = DATA_AXIS
    channel_axis: int = -1
    dtype: Any = None

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        ch_ax = self.channel_axis % x.ndim
        num_features = (self.num_features if self.num_features is not None
                        else x.shape[ch_ax])
        if x.shape[ch_ax] != num_features:
            raise ValueError(
                f"channel axis {ch_ax} of input shape {x.shape} != "
                f"num_features {num_features}")
        reduce_dims = tuple(d for d in range(x.ndim) if d != ch_ax)

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((num_features,),
                                                  jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((num_features,),
                                                jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            axis = self.axis_name
            if axis is not None:
                # degrade to local stats when the axis isn't bound (single
                # device, or called outside shard_map) — the reference
                # similarly falls back when dist isn't initialized
                try:
                    lax.axis_size(axis)
                except NameError:
                    axis = None
            mean, var, n = sync_batch_norm_stats(x, reduce_dims, axis)
            if (self.track_running_stats and not self.is_initializing()
                    and self.is_mutable_collection("batch_stats")):
                m = jnp.float32(self.momentum)
                # torch semantics: running_var uses the unbiased estimator
                unbiased = var * (n / jnp.maximum(n - 1.0, 1.0))
                ra_mean.value = (1 - m) * ra_mean.value + m * lax.stop_gradient(mean)
                ra_var.value = (1 - m) * ra_var.value + m * lax.stop_gradient(unbiased)

        shape = [1] * x.ndim
        shape[ch_ax] = num_features
        x32 = x.astype(jnp.float32)
        y = (x32 - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + self.eps)
        if self.affine:
            weight = self.param("weight", nn.initializers.ones,
                                (num_features,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros,
                              (num_features,), jnp.float32)
            y = y * weight.reshape(shape) + bias.reshape(shape)
        out_dtype = self.dtype if self.dtype is not None else x.dtype
        return y.astype(out_dtype)

    forward = __call__


def convert_syncbn_model(module: nn.Module,
                         axis_name: Optional[AxisName] = DATA_AXIS) -> nn.Module:
    """Recursively replace ``flax.linen.BatchNorm`` submodule *fields* with
    ``SyncBatchNorm`` (reference: apex/parallel/__init__.py:convert_syncbn_model,
    which walks ``module.named_children()``).

    Flax modules are frozen dataclasses, so only BatchNorm instances reachable
    as dataclass fields (directly or inside list/tuple/dict fields) can be
    rewritten; modules constructed inside ``setup``/``__call__`` bodies must
    instantiate SyncBatchNorm themselves.
    """
    import dataclasses

    def convert(obj):
        if isinstance(obj, nn.BatchNorm):
            if obj.use_bias != obj.use_scale:
                raise ValueError("BatchNorm with use_bias != use_scale has no "
                                 "SyncBatchNorm equivalent")
            # flax BatchNorm infers features at call time (no num_features
            # field); SyncBatchNorm does the same when num_features=None.
            # NB flax's ``momentum`` is the decay of the running stat (torch's
            # is the weight of the NEW stat), hence 1 - momentum here.
            return SyncBatchNorm(
                num_features=None, eps=obj.epsilon, momentum=1 - obj.momentum,
                affine=obj.use_scale, axis_name=axis_name,
                channel_axis=obj.axis if isinstance(obj.axis, int) else -1,
                name=obj.name)
        if isinstance(obj, nn.Module) and dataclasses.is_dataclass(obj):
            changes = {}
            for f in dataclasses.fields(obj):
                if f.name in ("name", "parent"):
                    continue
                v = getattr(obj, f.name, None)
                nv = convert_container(v)
                if nv is not v:
                    changes[f.name] = nv
            return obj.clone(**changes) if changes else obj
        return obj

    def convert_container(v):
        if isinstance(v, (nn.Module,)):
            return convert(v)
        if isinstance(v, list):
            nv = [convert_container(e) for e in v]
            return nv if any(a is not b for a, b in zip(nv, v)) else v
        if isinstance(v, tuple):
            nv = tuple(convert_container(e) for e in v)
            return nv if any(a is not b for a, b in zip(nv, v)) else v
        if isinstance(v, dict):
            nv = {k: convert_container(e) for k, e in v.items()}
            return nv if any(nv[k] is not v[k] for k in v) else v
        return v

    return convert(module)
