"""DistributedDataParallel facade.

Reference: apex/parallel/distributed.py:~200 — wraps a module, broadcasts
params rank0->all at construction, registers per-param grad hooks that bucket
grads (``message_size`` bytes), flatten them (apex_C.flatten) and allreduce on
a side stream overlapped with backward; ``delay_allreduce`` defers everything
to the end of backward.

On TPU every piece of that machinery is owned by XLA:

- *bucketing/flattening* — the SPMD partitioner emits one fused all-reduce
  per fusion group and sizes them itself;
- *overlap* — the latency-hiding scheduler interleaves grad collectives with
  remaining backward compute (the reference's side-stream trick);
- *broadcast at init* — replicated param sharding IS the broadcast.

So under ``pjit`` the wrapper only needs to (a) mark the batch as sharded over
``data`` and (b) average the loss/grads over that axis — which autodiff of a
``pmean`` loss already does. The explicit machinery survives in one place:
``allreduce_gradients`` for manual ``shard_map`` loops, the moral equivalent
of the reference's ``flat_dist_call``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax import lax

from apex_tpu.mesh import DATA_AXIS


class DistributedDataParallel:
    """API-parity wrapper over a flax module or apply-fn.

    ``DistributedDataParallel(model)(params, *args)`` calls the model;
    gradient synchronization happens in the caller's jitted step (pjit) or via
    ``allreduce_gradients`` (shard_map). Ctor kwargs of the reference
    (``message_size``, ``delay_allreduce``, ``allreduce_trigger_params``,
    ``gradient_average``, ``retain_allreduce_buffers``, ...) are accepted and
    recorded but have no TPU mechanism to drive — XLA decides bucketing and
    overlap; they exist so reference training scripts port unchanged.
    """

    def __init__(self, module, message_size: int = 10_000_000,
                 delay_allreduce: bool = False, shared_param: Optional[bool] = None,
                 allreduce_trigger_params=None, retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False, num_allreduce_streams: int = 1,
                 allreduce_communicators=None, gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_name: str = DATA_AXIS):
        self.module = module
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_predivide_factor = gradient_predivide_factor
        # recorded-only knobs (see class docstring)
        self.message_size = message_size
        self.delay_allreduce = delay_allreduce

    def __call__(self, *args, **kwargs):
        if hasattr(self.module, "apply"):
            return self.module.apply(*args, **kwargs)
        return self.module(*args, **kwargs)

    forward = __call__

    def allreduce_gradients(self, grads):
        """Average a grad pytree over the data axis (shard_map loops;
        reference: allreduce_hook/allreduce_bucket + gradient_average).

        Outside shard_map (GSPMD/pjit loops) this is the identity: the SPMD
        partitioner already psums grads produced from a batch sharded over
        ``data``, so there is nothing left to reduce — the facade stays
        callable from reference-shaped training scripts either way.
        """
        import jax.numpy as jnp

        from apex_tpu.transformer.tensor_parallel.mappings import (
            axis_is_bound)

        if not axis_is_bound(self.axis_name):
            return grads

        def red(g):
            g32 = g.astype(jnp.float32) if self.allreduce_always_fp32 else g
            if self.gradient_predivide_factor != 1.0:
                g32 = g32 / self.gradient_predivide_factor
            out = lax.psum(g32, self.axis_name)
            if self.gradient_average:
                n = lax.axis_size(self.axis_name)
                out = out / (n / self.gradient_predivide_factor)
            return out.astype(g.dtype)

        return jax.tree.map(red, grads)
