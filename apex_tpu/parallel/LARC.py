"""LARC — layer-wise adaptive rate clipping/scaling.

Reference: apex/parallel/LARC.py:~40 — wraps any optimizer; before the inner
``step()`` it rescales each param's grad by the local lr

    local_lr = trust_coefficient * ||p|| / (||g|| + weight_decay * ||p||)

clipped at the global lr (``clip=True``, LARC) or used directly
(``clip=False``, LARS-style scaling). Params with zero norm pass through.

Here the wrapper composes with the fused optimizers: per-tensor param/grad
norms come from the flat-buffer segment-norm kernel pass the optimizer
already owns (csrc/multi_tensor_l2norm analog), the grads are rescaled
per-segment, and the inner fused step runs unchanged — all inside one jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops import flat_buffer, optim_kernels
from apex_tpu.optimizers.common import FusedOptimizerBase


class LARC:
    """Wraps a FusedOptimizerBase (or any object with ``step(grads)``)."""

    def __init__(self, optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps
        self._jit_scale = None
        # reference semantics: LARC folds wd into the scaled grad
        # (p.grad += wd * p before the local-lr scale) and zeroes the inner
        # optimizer's weight_decay AROUND each step() so it isn't applied
        # twice — restored afterwards, so state_dict/defaults keep reporting
        # the user's hyperparameters and discarding the wrapper leaves the
        # optimizer unaltered
        if isinstance(optimizer, FusedOptimizerBase):
            if optimizer.wd_per_segment is not None:
                self._wd = optimizer.wd_per_segment      # (num_tensors,) fp32
            else:
                self._wd = float(optimizer.defaults.get("weight_decay", 0.0))

    # attribute passthrough (the reference forwards state/param_groups too)
    def __getattr__(self, name):
        return getattr(self.optim, name)

    def state_dict(self):
        return self.optim.state_dict()

    def load_state_dict(self, sd):
        self.optim.load_state_dict(sd)

    def _scale_grads_fused(self, grads):
        """Per-tensor trust-ratio scaling on the flat buffer (one fused pass)."""
        opt: FusedOptimizerBase = self.optim
        spec = opt.spec

        def _scale(g_tree, master, lr, wd):
            g = flat_buffer.flatten(g_tree, spec)
            # one fused pass: per-tensor ||g||^2 and ||p||^2 together
            stats = optim_kernels.segment_stats(
                g, opt.seg_rows, spec.num_tensors, b=master)
            gn = jnp.sqrt(stats[optim_kernels.STAT_SUMSQ_A, :spec.num_tensors])
            pn = jnp.sqrt(stats[optim_kernels.STAT_SUMSQ_B, :spec.num_tensors])
            adaptive = self.trust_coefficient * pn / (gn + wd * pn + self.eps)
            if self.clip:
                # reference: local_lr capped so local_lr/global_lr <= 1
                factor = jnp.minimum(adaptive / lr, 1.0)
            else:
                factor = adaptive
            factor = jnp.where((pn > 0) & (gn > 0), factor, 1.0)
            # reference LARC: grad <- local_lr * (grad + wd * p); the inner
            # optimizer then steps with weight_decay = 0 (set in __init__)
            wd_rows = (wd if jnp.ndim(wd) == 0 else wd[opt.seg_rows][:, None])
            g = (g + wd_rows * master) * factor[opt.seg_rows][:, None]
            return flat_buffer.unflatten(g, spec)

        if self._jit_scale is None:
            self._jit_scale = jax.jit(_scale)
        lr = jnp.float32(self.optim.defaults.get("lr", 1e-3))
        wd = jnp.asarray(self._wd, jnp.float32)
        return self._jit_scale(grads, self.optim.master, lr, wd)

    def step(self, grads, **kw):
        if isinstance(self.optim, FusedOptimizerBase):
            grads = self._scale_grads_fused(grads)
            # wd already folded into grads above; suppress it in the inner
            # step only, restoring the recorded hyperparameters after
            saved = (self.optim.defaults.get("weight_decay", 0.0),
                     self.optim.wd_per_segment)
            self.optim.defaults["weight_decay"] = 0.0
            self.optim.wd_per_segment = None
            try:
                return self.optim.step(grads, **kw)
            finally:
                self.optim.defaults["weight_decay"] = saved[0]
                self.optim.wd_per_segment = saved[1]
        grads = self._scale_grads_tree(grads)
        return self.optim.step(grads, **kw)

    def _scale_grads_tree(self, grads):
        raise NotImplementedError(
            "LARC requires a fused optimizer (FusedAdam/FusedSGD/...) — "
            "the reference likewise wraps a torch.optim.Optimizer")
