"""apex.fused_dense equivalent — GEMM with fused bias/GELU epilogues.

Reference: apex/fused_dense/fused_dense.py:~20-200 (``FusedDense``,
``FusedDenseGeluDense``, ``DenseNoBias`` over csrc/fused_dense_cuda.cu —
cublasLt GEMMs with bias and gelu_aux epilogues, ~800 LoC). On TPU, XLA's
epilogue fusion produces exactly these fused GEMMs from the naive
expression, including saving gelu input for backward via autodiff, so the
modules are thin; parity is the API and the gelu flavor (tanh approximation,
matching cublasLt's CUBLASLT_EPILOGUE_GELU_AUX).

Weights are torch-layout (out_features, in_features).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import resolve_compute_dtype


def _gelu(x):
    # cublasLt GELU epilogue uses the tanh approximation
    return jax.nn.gelu(x, approximate=True)


def fused_dense_function(x, weight, bias=None):
    """Reference: fused_dense_function / FusedDenseFunc.

    Consults the active amp policy (O1 analog: GEMMs compute in half)."""
    dt = resolve_compute_dtype(x.dtype)
    y = x.astype(dt) @ weight.astype(dt).T
    if bias is not None:
        y = y + bias.astype(dt)
    return y


def fused_dense_gelu_dense_function(x, weight1, bias1, weight2, bias2):
    """Reference: fused_dense_gelu_dense_function / FusedDenseGeluDenseFunc."""
    return fused_dense_function(
        _gelu(fused_dense_function(x, weight1, bias1)), weight2, bias2)


class FusedDense(nn.Module):
    """Drop-in for apex.fused_dense.FusedDense(in_features, out_features)."""

    in_features: int
    out_features: int
    bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.out_features, self.in_features), self.param_dtype)
        b = (self.param("bias", nn.initializers.zeros, (self.out_features,),
                        self.param_dtype) if self.bias else None)
        return fused_dense_function(x, w, b)

    forward = __call__


class DenseNoBias(nn.Module):
    """Drop-in for apex.fused_dense.DenseNoBias."""

    in_features: int
    out_features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.out_features, self.in_features), self.param_dtype)
        return fused_dense_function(x, w, None)

    forward = __call__


class FusedDenseGeluDense(nn.Module):
    """Drop-in for apex.fused_dense.FusedDenseGeluDense(in, intermediate, out)."""

    in_features: int
    intermediate_features: int
    out_features: int
    bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        assert self.bias, (
            "DenseGeluDense module without bias is currently not supported"
        )  # same restriction as the reference module
        w1 = self.param("weight1", nn.initializers.lecun_normal(),
                        (self.intermediate_features, self.in_features),
                        self.param_dtype)
        b1 = self.param("bias1", nn.initializers.zeros,
                        (self.intermediate_features,), self.param_dtype)
        w2 = self.param("weight2", nn.initializers.lecun_normal(),
                        (self.out_features, self.intermediate_features),
                        self.param_dtype)
        b2 = self.param("bias2", nn.initializers.zeros, (self.out_features,),
                        self.param_dtype)
        return fused_dense_gelu_dense_function(x, w1, b1, w2, b2)

    forward = __call__
