"""Reference: apex/fused_dense/__init__.py."""

from apex_tpu.fused_dense.fused_dense import (  # noqa: F401
    DenseNoBias,
    FusedDense,
    FusedDenseGeluDense,
    fused_dense_function,
    fused_dense_gelu_dense_function,
)
