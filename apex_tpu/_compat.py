"""Version-drift shims: run the package on older jax releases.

The library is written against the current jax surface (``jax.shard_map``,
``jax.typeof``, ``lax.axis_size``, ``lax.pcast``,
``pallas.tpu.CompilerParams``). Older jaxlibs (0.4.x) ship the same
functionality under earlier names — or, for the varying-manual-axes
(vma) typing, not at all, in which case the correct degradation is a
no-op (vma is a trace-time refinement; numerics are unchanged).

Each patch is gated on the attribute being ABSENT, so on a current jax
this module does nothing. Imported for its side effects from
``apex_tpu/__init__.py`` before any kernel/layer module loads.
"""

from __future__ import annotations

import jax
from jax import lax


def _install() -> None:
    if not hasattr(jax, "typeof"):
        # new-style jax.typeof(x) -> aval; .vma consumers use getattr with
        # a frozenset() default, so the missing attribute degrades cleanly
        def typeof(x):
            return getattr(x, "aval", None) or jax.core.get_aval(x)

        jax.typeof = typeof

    if not hasattr(jax, "shard_map"):
        from jax.experimental import shard_map as _sm

        def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, **kw):
            # the old spelling of check_vma is check_rep
            if check_vma is not None:
                kw.setdefault("check_rep", check_vma)
            return _sm.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)

        jax.shard_map = shard_map

    if not hasattr(lax, "axis_size"):
        def axis_size(axis_name):
            # psum of a unit literal constant-folds to the axis size and
            # raises the same NameError on an unbound axis as the real API
            # (axis_is_bound relies on that contract)
            return lax.psum(1, axis_name)

        lax.axis_size = axis_size

    if not hasattr(lax, "pcast"):
        def pcast(x, axis_name, *, to=None):
            # no vma typing on this jax: replicated->varying casts are
            # identity (shard_map check_rep handles replication checks)
            del axis_name, to
            return x

        lax.pcast = pcast

    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams"):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:  # pallas not available at all: kernels unusable
        pass


_install()
