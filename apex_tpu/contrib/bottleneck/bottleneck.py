"""Fused ResNet bottleneck + spatial-parallel variant.

Reference: apex/contrib/bottleneck/bottleneck.py over ``fast_bottleneck``
(apex/contrib/csrc/bottleneck/bottleneck.cpp — cudnn-frontend fused
conv-bias-relu chains) and ``halo_exchangers.py`` (+peer_memory/nccl_p2p)
for the spatial-parallel version that splits H across GPUs and exchanges
1-row halos around the 3x3 conv.

TPU restatement: the conv+scale+bias+relu chain is written as plain lax
convs with frozen-BN affine folded in — XLA's epilogue fusion produces the
fused kernels the cudnn-frontend graph hand-assembled. SpatialBottleneck
runs inside shard_map with H sharded over a mesh axis; the 3x3 conv's
cross-boundary rows come from ``halo_exchange_1d`` (ppermute), after which
the conv runs VALID over the haloed slab — the same dataflow as the
reference's peer-memory halo exchangers.

Like the reference module (which loads frozen weights and scale/bias from
a trained torchvision checkpoint), the BN is FROZEN: scale/bias are
parameters, not running stats.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.peer_memory import halo_exchange_1d
from apex_tpu.mesh import CONTEXT_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import axis_is_bound


def _conv(x, w, stride=1, padding=0):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class Bottleneck(nn.Module):
    """Frozen-BN bottleneck: 1x1 -> 3x3(stride) -> 1x1 + residual, NHWC.

    Ctor mirrors the reference: (in_channels, bottleneck_channels,
    out_channels, stride); ``explicit_nhwc`` accepted for parity (NHWC is
    the only layout here).
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    explicit_nhwc: bool = True
    use_cudnn: bool = True          # parity knob, no-op
    param_dtype: Any = jnp.float32

    def _scale_bias(self, name, c):
        s = self.param(f"{name}_scale", nn.initializers.ones, (c,),
                       self.param_dtype)
        b = self.param(f"{name}_bias", nn.initializers.zeros, (c,),
                       self.param_dtype)
        return s, b

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.he_normal()
        ci, cb, co = (self.in_channels, self.bottleneck_channels,
                      self.out_channels)
        w1 = self.param("conv1_weight", init, (1, 1, ci, cb),
                        self.param_dtype)
        w2 = self.param("conv2_weight", init, (3, 3, cb, cb),
                        self.param_dtype)
        w3 = self.param("conv3_weight", init, (1, 1, cb, co),
                        self.param_dtype)
        s1, b1 = self._scale_bias("bn1", cb)
        s2, b2 = self._scale_bias("bn2", cb)
        s3, b3 = self._scale_bias("bn3", co)

        y = jax.nn.relu(_conv(x, w1) * s1 + b1)
        y = self._conv3x3(y, w2)
        y = jax.nn.relu(y * s2 + b2)
        y = _conv(y, w3) * s3 + b3

        residual = x
        if ci != co or self.stride != 1:
            wd = self.param("downsample_weight", init, (1, 1, ci, co),
                            self.param_dtype)
            sd, bd = self._scale_bias("downsample_bn", co)
            residual = _conv(x, wd, stride=self.stride) * sd + bd
        return jax.nn.relu(y + residual)

    def _conv3x3(self, y, w2):
        return _conv(y, w2, stride=self.stride, padding=1)

    forward = __call__


class SpatialBottleneck(Bottleneck):
    """Bottleneck with H split over ``spatial_axis`` (reference:
    SpatialBottleneck + PeerHaloExchanger1d): the 3x3 conv exchanges
    1-row halos with the neighbor ranks via ppermute, then runs VALID over
    the haloed slab. Run inside shard_map with the axis bound; outside,
    degrades to the plain Bottleneck (reference: spatial_group_size=1).
    """

    spatial_axis: str = CONTEXT_AXIS
    halo_ex: Optional[Any] = None   # parity slot for a PeerHaloExchanger1d

    def _conv3x3(self, y, w2):
        if not axis_is_bound(self.spatial_axis):
            return _conv(y, w2, stride=self.stride, padding=1)
        haloed = halo_exchange_1d(y, 1, self.spatial_axis, spatial_dim=1)
        # height got +2 halo rows -> VALID in H, SAME(1) in W
        return lax.conv_general_dilated(
            haloed, w2, window_strides=(self.stride, self.stride),
            padding=((0, 0), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
