"""apex.contrib.bottleneck equivalent."""

from apex_tpu.contrib.bottleneck.bottleneck import (
    Bottleneck,
    SpatialBottleneck,
)

__all__ = ["Bottleneck", "SpatialBottleneck"]
