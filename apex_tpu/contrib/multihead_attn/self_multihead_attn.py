"""SelfMultiheadAttn — fused self-attention block.

Reference: apex/contrib/multihead_attn/self_multihead_attn.py:~30 (module) +
fast_self_multihead_attn_func.py / self_multihead_attn_func.py (autograd fns
over the fast_multihead_attn CUDA extension — QKV GEMM, masked
softmax+dropout, AV GEMM, out-proj, optional pre-LN+residual "norm_add").

Here ``impl='fast'`` routes the attention core through the Pallas flash
kernel (apex_tpu/ops/flash_attention.py) with in-kernel dropout;
``impl='default'`` is the unfused pure-jnp path that the reference's tests
use as ground truth. The projections are plain jnp matmuls — on TPU, XLA
fuses bias/reshape into the MXU GEMM, which is exactly what the CUDA
strided-batched-GEMM plumbing hand-built.

Layout matches the reference: inputs [seq, batch, embed_dim]; weights are
torch-layout (out_features, in_features).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.contrib.multihead_attn._core import attention_core, masks_to_bias
from apex_tpu.ops.layer_norm import layer_norm as _layer_norm


class SelfMultiheadAttn(nn.Module):
    """Drop-in for apex.contrib.multihead_attn.SelfMultiheadAttn.

    Ctor args mirror the reference; ``forward`` is ``__call__`` with the same
    signature (query==key==value for self-attention).
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    separate_qkv_params: bool = False
    mask_additive: bool = False
    impl: str = "fast"
    param_dtype: jnp.dtype = jnp.float32

    def setup(self):
        assert self.embed_dim % self.num_heads == 0, (
            "embed_dim must be divisible by num_heads")
        e = self.embed_dim
        init = nn.initializers.xavier_uniform()
        if self.separate_qkv_params:
            self.q_weight = self.param("q_weight", init, (e, e), self.param_dtype)
            self.k_weight = self.param("k_weight", init, (e, e), self.param_dtype)
            self.v_weight = self.param("v_weight", init, (e, e), self.param_dtype)
        else:
            self.qkv_weight = self.param("qkv_weight", init, (3 * e, e),
                                         self.param_dtype)
        if self.bias:
            zeros = nn.initializers.zeros
            if self.separate_qkv_params:
                self.q_bias = self.param("q_bias", zeros, (e,), self.param_dtype)
                self.k_bias = self.param("k_bias", zeros, (e,), self.param_dtype)
                self.v_bias = self.param("v_bias", zeros, (e,), self.param_dtype)
            else:
                self.qkv_bias = self.param("qkv_bias", zeros, (3 * e,),
                                           self.param_dtype)
            self.out_proj_bias = self.param("out_proj_bias", zeros, (e,),
                                            self.param_dtype)
        self.out_proj_weight = self.param("out_proj_weight", init, (e, e),
                                          self.param_dtype)
        if self.include_norm_add:
            self.lyr_nrm_gamma_weights = self.param(
                "lyr_nrm_gamma_weights", nn.initializers.ones, (e,),
                self.param_dtype)
            self.lyr_nrm_beta_weights = self.param(
                "lyr_nrm_beta_weights", nn.initializers.zeros, (e,),
                self.param_dtype)

    def __call__(self, query, key=None, value=None,
                 key_padding_mask: Optional[jax.Array] = None,
                 need_weights: bool = False,
                 attn_mask: Optional[jax.Array] = None,
                 is_training: bool = True):
        del key, value  # self-attention: the reference ignores them too
        if need_weights:
            raise NotImplementedError(
                "need_weights is unsupported by the fused path (same as the "
                "reference fast impl)")
        sq, b, e = query.shape
        h = self.num_heads
        d = e // h
        residual = query

        x = query
        if self.include_norm_add:
            x = _layer_norm(x, self.lyr_nrm_gamma_weights,
                            self.lyr_nrm_beta_weights, eps=1e-5)

        dt = resolve_compute_dtype(x.dtype)  # amp O1 seam: GEMMs in half
        x = x.astype(dt)
        if self.separate_qkv_params:
            q = x @ self.q_weight.astype(dt).T
            k = x @ self.k_weight.astype(dt).T
            v = x @ self.v_weight.astype(dt).T
            if self.bias:
                q = q + self.q_bias.astype(dt)
                k = k + self.k_bias.astype(dt)
                v = v + self.v_bias.astype(dt)
        else:
            qkv = x @ self.qkv_weight.astype(dt).T
            if self.bias:
                qkv = qkv + self.qkv_bias.astype(dt)
            q, k, v = jnp.split(qkv, 3, axis=-1)

        # [sq, b, e] -> [b, h, sq, d]
        def to_bhsd(t):
            return t.reshape(sq, b, h, d).transpose(1, 2, 0, 3)

        q, k, v = to_bhsd(q), to_bhsd(k), to_bhsd(v)
        bias_ = masks_to_bias(key_padding_mask, attn_mask, self.mask_additive)
        rate = self.dropout if is_training else 0.0
        ctx = attention_core(self, q, d, k, v, bias_, rate, self.impl)

        # [b, h, sq, d] -> [sq, b, e]
        ctx = ctx.transpose(2, 0, 1, 3).reshape(sq, b, e)
        out = ctx @ self.out_proj_weight.astype(dt).T
        if self.bias:
            out = out + self.out_proj_bias.astype(dt)
        if self.include_norm_add:
            out = out + residual
        return out, None

    # torch-style alias
    forward = __call__
