"""Reference: apex/contrib/multihead_attn/__init__.py."""

from apex_tpu.contrib.multihead_attn.self_multihead_attn import SelfMultiheadAttn  # noqa: F401
from apex_tpu.contrib.multihead_attn.encdec_multihead_attn import EncdecMultiheadAttn  # noqa: F401
