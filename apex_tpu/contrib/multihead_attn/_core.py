"""Shared attention core for the multihead_attn modules.

One implementation of (a) the reference's two-mask folding and (b) the
fast-vs-default attention dispatch, used by both SelfMultiheadAttn and
EncdecMultiheadAttn (the reference duplicates this across
fast_self_multihead_attn_func.py / fast_encdec_multihead_attn_func.py /
the impl='default' python paths; here it lives once).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention as _flash_attention

_NEG = -1e9


def masks_to_bias(key_padding_mask, attn_mask, mask_additive):
    """Fold the reference's two masks into one additive flash bias
    broadcastable to [b, 1, sq, sk].

    key_padding_mask: [b, sk] bool (True = pad) or additive float when
    ``mask_additive``; attn_mask: [sq, sk] likewise.
    """
    bias = None
    if key_padding_mask is not None:
        if mask_additive:
            pad = key_padding_mask.astype(jnp.float32)
        else:
            pad = jnp.where(key_padding_mask, _NEG, 0.0)
        bias = pad[:, None, None, :]
    if attn_mask is not None:
        if mask_additive:
            am = attn_mask.astype(jnp.float32)
        else:
            am = jnp.where(attn_mask, _NEG, 0.0)
        am = am[None, None, :, :]
        bias = am if bias is None else bias + am
    return bias


def attention_core(module, q, q_dim, k, v, bias, rate, impl):
    """softmax(q k^T / sqrt(d) + bias) v with dropout; fast = Pallas flash
    kernel (in-kernel dropout), default = unfused jnp ground truth.

    q/k/v: [b, h, s, d]; ``module`` supplies make_rng('dropout') when needed.
    """
    scale = q_dim ** -0.5
    if impl == "fast":
        seed = (jax.random.randint(module.make_rng("dropout"), (), 0,
                                   jnp.iinfo(jnp.int32).max)
                if rate > 0.0 else 0)
        return _flash_attention(q, k, v, bias=bias, scale=scale,
                                dropout_rate=rate, dropout_seed=seed)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        s = s + bias
    p = jax.nn.softmax(s, axis=-1)
    if rate > 0.0:
        keep = jax.random.bernoulli(module.make_rng("dropout"), 1.0 - rate,
                                    p.shape)
        p = p * keep / (1.0 - rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
