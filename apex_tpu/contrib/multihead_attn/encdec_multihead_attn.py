"""EncdecMultiheadAttn — fused encoder-decoder cross-attention.

Reference: apex/contrib/multihead_attn/encdec_multihead_attn.py +
fast_encdec_multihead_attn_func.py / encdec_multihead_attn_norm_add_func.py:
q projected from the decoder query, packed KV projected from the encoder
output (key is asserted identical to value, as in the reference), optional
pre-LN + residual-add on the query side.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import resolve_compute_dtype
from apex_tpu.contrib.multihead_attn._core import attention_core, masks_to_bias
from apex_tpu.ops.layer_norm import layer_norm as _layer_norm


class EncdecMultiheadAttn(nn.Module):
    """Drop-in for apex.contrib.multihead_attn.EncdecMultiheadAttn."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    param_dtype: jnp.dtype = jnp.float32

    def setup(self):
        assert self.embed_dim % self.num_heads == 0
        if self.bias:
            # matches the reference assertion: fused encdec has no bias path
            raise ValueError(
                "EncdecMultiheadAttn does not support bias (reference "
                "apex/contrib/multihead_attn/encdec_multihead_attn.py asserts "
                "the same)")
        e = self.embed_dim
        init = nn.initializers.xavier_uniform()
        self.q_weight = self.param("q_weight", init, (e, e), self.param_dtype)
        self.kv_weight = self.param("kv_weight", init, (2 * e, e),
                                    self.param_dtype)
        self.out_proj_weight = self.param("out_proj_weight", init, (e, e),
                                          self.param_dtype)
        if self.include_norm_add:
            self.lyr_nrm_gamma_weights = self.param(
                "lyr_nrm_gamma_weights", nn.initializers.ones, (e,),
                self.param_dtype)
            self.lyr_nrm_beta_weights = self.param(
                "lyr_nrm_beta_weights", nn.initializers.zeros, (e,),
                self.param_dtype)

    def __call__(self, query, key, value,
                 key_padding_mask: Optional[jax.Array] = None,
                 need_weights: bool = False,
                 attn_mask: Optional[jax.Array] = None,
                 is_training: bool = True):
        if need_weights:
            raise NotImplementedError(
                "need_weights is unsupported by the fused path")
        if value is not None and value is not key:
            # K and V are both projected from `key`; a distinct value tensor
            # would be silently ignored (reference asserts `key is value`)
            raise ValueError(
                "EncdecMultiheadAttn packs K and V from the same input; pass "
                "value=key (or None)")
        sq, b, e = query.shape
        sk = key.shape[0]
        h = self.num_heads
        d = e // h
        residual = query

        x = query
        if self.include_norm_add:
            x = _layer_norm(x, self.lyr_nrm_gamma_weights,
                            self.lyr_nrm_beta_weights, eps=1e-5)

        dt = resolve_compute_dtype(x.dtype)  # amp O1 seam: GEMMs in half
        q = x.astype(dt) @ self.q_weight.astype(dt).T
        kv = key.astype(dt) @ self.kv_weight.astype(dt).T
        k, v = jnp.split(kv, 2, axis=-1)

        q = q.reshape(sq, b, h, d).transpose(1, 2, 0, 3)
        k = k.reshape(sk, b, h, d).transpose(1, 2, 0, 3)
        v = v.reshape(sk, b, h, d).transpose(1, 2, 0, 3)

        bias_ = masks_to_bias(key_padding_mask, attn_mask, False)
        rate = self.dropout if is_training else 0.0
        ctx = attention_core(self, q, d, k, v, bias_, rate, self.impl)

        ctx = ctx.transpose(2, 0, 1, 3).reshape(sq, b, e)
        out = ctx @ self.out_proj_weight.astype(dt).T
        if self.include_norm_add:
            out = out + residual
        return out, None

    forward = __call__
