"""FastLayerNorm — drop-in for apex.contrib.layer_norm.FastLayerNorm.

Reference: apex/contrib/layer_norm/layer_norm.py (``FastLayerNorm(hidden_
size, eps)`` over the hand-tuned ``fast_layer_norm`` kernels,
apex/contrib/csrc/layer_norm/ln_kernel_traits.h — per-hidden-size configs
768..12288). The TPU build has one autotiled Pallas LN kernel
(apex_tpu/ops/layer_norm.py) serving both LN extensions, so FastLayerNorm
subclasses FusedLayerNorm and only enforces the reference's supported-size
check surface (relaxed: any lane-friendly size works here — enforcing the
GPU list would be gratuitous).
"""

from __future__ import annotations

from apex_tpu.normalization import FusedLayerNorm


class FastLayerNorm(FusedLayerNorm):
    """Same kernel as FusedLayerNorm; reference-named API."""

    # the reference's ctor is (hidden_size, eps=1e-5); FusedLayerNorm's
    # first field is normalized_shape with eps defaulting to 1e-5 — aligned.
