"""apex.contrib.layer_norm equivalent (MLPerf FastLayerNorm).

Reference: apex/contrib/layer_norm/layer_norm.py — ``FastLayerNorm``, a
faster LN for enumerated hidden sizes (768..12288) over
apex/contrib/csrc/layer_norm/. SURVEY.md §2.2: ONE Pallas LN kernel
replaces both LN extensions, so this is an API shim over FusedLayerNorm.
"""

from apex_tpu.contrib.layer_norm.layer_norm import FastLayerNorm

__all__ = ["FastLayerNorm"]
