"""apex.contrib.optimizers equivalents (reference:
apex/contrib/optimizers/ — DistributedFusedAdam, DistributedFusedLAMB, plus
legacy FP16_Optimizer/FusedSGD re-exports)."""

from apex_tpu.contrib.optimizers.distributed import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
    DistributedFusedOptimizerBase,
)
# legacy aliases the reference keeps in contrib.optimizers
from apex_tpu.fp16_utils import FP16_Optimizer  # noqa: F401
from apex_tpu.optimizers import FusedSGD  # noqa: F401

__all__ = [
    "DistributedFusedAdam",
    "DistributedFusedLAMB",
    "DistributedFusedOptimizerBase",
    "FP16_Optimizer",
    "FusedSGD",
]
