"""ZeRO-style distributed fused optimizers.

Reference: apex/contrib/optimizers/distributed_fused_adam.py (~3k LoC:
bucketed reduce-scatter of grads during backward, per-rank optimizer-state
shards, `_pipeline_block_reductions` / `_pipeline_step` overlap, param
all-gather) and distributed_fused_lamb.py (~1.5k, the MLPerf-BERT optimizer:
two-stage LAMB kernels with an allreduce of per-tensor norms between stages,
``clip_after_ar``).

TPU restatement: the flat ``(rows, LANE)`` fp32 buffer (flat_buffer.py) is
row-sharded over the ``data`` mesh axis — each rank owns ``rows/dp``
contiguous rows of master params and optimizer state. One step is

    grads -> flatten -> ``psum_scatter`` (the bucketed reduce-scatter)
          -> fused Pallas update on the LOCAL shard
          -> ``all_gather`` of the updated master rows -> unflatten.

The reference's hand-rolled comm/compute overlap (_pipeline_block_reductions
round-robining NCCL groups) is not re-implemented: XLA's latency-hiding
scheduler overlaps the reduce-scatter/all-gather with neighboring compute,
which is the TPU-native form of the same optimization. LAMB's cross-rank
norm agreement maps to ``stats_psum_axis`` between the two kernel phases,
and ``clip_after_ar`` clips on the globally-reduced grad norm (psum of
per-shard partial sumsq) exactly like the reference.

Two call surfaces:

- ``step(grads)`` — facade parity with FusedAdam/FusedLAMB: runs its own
  ``shard_map`` over the mesh; state stays physically sharded between steps.
- ``shard_step(g_local, shard_state)`` — functional form for use INSIDE an
  existing ``shard_map`` training step where each rank holds its own
  (different) local grads; this is the true ZeRO data path.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import mesh as mesh_lib
from apex_tpu.mesh import DATA_AXIS
from apex_tpu.ops import flat_buffer, optim_kernels
from apex_tpu.ops.flat_buffer import LANE, FlatSpec, build_spec
from apex_tpu.optimizers.common import path_name


def _round_up(n: int, k: int) -> int:
    return -(-n // k) * k


class DistributedFusedOptimizerBase:
    """Row-sharded flat-buffer optimizer state over the ``data`` mesh axis."""

    STATE_BUFFERS: tuple = ()

    def __init__(self, params, defaults: dict, *,
                 mesh=None, dp_axis: str = DATA_AXIS,
                 average_grads: bool = True,
                 exclude_from_weight_decay: Optional[Callable[[str], bool]] = None):
        self.mesh = mesh if mesh is not None else mesh_lib.get_global_mesh()
        self.dp_axis = dp_axis
        self.dp = int(self.mesh.shape[dp_axis])
        self.average_grads = average_grads
        self.defaults = dict(defaults)

        self.spec: FlatSpec = build_spec(params)
        self.padded_rows = _round_up(self.spec.total_rows, self.dp)
        self.shard_rows = self.padded_rows // self.dp
        # padding rows get a dummy segment (num_tensors) so their (zero)
        # contributions never land in a real tensor's stats slot
        seg = np.full(self.padded_rows, self.spec.num_tensors, np.int32)
        seg[: self.spec.total_rows] = self.spec.segment_rows()
        self._seg_global = jnp.asarray(seg)
        self.num_segments = self.spec.num_tensors + (
            1 if self.padded_rows > self.spec.total_rows else 0)

        wd = float(self.defaults.get("weight_decay", 0.0))
        if exclude_from_weight_decay is not None:
            paths, _ = jax.tree_util.tree_flatten_with_path(params)
            wd_list = [0.0 if exclude_from_weight_decay(path_name(p)) else wd
                       for p, _ in paths]
        else:
            wd_list = [wd] * self.spec.num_tensors
        if self.num_segments > self.spec.num_tensors:
            wd_list = wd_list + [0.0]
        self.wd_per_segment = jnp.asarray(wd_list, jnp.float32)

        # physically row-sharded master + state (ZeRO partitioning)
        shard = NamedSharding(self.mesh, P(dp_axis, None))
        full = flat_buffer.flatten(params, self.spec)
        pad = self.padded_rows - self.spec.total_rows
        if pad:
            full = jnp.concatenate([full, jnp.zeros((pad, LANE), jnp.float32)])
        self.master = jax.device_put(full, shard)
        self.state = {
            name: jax.device_put(
                jnp.zeros((self.padded_rows, LANE), jnp.float32), shard)
            for name in self.STATE_BUFFERS
        }
        self.step_count = jnp.zeros((), jnp.int32)
        self._amp_scaler = None
        self._out_dtypes = None
        self._jit_step = None

    # -- torch-API parity shims ----------------------------------------------
    def zero_grad(self, set_to_none: bool = True):
        """No-op (JAX grads are values)."""

    @property
    def param_groups(self):
        return [dict(self.defaults, params=None)]

    def attach_amp_scaler(self, scaler) -> None:
        self._amp_scaler = scaler
        self._jit_step = None

    def set_output_dtypes(self, dtypes) -> None:
        self._out_dtypes = list(dtypes)
        self._jit_step = None

    def state_dict(self):
        return {"master": self.master, "state": dict(self.state),
                "step": self.step_count, "defaults": dict(self.defaults)}

    def load_state_dict(self, sd):
        shard = NamedSharding(self.mesh, P(self.dp_axis, None))
        self.master = jax.device_put(jnp.asarray(sd["master"]), shard)
        self.state = {k: jax.device_put(jnp.asarray(v), shard)
                      for k, v in sd["state"].items()}
        self.step_count = jnp.asarray(sd["step"])
        self.defaults.update(sd.get("defaults", {}))

    # -- core ----------------------------------------------------------------
    def _shard_update(self, g_shard, master_shard, state_shard, step, hyper,
                      seg_local, gnorm, finite):
        """Update THIS rank's rows. Implemented by subclasses."""
        raise NotImplementedError

    def _seg_local(self):
        """Local slice of the row->segment map (traced rank index)."""
        r = lax.axis_index(self.dp_axis)
        return lax.dynamic_slice_in_dim(
            self._seg_global, r * self.shard_rows, self.shard_rows)

    def shard_step(self, g_tree, master_shard, state_shard, step, *,
                   grad_scale=None, noop=None, scaler_state=None):
        """One distributed step, called INSIDE shard_map (``dp_axis`` bound).

        ``g_tree``: this rank's (unreduced) grad pytree — param shapes.
        Returns ``(params_full, new_master_shard, new_state_shard, new_step,
        new_scaler_state)``; params are all-gathered (replicated over dp).
        """
        spec = self.spec
        g_flat = flat_buffer.flatten(g_tree, spec)
        pad = self.padded_rows - spec.total_rows
        if pad:
            g_flat = jnp.concatenate(
                [g_flat, jnp.zeros((pad, LANE), jnp.float32)])
        # the ZeRO reduce-scatter (reference: _pipeline_block_reductions)
        g_shard = lax.psum_scatter(g_flat, self.dp_axis,
                                   scatter_dimension=0, tiled=True)
        if self.average_grads:
            g_shard = g_shard / self.dp

        seg_local = self._seg_local()
        # post-reduction global grad norm + found-inf, agreed across ranks
        # (reference: clip_after_ar + the distributed noop_flag allreduce)
        stats = optim_kernels.segment_stats(g_shard, seg_local,
                                            self.num_segments)
        stats = lax.psum(stats, self.dp_axis)
        gnorm = jnp.sqrt(jnp.sum(stats[optim_kernels.STAT_SUMSQ_A]))
        finite = jnp.sum(stats[optim_kernels.STAT_NONFINITE]) == 0.0

        gs = jnp.float32(1.0) if grad_scale is None else jnp.asarray(
            grad_scale, jnp.float32)
        noop_ = jnp.zeros((), jnp.float32) if noop is None else jnp.asarray(
            noop, jnp.float32)
        scaler = self._amp_scaler
        if scaler is not None and scaler_state is not None:
            found_inf = 1.0 - finite.astype(jnp.float32)
            gs = gs / scaler_state.scale
            noop_ = jnp.maximum(noop_, found_inf)
            scaler_state = scaler.update(scaler_state, found_inf)
        else:
            noop_ = jnp.maximum(noop_, 1.0 - finite.astype(jnp.float32))

        hyper = {k: jnp.asarray(v, jnp.float32)
                 for k, v in self.defaults.items()
                 if isinstance(v, (int, float))}
        hyper["grad_scale"] = gs
        hyper["noop"] = noop_
        new_step = step + jnp.where(noop_ > 0.0, 0, 1).astype(step.dtype)

        new_master, new_state = self._shard_update(
            g_shard, master_shard, state_shard, new_step, hyper, seg_local,
            gnorm * gs, finite)

        # param all-gather (reference: _pipeline_step's allgather of params)
        full = lax.all_gather(new_master, self.dp_axis, axis=0, tiled=True)
        if pad:
            full = full[: spec.total_rows]
        params = flat_buffer.unflatten(full, spec, dtypes=self._out_dtypes)
        return params, new_master, new_state, new_step, scaler_state

    def step(self, grads, grad_scale=None, noop=None):
        """Facade step (outside shard_map): grads may be replicated or
        dp-sharded; state stays physically row-sharded between calls."""
        gdef = jax.tree.structure(grads)
        if gdef != self.spec.treedef:
            raise ValueError(
                f"grad pytree structure {gdef} does not match the parameter "
                f"structure this optimizer was built with ({self.spec.treedef})")
        if getattr(self, "_amp_require_noop", False) and noop is None:
            raise RuntimeError(
                "this optimizer was initialized by amp with multiple "
                "dynamically-scaled losses: combine grads with "
                "amp.unscale_and_combine and call "
                "step(grads, noop=noop)")
        if self._jit_step is None:
            def _pure(g_tree, master, state, step, gs, noop_, sstate):
                def body(g_tree, master_s, state_s, step, gs, noop_, sstate):
                    return self.shard_step(
                        g_tree, master_s, state_s, step,
                        grad_scale=gs, noop=noop_, scaler_state=sstate)

                row_shard = P(self.dp_axis, None)
                state_specs = {k: row_shard for k in state}
                sstate_spec = None if sstate is None else jax.tree.map(
                    lambda _: P(), sstate)
                return jax.shard_map(
                    body, mesh=self.mesh,
                    in_specs=(P(), row_shard, state_specs, P(), P(), P(),
                              sstate_spec),
                    out_specs=(P(), row_shard, state_specs, P(), sstate_spec),
                    check_vma=False,
                )(g_tree, master, state, step, gs, noop_, sstate)

            self._jit_step = jax.jit(_pure, donate_argnums=(1, 2))

        gs = jnp.asarray(1.0 if grad_scale is None else grad_scale, jnp.float32)
        noop_ = jnp.asarray(0.0 if noop is None else noop, jnp.float32)
        sstate = self._amp_scaler.state if self._amp_scaler is not None else None
        params, self.master, self.state, self.step_count, sstate = \
            self._jit_step(grads, self.master, self.state, self.step_count,
                           gs, noop_, sstate)
        if self._amp_scaler is not None:
            self._amp_scaler.state = sstate
        return params


class DistributedFusedAdam(DistributedFusedOptimizerBase):
    """Reference: apex/contrib/optimizers/distributed_fused_adam.py —
    FusedAdam with ZeRO state sharding over the data-parallel ranks."""

    STATE_BUFFERS = ("m", "v")

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, max_grad_norm=0.0, amsgrad=False,
                 mesh=None, dp_axis: str = DATA_AXIS, average_grads=True,
                 exclude_from_weight_decay=None, **unused_reference_knobs):
        if amsgrad:
            raise RuntimeError(
                "DistributedFusedAdam does not support AMSGrad.")
        defaults = dict(lr=lr, beta1=betas[0], beta2=betas[1], eps=eps,
                        weight_decay=weight_decay, max_grad_norm=max_grad_norm)
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        super().__init__(params, defaults, mesh=mesh, dp_axis=dp_axis,
                         average_grads=average_grads,
                         exclude_from_weight_decay=exclude_from_weight_decay)

    def _shard_update(self, g_shard, master_shard, state_shard, step, hyper,
                      seg_local, gnorm, finite):
        max_norm = hyper.get("max_grad_norm", jnp.float32(0.0))
        clip = jnp.where((max_norm > 0.0) & (gnorm > max_norm),
                         max_norm / gnorm, jnp.float32(1.0))
        p, m, v = optim_kernels.adam_update(
            g_shard, master_shard, state_shard["m"], state_shard["v"],
            beta1=hyper["beta1"], beta2=hyper["beta2"], eps=hyper["eps"],
            weight_decay=self.wd_per_segment, lr=hyper["lr"], step=step,
            grad_scale=hyper["grad_scale"] * clip, noop=hyper["noop"],
            adam_w_mode=self.adam_w_mode, bias_correction=self.bias_correction,
            seg_rows=seg_local, num_segments=self.num_segments)
        return p, dict(m=m, v=v)


class DistributedFusedLAMB(DistributedFusedOptimizerBase):
    """Reference: apex/contrib/optimizers/distributed_fused_lamb.py — the
    MLPerf-BERT LAMB: sharded state, per-tensor trust-ratio norms allreduced
    between the two kernel stages, ``clip_after_ar``."""

    STATE_BUFFERS = ("m", "v")

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 max_grad_norm=1.0, adam_w_mode=True, grad_averaging=True,
                 use_nvlamb=False, clip_after_ar=True,
                 mesh=None, dp_axis: str = DATA_AXIS, average_grads=True,
                 exclude_from_weight_decay=None, **unused_reference_knobs):
        if not adam_w_mode:
            raise NotImplementedError(
                "DistributedFusedLAMB: only adam_w_mode=True (reference default).")
        if not clip_after_ar:
            raise NotImplementedError(
                "clip_before_ar (clip_after_ar=False) is not implemented: on "
                "TPU the reduce-scatter and the norm are one fused program, "
                "so pre-reduction clipping has no latency to hide.")
        defaults = dict(lr=lr, beta1=betas[0], beta2=betas[1], eps=eps,
                        weight_decay=weight_decay, max_grad_norm=max_grad_norm)
        self.bias_correction = bias_correction
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        super().__init__(params, defaults, mesh=mesh, dp_axis=dp_axis,
                         average_grads=average_grads,
                         exclude_from_weight_decay=exclude_from_weight_decay)

    def _shard_update(self, g_shard, master_shard, state_shard, step, hyper,
                      seg_local, gnorm, finite):
        max_norm = hyper["max_grad_norm"]
        clip = jnp.where((max_norm > 0.0) & (gnorm > max_norm),
                         max_norm / gnorm, jnp.float32(1.0))
        p, m, v = optim_kernels.lamb_update(
            g_shard, master_shard, state_shard["m"], state_shard["v"],
            seg_local, self.num_segments,
            beta1=hyper["beta1"], beta2=hyper["beta2"], eps=hyper["eps"],
            weight_decay=self.wd_per_segment, lr=hyper["lr"], step=step,
            grad_scale=hyper["grad_scale"] * clip, noop=hyper["noop"],
            bias_correction=self.bias_correction,
            grad_averaging=self.grad_averaging, use_nvlamb=self.use_nvlamb,
            stats_psum_axis=self.dp_axis)
        return p, dict(m=m, v=v)
