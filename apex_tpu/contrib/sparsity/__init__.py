"""apex.contrib.sparsity equivalent (ASP 2:4 structured sparsity).

Reference: apex/contrib/sparsity/ — asp.py, sparse_masklib.py,
permutation_lib.py (+ CUDA permutation_search_kernels, here a jitted
search). TPUs have no 2:4 sparse math units; this is the accuracy-workflow
emulation SURVEY.md §7 M10 prescribes.
"""

from apex_tpu.contrib.sparsity.asp import ASP
from apex_tpu.contrib.sparsity.sparse_masklib import (
    create_mask,
    magnitude_retained,
    mn_1d_mask,
)
from apex_tpu.contrib.sparsity.permutation_lib import (
    apply_permutation_and_mask,
    search_permutation,
)

__all__ = ["ASP", "create_mask", "mn_1d_mask", "magnitude_retained",
           "search_permutation", "apply_permutation_and_mask"]
