"""Channel-permutation search for 2:4 sparsity.

Reference: apex/contrib/sparsity/permutation_lib.py +
permutation_search_kernels/ (CUDA kernels scoring channel permutations) —
permuting a weight's INPUT channels before masking can keep more magnitude
under the 2:4 constraint (the permutation is then folded into the previous
layer, so the network function is unchanged).

TPU restatement: a jitted greedy pair-swap search. Each sweep evaluates ALL
O(C^2) adjacent-group column swaps in parallel (the objective is separable
over groups of 4 columns, so a swap's delta only touches two groups —
vectorized as a [C, C] delta matrix built from per-group retained-magnitude
tables, matmul-heavy and MXU-friendly), applies the best swap, and repeats
for a fixed number of sweeps under ``lax.while_loop``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.sparsity.sparse_masklib import mn_1d_mask


def _retained_per_group(w_abs: jax.Array) -> jax.Array:
    """Sum of the top-2 |values| of each group of 4 columns: [rows, C/4] ->
    summed over rows -> [C/4]."""
    g = w_abs.reshape(w_abs.shape[0], -1, 4)
    top2 = jnp.sort(g, axis=-1)[..., 2:]
    return top2.sum(axis=(0, 2))


def _score(w_abs: jax.Array) -> jax.Array:
    return _retained_per_group(w_abs).sum()


@functools.partial(jax.jit, static_argnames=("max_swaps",))
def search_permutation(w: jax.Array, max_swaps: int = 64):
    """Greedy column-swap search maximizing 2:4 retained magnitude.

    ``w``: (rows, C) with C % 4 == 0. Returns (perm [C], score) such that
    ``w[:, perm]`` retains at least as much magnitude as ``w`` under the
    m4n2_1d mask (monotone improvement; stops early when no swap helps).
    """
    rows, c = w.shape
    w_abs0 = jnp.abs(w)

    def swap_delta_matrix(w_abs):
        """delta[i, j] = score gain from swapping columns i and j."""
        base = _retained_per_group(w_abs)  # [G]
        gid = jnp.arange(c) // 4

        # candidate score of group g with column slot s replaced by column j:
        # build for all (slot, j) pairs — [C, C] table where entry (i, j) is
        # the retained sum of i's group after i <- j's values
        def group_with_replacement(i, j):
            g = gid[i]
            cols = lax.dynamic_slice_in_dim(w_abs, g * 4, 4, axis=1)
            slot = i % 4
            cols = lax.dynamic_update_slice_in_dim(
                cols, w_abs[:, j][:, None], slot, axis=1)
            top2 = jnp.sort(cols, axis=-1)[..., 2:]
            return top2.sum()

        idx = jnp.arange(c)
        repl = jax.vmap(lambda i: jax.vmap(
            lambda j: group_with_replacement(i, j))(idx))(idx)  # [C, C]
        same_group = gid[:, None] == gid[None, :]
        delta = (repl + repl.T
                 - base[gid][:, None] - base[gid][None, :])
        return jnp.where(same_group, -jnp.inf, delta)

    def cond(state):
        _, _, improved, it = state
        return improved & (it < max_swaps)

    def body(state):
        perm, w_abs, _, it = state
        delta = swap_delta_matrix(w_abs)
        flat = jnp.argmax(delta)
        i, j = flat // c, flat % c
        gain = delta[i, j]
        do = gain > 1e-7

        def apply_swap(args):
            perm, w_abs = args
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
            ci, cj = w_abs[:, i], w_abs[:, j]
            w_abs = w_abs.at[:, i].set(cj).at[:, j].set(ci)
            return perm, w_abs

        perm, w_abs = lax.cond(do, apply_swap, lambda a: a, (perm, w_abs))
        return perm, w_abs, do, it + 1

    perm0 = jnp.arange(c)
    perm, w_abs, _, _ = lax.while_loop(
        cond, body, (perm0, w_abs0, jnp.bool_(True), jnp.int32(0)))
    return perm, _score(w_abs)


def apply_permutation_and_mask(w: jax.Array, perm: jax.Array):
    """Permute input channels, mask 2:4, un-permute — the network-function-
    preserving use (the reference folds the permutation into the upstream
    layer instead; un-permuting keeps this a drop-in weight transform)."""
    wp = w[:, perm]
    mask_p = mn_1d_mask(wp)
    inv = jnp.argsort(perm)
    return mask_p[:, inv]
