"""2:4 (n:m) structured-sparsity mask computation.

Reference: apex/contrib/sparsity/sparse_masklib.py — ``create_mask(tensor,
pattern)`` with patterns like "m4n2_1d" (keep the 2 largest magnitudes of
every 4 consecutive elements along the input dim) and "m4n2_2d_best".

TPU note (SURVEY.md §7 M10): TPUs have no 2:4 sparse math units, so masks
are an accuracy-workflow emulation — the masked weights train/evaluate
exactly like on GPU, but there is no 2x math speedup to harvest. The mask
math itself is vectorized jnp (sort-free top-k by pairwise comparison) and
jit-friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _mask_1d_groups(flat: jax.Array, m: int, n: int) -> jax.Array:
    """Keep the ``n`` largest |values| of every ``m`` consecutive elements.

    flat: (..., k*m) -> bool mask, same shape. Ties break toward the
    earlier element (stable argsort), matching the reference's topk.
    """
    groups = flat.reshape(*flat.shape[:-1], -1, m)
    mag = jnp.abs(groups)
    # rank[i] = how many elements strictly beat element i (ties: earlier
    # index wins) — rank < n <=> kept
    gt = (mag[..., None, :] > mag[..., :, None])
    eq = (mag[..., None, :] == mag[..., :, None])
    idx = jnp.arange(m)
    earlier = idx[None, :] < idx[:, None]
    rank = (gt | (eq & earlier)).sum(-1)
    keep = rank < n
    return keep.reshape(flat.shape)


def mn_1d_mask(t: jax.Array, m: int = 4, n: int = 2) -> jax.Array:
    """Pattern "m4n2_1d": groups along the LAST dim (the input/contraction
    dim of a torch-layout (out, in) weight)."""
    if t.shape[-1] % m != 0:
        raise ValueError(
            f"last dim {t.shape[-1]} not divisible by m={m} "
            "(reference: tensors must be padded or excluded)")
    return _mask_1d_groups(t, m, n)


def create_mask(t: jax.Array, pattern: str = "m4n2_1d") -> jax.Array:
    """bool mask with ``pattern`` sparsity (reference: create_mask).

    Supported: "m4n2_1d" (the reference default for linears — its 2d
    patterns exist only to feed the GPU sparse-MMA layout, which has no TPU
    analog; SURVEY.md §7 M10 scopes ASP as accuracy-workflow emulation).
    """
    if pattern in ("m4n2_1d", "m4n2_1d_best"):
        return mn_1d_mask(t, 4, 2)
    raise ValueError(f"unsupported sparsity pattern {pattern!r} "
                     "(supported: m4n2_1d)")


def magnitude_retained(t: jax.Array, mask: jax.Array) -> jax.Array:
    """Fraction of total |weight| magnitude the mask keeps (the permutation
    search's objective)."""
    a = jnp.abs(t)
    return jnp.sum(a * mask) / jnp.maximum(jnp.sum(a), 1e-30)
