"""ASP — automatic sparsity (2:4 structured) workflow.

Reference: apex/contrib/sparsity/asp.py:~50-300 — the ASP class:
``init_model_for_pruning`` walks the model and registers mask buffers for
prunable weights; ``init_optimizer_for_pruning`` monkey-patches
``optimizer.step`` so weights (and grads) are re-masked around every step;
``compute_sparse_masks`` fills the masks (magnitude 2:4, optional channel
permutation); ``prune_trained_model`` = all three for the
train → prune → fine-tune recipe.

TPU restatement over parameter PYTREES: masks are a pytree mirroring the
prunable leaves; the optimizer hook wraps ``FusedOptimizerBase.step`` (any
object with ``step(grads)``) to mask grads going in and params coming out —
one fused elementwise multiply each way, jitted. Conv weights are handled
like linears along their input dim (reference's default whitelist is
Linear/Conv2d with dims divisible by the pattern size).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity import sparse_masklib
from apex_tpu.contrib.sparsity.permutation_lib import (
    apply_permutation_and_mask,
    search_permutation,
)
from apex_tpu.optimizers.common import path_name


def _default_prunable(name: str, leaf) -> bool:
    """Reference whitelist analog: 2d+ weights with both in/out dims
    divisible by 4 (torch.nn.Linear/Conv weights), skipping embeddings,
    norms and biases by name."""
    if leaf.ndim < 2:
        return False
    n = name.lower()
    if any(t in n for t in ("emb", "norm", "bias", "bn")):
        return False
    return leaf.shape[-1] % 4 == 0


class ASP:
    """Drop-in for apex.contrib.sparsity.ASP (classmethod API preserved)."""

    __model_params = None          # prunable-leaf predicate results
    __masks = None                 # pytree: bool mask or None per leaf
    __pattern = "m4n2_1d"
    __allow_recompute = False
    __allow_permutation = False
    __calculate_verbosity = 0
    __optimizer = None
    __orig_step = None

    # -- reference API --------------------------------------------------------
    @classmethod
    def init_model_for_pruning(cls, params, mask_calculator: str = "m4n2_1d",
                               verbosity: int = 3,
                               whitelist=None,
                               allowed_layer_names=None,
                               disallowed_layer_names=(),
                               allow_recompute_mask: bool = False,
                               custom_layer_dict=None,
                               allow_permutation: bool = False,
                               prunable: Optional[Callable] = None):
        """Register (all-ones) masks for every prunable leaf of ``params``.

        ``prunable(name, leaf) -> bool`` overrides the default whitelist;
        ``disallowed_layer_names`` are substrings excluded by name
        (reference semantics). Returns the mask pytree.
        """
        pred = prunable or _default_prunable

        def mk(path, leaf):
            name = path_name(path)
            if any(d in name for d in disallowed_layer_names):
                return None
            if allowed_layer_names is not None and not any(
                    a in name for a in allowed_layer_names):
                return None
            if not pred(name, leaf):
                return None
            return jnp.ones(leaf.shape, jnp.bool_)

        cls.__masks = jax.tree_util.tree_map_with_path(mk, params)
        cls.__pattern = mask_calculator
        cls.__allow_recompute = allow_recompute_mask
        cls.__allow_permutation = allow_permutation
        cls.__calculate_verbosity = verbosity
        return cls.__masks

    @classmethod
    def init_optimizer_for_pruning(cls, optimizer):
        """Wrap ``optimizer.step`` to mask grads in and params out
        (reference: monkey-patched ``__optimizer_step`` masking weights and
        grads around the inner step)."""
        if cls.__optimizer is not None:
            raise RuntimeError(
                "ASP.init_optimizer_for_pruning called twice (reference "
                "raises the same)")
        cls.__optimizer = optimizer
        cls.__orig_step = optimizer.step

        def masked_step(grads, *a, **kw):
            grads = cls.apply_masks(grads)
            params = cls.__orig_step(grads, *a, **kw)
            return cls.apply_masks(params)

        optimizer.step = masked_step
        return optimizer

    @classmethod
    def compute_sparse_masks(cls, params):
        """Fill the registered masks from current magnitudes; returns
        (masks, masked_params)."""
        if cls.__masks is None:
            raise RuntimeError("call init_model_for_pruning first")

        def calc(mask, leaf):
            if mask is None:
                return None
            flat2d = leaf.reshape(-1, leaf.shape[-1])
            if cls.__allow_permutation:
                perm, _ = search_permutation(jnp.abs(flat2d))
                m = apply_permutation_and_mask(flat2d, perm)
            else:
                m = sparse_masklib.create_mask(flat2d, cls.__pattern)
            return m.reshape(leaf.shape)

        cls.__masks = jax.tree.map(calc, cls.__masks, params,
                                   is_leaf=lambda x: x is None)
        return cls.__masks, cls.apply_masks(params)

    @classmethod
    def prune_trained_model(cls, params, optimizer):
        """The one-call recipe (reference: prune_trained_model)."""
        cls.init_model_for_pruning(params)
        cls.init_optimizer_for_pruning(optimizer)
        _, masked = cls.compute_sparse_masks(params)
        return masked, optimizer

    @classmethod
    def is_sparsity_enabled(cls) -> bool:
        return cls.__masks is not None

    @classmethod
    def restore_pruned_weights(cls, params):
        """Reference: restore_pruned_weights — drop masks (weights were
        never destroyed here: masking is applied functionally)."""
        cls.reset()
        return params

    # -- helpers --------------------------------------------------------------
    @classmethod
    def masks(cls):
        return cls.__masks

    @classmethod
    def apply_masks(cls, tree):
        """Elementwise mask of a param/grad pytree (None-masked leaves pass
        through untouched)."""

        def mul(mask, leaf):
            if mask is None:
                return leaf
            return leaf * mask.astype(leaf.dtype)

        return jax.tree.map(mul, cls.__masks, tree,
                            is_leaf=lambda x: x is None)

    @classmethod
    def state_dict(cls):
        """Mask buffers are checkpointable (reference saves them as
        registered buffers)."""
        return {"masks": cls.__masks, "pattern": cls.__pattern}

    @classmethod
    def load_state_dict(cls, sd):
        cls.__masks = sd["masks"]
        cls.__pattern = sd.get("pattern", "m4n2_1d")

    @classmethod
    def reset(cls):
        if cls.__optimizer is not None and cls.__orig_step is not None:
            cls.__optimizer.step = cls.__orig_step
        cls.__masks = None
        cls.__optimizer = None
        cls.__orig_step = None
