"""Fused focal loss (detection).

Reference: apex/contrib/csrc/focal_loss/focal_loss_cuda.cu (~350 LoC) +
apex/contrib/focal_loss/focal_loss.py — sigmoid focal loss over one-hot
targets for RetinaNet-style detection, fused fwd+bwd with a
``num_positives_normalizer``. On TPU the whole expression XLA-fuses from
the jnp formulation (SURVEY.md §2.2 row: "jnp one-liner with custom_vjp if
needed" — autodiff's backward matches the hand-written one, so no
custom_vjp is needed).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def focal_loss(logits, targets, num_classes: int, alpha: float = 0.25,
               gamma: float = 2.0, label_smoothing: float = 0.0,
               num_positives_normalizer=None):
    """Sigmoid focal loss summed over classes, per anchor.

    ``logits``: [..., num_classes]; ``targets``: [...] int class ids where
    0 = background (one-hot over classes 1..C, matching the reference's
    ``cls_output``/``cls_targets_at_level`` convention: class c maps to
    column c-1, background contributes only the (1-alpha) negative term).
    Returns the scalar sum divided by ``num_positives_normalizer`` when
    given (the reference divides by the positive count on the caller side).
    """
    t32 = jax.nn.one_hot(targets - 1, num_classes, dtype=jnp.float32)
    x = logits.astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    if label_smoothing > 0.0:
        t32 = t32 * (1.0 - label_smoothing) + 0.5 * label_smoothing
    # standard stable BCE-with-logits
    bce = jnp.maximum(x, 0) - x * t32 + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * t32 + (1.0 - p) * (1.0 - t32)
    alpha_t = alpha * t32 + (1.0 - alpha) * (1.0 - t32)
    loss = alpha_t * ((1.0 - p_t) ** gamma) * bce
    total = jnp.sum(loss)
    if num_positives_normalizer is not None:
        total = total / jnp.maximum(num_positives_normalizer, 1.0)
    return total


class FocalLoss:
    """Callable-object facade (reference exposes an autograd Function)."""

    def __init__(self, num_classes: int, alpha: float = 0.25,
                 gamma: float = 2.0, label_smoothing: float = 0.0):
        self.num_classes = num_classes
        self.alpha = alpha
        self.gamma = gamma
        self.label_smoothing = label_smoothing

    def __call__(self, logits, targets, num_positives_normalizer=None):
        return focal_loss(logits, targets, self.num_classes, self.alpha,
                          self.gamma, self.label_smoothing,
                          num_positives_normalizer)
