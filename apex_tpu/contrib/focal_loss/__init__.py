"""apex.contrib.focal_loss equivalent."""

from apex_tpu.contrib.focal_loss.focal_loss import (
    focal_loss,
    FocalLoss,
)

__all__ = ["focal_loss", "FocalLoss"]
