"""index_mul_2d — fused gather-multiply(-scatter).

Reference: apex/contrib/csrc/index_mul_2d/index_mul_2d_cuda.cu (~350 LoC) +
apex/contrib/index_mul_2d/index_mul_2d.py: ``out[i] = in1[i] * in2[idx[i]]``
for 2d tensors, fwd+bwd fused (fp16/fp32), used by OpenFold. On TPU the
gather and the multiply fuse in XLA from the jnp expression; autodiff emits
the same scatter-add backward the CUDA bwd hand-writes.
"""

from __future__ import annotations

import jax.numpy as jnp


def index_mul_2d(in1, in2, idx1):
    """``in1[i, :] * in2[idx1[i], :]`` — the reference's signature
    ``index_mul_2d(in1, in2, idx1)`` (in1 pre-gathered, in2 indexed)."""
    return in1 * jnp.take(in2, idx1, axis=0)
