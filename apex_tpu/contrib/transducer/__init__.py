"""apex.contrib.transducer equivalent (RNN-T joint + loss)."""

from apex_tpu.contrib.transducer.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_loss,
)

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_loss"]
