"""RNN-T transducer joint + loss.

Reference: apex/contrib/csrc/transducer/ — ``transducer_joint_kernel.cu``
(fused f+g broadcast-add with optional packing/relu/dropout) and
``transducer_loss_kernel.cu`` (alpha/beta dynamic program + fused grad),
wrapped by apex/contrib/transducer/transducer.py (``TransducerJoint``,
``TransducerLoss``).

TPU restatement: the joint is a broadcast add (XLA fuses the activation and
the following projection); the loss is the standard RNN-T forward DP over
log-probs run as a ``lax.scan`` over anti-diagonals — each diagonal updates
in parallel on the VPU (the CUDA kernel parallelizes the same wavefront),
and autodiff of the scan IS the beta/grad pass (scan-transpose replays the
DP backward, the mechanism the reference hand-writes).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


class TransducerJoint:
    """Drop-in for apex.contrib.transducer.TransducerJoint.

    ``f``: [B, T, H] acoustic; ``g``: [B, U, H] label; returns [B, T, U, H]
    (``pack_output`` and dropout knobs accepted; packing — a CUDA memory
    optimization around ragged batches — is a no-op here: XLA keeps the
    dense layout and masking handles raggedness).
    """

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: bool = False, dropout_prob: float = 0.0,
                 probe_mask: bool = False):
        if dropout and dropout_prob > 0.0:
            raise NotImplementedError(
                "transducer joint dropout: pass rngs explicitly via __call__")
        self.pack_output = pack_output
        self.relu = relu

    def __call__(self, f, g, f_len=None, g_len=None, batch_offset=None,
                 packed_batch=None):
        h = f[:, :, None, :] + g[:, None, :, :]
        if self.relu:
            h = jax.nn.relu(h)
        return h


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T negative log-likelihood per batch element.

    ``log_probs``: [B, T, U+1, V] log-softmax outputs of the joint;
    ``labels``: [B, U] int32; ``f_len``: [B] valid T per sample; ``y_len``:
    [B] valid U per sample. Returns [B] losses (reference:
    transducer_loss_kernel.cu alpha pass; backward via autodiff of the
    scan = the beta pass).
    """
    b, t_max, u1_max, v = log_probs.shape
    u_max = u1_max - 1

    # per-(t,u) emission log-probs
    lp_blank = log_probs[..., blank_idx]                       # [B, T, U+1]
    lab = jnp.pad(labels, ((0, 0), (0, 1)))                    # [B, U+1]
    lp_label = jnp.take_along_axis(
        log_probs, lab[:, None, :, None], axis=-1)[..., 0]     # [B, T, U+1]

    neg_inf = jnp.float32(-1e30)

    # alpha DP over anti-diagonals d = t + u (wavefront parallelism, the
    # CUDA kernel's strategy): alpha[t, u] on diagonal d reads d-1.
    # State: alpha values laid out by u (length U+1), carried per diagonal.
    def diag_step(alpha_prev, d):
        # alpha_prev[u] = alpha[t=d-1-u? ...] — we carry the full [T, U+1]
        # is too big; carry per-diagonal vector indexed by u with t = d - u.
        u_idx = jnp.arange(u1_max)
        t_idx = d - u_idx
        valid = (t_idx >= 0) & (t_idx < t_max)

        # from the left (t-1, u): blank transition
        lpb = _gather_tu(lp_blank, t_idx - 1, u_idx)
        from_t = jnp.where(valid & (t_idx >= 1),
                           alpha_prev + lpb, neg_inf)
        # from below (t, u-1): label transition
        lpl = _gather_tu(lp_label, t_idx, u_idx - 1)
        alpha_um1 = jnp.concatenate([jnp.full((b, 1), neg_inf),
                                     alpha_prev[:, :-1]], axis=1)
        from_u = jnp.where(valid & (u_idx >= 1)[None, :],
                           alpha_um1 + lpl, neg_inf)

        alpha_d = jnp.logaddexp(from_t, from_u)
        alpha_d = jnp.where((t_idx == 0) & (u_idx == 0), 0.0, alpha_d)
        alpha_d = jnp.where(valid[None, :], alpha_d, neg_inf)
        return alpha_d, alpha_d

    def _gather_tu(lp, t_idx, u_idx):
        # lp: [B, T, U+1] -> [B, U+1] at (t_idx[u], u), -inf out of range
        t_safe = jnp.clip(t_idx, 0, t_max - 1)
        u_safe = jnp.clip(u_idx, 0, u1_max - 1)
        g = lp[:, t_safe, u_safe]
        ok = (t_idx >= 0) & (t_idx < t_max) & (u_idx >= 0) & (u_idx < u1_max)
        return jnp.where(ok[None, :], g, neg_inf)

    alpha0 = jnp.full((b, u1_max), neg_inf).at[:, 0].set(0.0)
    n_diags = t_max + u_max
    _, alphas = lax.scan(diag_step, alpha0, jnp.arange(1, n_diags))
    # alphas: [D-1, B, U+1]; prepend diagonal 0
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [D, B, U+1]

    # final: alpha[T-1, U] + log_prob_blank(T-1, U), per-sample lengths
    d_final = f_len - 1 + y_len                                # [B]
    a_final = alphas[d_final, jnp.arange(b), y_len]            # [B]
    lpb_final = lp_blank[jnp.arange(b), f_len - 1, y_len]
    return -(a_final + lpb_final)


class TransducerLoss:
    """Drop-in for apex.contrib.transducer.TransducerLoss (callable:
    ``loss(x, label, f_len, y_len, blank_idx)``; ``packed_input`` accepted
    for parity, dense layout assumed)."""

    def __init__(self, fuse_softmax_backward: bool = True,
                 opt: int = 1, packed_input: bool = False):
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0,
                 batch_offset=None, max_f_len=None, debug_list=None):
        log_probs = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        return transducer_loss(log_probs, label, f_len, y_len, blank_idx)
