"""apex.contrib.fmha equivalent.

Reference: apex/contrib/fmha/fmha.py (``FMHAFun`` over ``fmhalib`` — fixed
seqlen<=512 fp16 fused attention for MLPerf BERT, varlen via cu_seqlens).
Subsumed by the Pallas flash-attention kernel (no seqlen cap, varlen via
segment ids); this shim keeps the reference call surface.
"""

from apex_tpu.contrib.fmha.fmha import FMHAFun, fmha

__all__ = ["FMHAFun", "fmha"]
