"""FMHA shim over the flash-attention kernel.

Reference: apex/contrib/fmha/fmha.py — ``FMHAFun(qkv, cu_seqlens, ...)``
takes PACKED varlen input: ``qkv`` [total_tokens, 3, H, D] with
``cu_seqlens`` [B+1] prefix offsets. The Pallas flash kernel takes dense
[B, H, S, D] with segment ids, so this shim unpacks cu_seqlens into a
padded batch + segment mask, runs the kernel, and repacks — same contract,
no 512-seqlen cap.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops import flash_attention


def fmha(qkv, cu_seqlens, max_s: int, *, is_training: bool = True,
         dropout_rate: float = 0.0, dropout_seed: int = 0):
    """Packed-varlen fused MHA. Returns [total_tokens, H, D]."""
    total, three, h, d = qkv.shape
    assert three == 3, qkv.shape
    b = cu_seqlens.shape[0] - 1

    # scatter packed tokens into a padded [B, max_s] layout
    seq_of_token = jnp.searchsorted(cu_seqlens[1:], jnp.arange(total),
                                    side="right")
    pos_in_seq = jnp.arange(total) - cu_seqlens[seq_of_token]
    padded = jnp.zeros((b, max_s, 3, h, d), qkv.dtype)
    padded = padded.at[seq_of_token, pos_in_seq].set(qkv)

    lens = cu_seqlens[1:] - cu_seqlens[:-1]                     # [B]
    valid = jnp.arange(max_s)[None, :] < lens[:, None]          # [B, max_s]
    segment_ids = jnp.where(valid, 1, 0).astype(jnp.int32)

    q, k, v = (padded[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
    rate = dropout_rate if is_training else 0.0
    ctx = flash_attention(q, k, v, segment_ids=segment_ids,
                          dropout_rate=rate, dropout_seed=dropout_seed)
    ctx = ctx.transpose(0, 2, 1, 3)                             # [B, S, H, D]
    return ctx[seq_of_token, pos_in_seq]                        # repack


class FMHAFun:
    """Callable facade matching the reference's autograd-Function name."""

    @staticmethod
    def apply(qkv, cu_seqlens, p_dropout, max_s, is_training,
              zero_tensors=False):
        return fmha(qkv, cu_seqlens, max_s, is_training=is_training,
                    dropout_rate=p_dropout)
