"""1-D halo exchange over a mesh axis.

Reference: apex/contrib/peer_memory/peer_halo_exchanger_1d.py —
``PeerHaloExchanger1d.__call__(y, half_halo)``: each rank holds a spatial
slab of an NHWC activation split along H; it sends its top/bottom
``half_halo`` rows to its neighbors via cudaIpc peer memory (or the
nccl_p2p ring fallback) so convolutions see valid halos.

TPU restatement: two ``ppermute`` shifts on the mesh axis (one up, one
down) — XLA collective-permute over ICI neighbor links, which is exactly
the physical transfer the cudaIpc path hand-built. Boundary ranks receive
zeros (the reference leaves the padded border, zero-filled by the caller).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu import collectives as coll
from apex_tpu.mesh import CONTEXT_AXIS


def halo_exchange_1d(y, half_halo: int, axis_name: str = CONTEXT_AXIS,
                     spatial_dim: int = 1):
    """Concatenate neighbors' boundary rows around this rank's slab.

    ``y``: local [..., H_local, ...] slab (``spatial_dim`` indexes H).
    Returns the slab extended to H_local + 2*half_halo. Must run inside
    shard_map with ``axis_name`` bound.
    """
    top = jnp.take(y, jnp.arange(half_halo), axis=spatial_dim)
    h = y.shape[spatial_dim]
    bottom = jnp.take(y, jnp.arange(h - half_halo, h), axis=spatial_dim)
    # my bottom rows -> next rank's top halo; my top rows -> prev's bottom
    from_prev = coll.shift_right(bottom, axis_name)   # recv prev's bottom
    from_next = coll.shift_left(top, axis_name)       # recv next's top
    return jnp.concatenate([from_prev, y, from_next], axis=spatial_dim)


class PeerHaloExchanger1d:
    """Drop-in for apex.contrib.peer_memory.PeerHaloExchanger1d."""

    def __init__(self, ranks=None, rank_in_group=None, peer_pool=None,
                 half_halo: int = 1, axis_name: str = CONTEXT_AXIS):
        self.half_halo = half_halo
        self.axis_name = axis_name

    def __call__(self, y, H_split: bool = True, explicit_nhwc: bool = True,
                 numSM: int = 0, diagnostics: bool = False):
        dim = 1 if H_split else 2
        return halo_exchange_1d(y, self.half_halo, self.axis_name,
                                spatial_dim=dim)
