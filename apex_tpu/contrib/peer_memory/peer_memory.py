"""PeerMemoryPool facade.

Reference: apex/contrib/peer_memory/peer_memory.py — a cudaIpc-backed pool
of peer-addressable buffers that the halo exchangers write through. XLA
owns all device memory on TPU (SURVEY.md §2.2: "N/A on TPU — XLA owns
buffers"), so the pool is a documented no-op facade kept so reference code
that constructs one keeps running; the actual halo traffic is ppermute
(see peer_halo_exchanger_1d.py).
"""

from __future__ import annotations


class PeerMemoryPool:
    """API placeholder: allocations are XLA's job on TPU."""

    def __init__(self, static_size: int = 0, dynamic_size: int = 0,
                 peer_ranks=None):
        self.peer_ranks = peer_ranks

    def allocate_peer_tensors(self, shape, dtype, channels_last: bool,
                              requires_grad: bool):
        raise NotImplementedError(
            "PeerMemoryPool.allocate_peer_tensors has no TPU analog — XLA "
            "owns device buffers; use PeerHaloExchanger1d/halo_exchange_1d "
            "(ppermute) directly")

    def reset(self):
        pass
