"""apex.contrib.peer_memory equivalent (halo exchange for spatial
parallelism)."""

from apex_tpu.contrib.peer_memory.peer_halo_exchanger_1d import (
    PeerHaloExchanger1d,
    halo_exchange_1d,
)
from apex_tpu.contrib.peer_memory.peer_memory import PeerMemoryPool

__all__ = ["PeerHaloExchanger1d", "halo_exchange_1d", "PeerMemoryPool"]
