"""openfold analog — reference: apex/contrib/openfold_triton/.

The reference package is pure-Triton AlphaFold kernels with two public
compute entry points (SURVEY.md §2.3 niche row): a LayerNorm tuned for
OpenFold's small-last-dim shapes and a fused attention core (softmax over
per-head bias + gating epilogue handled in Python around it). On TPU both
map directly onto kernels this library already ships — this module is the
explicit mapping so OpenFold-style callers have a named import:

- ``layer_norm`` -> apex_tpu.ops.layer_norm (Pallas fwd+bwd, fp32 accum);
  OpenFold's [*, N_res, N_res, c_z]-style shapes flatten to rows like any
  other LN input, so no small-shape special case is needed.
- ``attention_core(q, k, v, bias1, bias2)`` -> the flash-attention kernel
  with additive bias (the Triton kernel's mask/pair biases sum into one
  additive term; softmax/AV fusion comes from the kernel itself).

The rest of the reference package (CUDA-graph/SWA training-loop helpers,
DAP process groups) is training-harness code outside this library's
kernel-parity scope — see docs/contrib.md.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import flash_attention
from apex_tpu.ops import layer_norm as _fused_layer_norm

__all__ = ["layer_norm", "attention_core"]


def layer_norm(x, weight, bias, eps: float = 1e-5):
    """OpenFold LayerNormSmallShapeOptImpl analog (normalize over the last
    dim with affine): one Pallas kernel serves every hidden size."""
    return _fused_layer_norm(x, weight, bias, eps=eps)


def attention_core(q, k, v, bias1: Optional[jax.Array] = None,
                   bias2: Optional[jax.Array] = None, *,
                   scale: Optional[float] = None):
    """Fused attention core: softmax(scale*q@k^T + bias1 + bias2) @ v.

    q/k/v: [batch, heads, seq, dim] (callers with OpenFold's extra leading
    dims flatten them into batch). bias1/bias2 broadcast over
    [batch, heads, q, k] — the reference kernel's mask bias and triangle/
    pair bias; they are summed into the flash kernel's additive-bias slot.
    """
    bias = None
    if bias1 is not None and bias2 is not None:
        bias = (bias1.astype(jnp.float32) + bias2.astype(jnp.float32))
    elif bias1 is not None:
        bias = bias1
    elif bias2 is not None:
        bias = bias2
    return flash_attention(q, k, v, bias=bias, scale=scale)
