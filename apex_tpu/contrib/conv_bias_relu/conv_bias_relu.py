"""Fused Conv+Bias(+Mask)(+ReLU) functions.

Reference: apex/contrib/conv_bias_relu/conv_bias_relu.py over a
cudnn-frontend fused-op extension: ConvBias, ConvBiasReLU, ConvBiasMaskReLU,
ConvFrozenScaleBiasReLU — NHWC convs with fused epilogues. On TPU, XLA
fuses conv+bias+relu from the naive expression (the epilogue fusion IS the
compiler's job here); these functions pin the NHWC layout and the
reference's call signatures. All are differentiable (autodiff backward ==
the reference's dgrad/wgrad/dbias fused kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _conv_nhwc(x, w, stride: int, padding: int):
    """NHWC conv with HWIO weights, symmetric padding."""
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def ConvBias(x, weight, bias, padding: int = 0, stride: int = 1):
    """conv + bias (reference: ConvBias_.apply)."""
    return _conv_nhwc(x, weight, stride, padding) + bias


def ConvBiasReLU(x, weight, bias, padding: int = 0, stride: int = 1):
    """conv + bias + relu (reference: ConvBiasReLU_.apply)."""
    return jax.nn.relu(ConvBias(x, weight, bias, padding, stride))


def ConvBiasMaskReLU(x, weight, bias, mask, padding: int = 0,
                     stride: int = 1):
    """conv + bias + elementwise mask + relu (reference: ConvBiasMaskReLU_)."""
    return jax.nn.relu(ConvBias(x, weight, bias, padding, stride) * mask)


def ConvFrozenScaleBiasReLU(x, weight, scale, bias, padding: int = 0,
                            stride: int = 1):
    """conv, then frozen-BN-style scale*y + bias, then relu
    (reference: ConvFrozenScaleBiasReLU_)."""
    y = _conv_nhwc(x, weight, stride, padding)
    return jax.nn.relu(y * scale + bias)
