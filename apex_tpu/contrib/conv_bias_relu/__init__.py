"""apex.contrib.conv_bias_relu equivalent."""

from apex_tpu.contrib.conv_bias_relu.conv_bias_relu import (
    ConvBias,
    ConvBiasMaskReLU,
    ConvBiasReLU,
    ConvFrozenScaleBiasReLU,
)

__all__ = ["ConvBias", "ConvBiasReLU", "ConvBiasMaskReLU",
           "ConvFrozenScaleBiasReLU"]
