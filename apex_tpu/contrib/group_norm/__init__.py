"""apex.contrib.group_norm equivalent."""

from apex_tpu.contrib.group_norm.group_norm import GroupNorm

__all__ = ["GroupNorm"]
