"""GroupNorm module — drop-in for apex.contrib.group_norm.GroupNorm.

Reference: apex/contrib/group_norm/group_norm.py — a torch.nn.GroupNorm
drop-in over the NHWC CUDA kernels (apex/contrib/csrc/group_norm/), with
``act="silu"`` fusing the activation (diffusion workloads). Input here is
NHWC (the TPU-native layout; the reference's whole point was avoiding
torch's NCHW default).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.group_norm import group_norm_nhwc


class GroupNorm(nn.Module):
    """``GroupNorm(num_groups, num_channels, eps, affine, act)``."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: Optional[str] = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if x.shape[-1] != self.num_channels:
            raise ValueError(
                f"input channels {x.shape[-1]} != num_channels "
                f"{self.num_channels} (NHWC expected)")
        if self.affine:
            w = self.param("weight", nn.initializers.ones,
                           (self.num_channels,), self.param_dtype)
            b = self.param("bias", nn.initializers.zeros,
                           (self.num_channels,), self.param_dtype)
        else:
            w = b = None
        return group_norm_nhwc(x, w, b, self.num_groups, self.eps,
                               self.act)

    forward = __call__
