"""BatchNorm2d_NHWC — NHWC BN with fused residual-add + ReLU.

Reference: apex/contrib/groupbn/batch_norm.py over the ``bnp`` extension
(apex/contrib/csrc/groupbn/batch_norm.cu, batch_norm_add_relu.cu, ipc.cu —
NHWC BN with fused add+ReLU and intra-node cudaIpc peer reduction for
group BN). TPU restatement (SURVEY.md §2.2): the stats reduction is
SyncBatchNorm's psum (the ``bn_group`` arg maps to a mesh axis), and the
add+ReLU epilogue is expressed inline for XLA to fuse — the CUDA file's
whole purpose.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.mesh import DATA_AXIS
from apex_tpu.parallel import SyncBatchNorm


class BatchNorm2d_NHWC(nn.Module):
    """Drop-in for apex.contrib.groupbn.BatchNorm2d_NHWC.

    ``fuse_relu`` applies ReLU after the norm; call with ``z=`` to fuse the
    residual add (reference: batch_norm_add_relu). ``bn_group`` > 1 syncs
    stats over ``axis_name`` (the cudaIpc group analog).
    """

    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[Any] = DATA_AXIS
    eps: float = 1e-5
    momentum: float = 0.1

    @nn.compact
    def __call__(self, x, z=None, use_running_average: bool = False):
        bn = SyncBatchNorm(
            num_features=self.num_features, eps=self.eps,
            momentum=self.momentum,
            axis_name=self.axis_name if self.bn_group > 1 else None,
            name="bn")
        y = bn(x, use_running_average=use_running_average)
        if z is not None:
            y = y + z.astype(y.dtype)
        if self.fuse_relu:
            y = nn.relu(y)
        return y

    forward = __call__
