"""apex.contrib.groupbn equivalent (NHWC BatchNorm with fused add+ReLU)."""

from apex_tpu.contrib.groupbn.batch_norm import BatchNorm2d_NHWC

__all__ = ["BatchNorm2d_NHWC"]
