from apex_tpu.contrib.clip_grad.clip_grad import clip_grad_norm_  # noqa: F401
