"""Fused global-norm gradient clipping.

Reference: apex/contrib/clip_grad/clip_grad.py:~20 — ``clip_grad_norm_``
computes the global norm with ONE ``amp_C.multi_tensor_l2norm`` launch and
scales all grads with one ``multi_tensor_scale`` launch (vs torch's
per-tensor loop).

JAX grads are values, so the fused variant returns the clipped pytree:

    grads, total_norm = clip_grad_norm_(grads, max_norm)

For norm_type == 2 the norm comes from the Pallas flat-buffer stats kernel
(same pass the fused optimizers use); other norm types fall back to a jitted
tree reduction (the reference likewise falls back to torch for p != 2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# jitted fused clip keyed by the grad pytree signature (treedef + leaf
# shapes/dtypes) so per-step calls don't rebuild the FlatSpec or dispatch
# O(num_tensors) eager pads/slices
_JIT_CACHE: dict = {}


def _fused_clip(grads):
    from apex_tpu.ops import flat_buffer, optim_kernels

    leaves, treedef = jax.tree.flatten(grads)
    key = (treedef, tuple((l.shape, jnp.result_type(l)) for l in leaves))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        spec = flat_buffer.build_spec(grads)
        seg_rows = jnp.asarray(spec.segment_rows())

        @jax.jit
        def fn(g_tree, max_norm):
            flat = flat_buffer.flatten(g_tree, spec)
            total_norm, _, _ = optim_kernels.global_grad_norm_and_finite(
                flat, seg_rows, spec.num_tensors)
            clip = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
            return flat_buffer.unflatten(flat * clip, spec), total_norm

        _JIT_CACHE[key] = fn
    return fn


def clip_grad_norm_(grads, max_norm: float, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """Returns ``(clipped_grads, total_norm)``.

    ``error_if_nonfinite`` mirrors torch's kwarg: JAX can't raise on traced
    values, so a non-finite norm instead zeroes no gradients and propagates
    the non-finite norm for the caller's scaler logic to catch (the fused
    optimizers' ``noop`` flag handles the skip).
    """
    if norm_type == 2.0:
        return _fused_clip(grads)(grads, jnp.float32(max_norm))
    max_norm = float(max_norm)
    if norm_type == float("inf"):
        total_norm = jnp.max(jnp.asarray(
            [jnp.max(jnp.abs(g)) for g in jax.tree.leaves(grads)]))
    else:
        total_norm = jnp.sum(jnp.asarray(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in jax.tree.leaves(grads)])) ** (1.0 / norm_type)
    clip = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
    return jax.tree.map(lambda g: (g * clip).astype(g.dtype), grads), total_norm
