"""Reference: apex/contrib/xentropy/__init__.py."""

from apex_tpu.contrib.xentropy.softmax_xentropy import SoftmaxCrossEntropyLoss  # noqa: F401
