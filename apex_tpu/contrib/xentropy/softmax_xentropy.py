"""Memory-saving label-smoothed cross entropy.

Reference: apex/contrib/xentropy/softmax_xentropy.py:~10 —
``SoftmaxCrossEntropyLoss`` autograd Function over ``xentropy_cuda``; the
kernel here is apex_tpu/ops/xentropy.py (saves only logsumexp, recomputes
softmax in backward).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops import xentropy as _ops


class SoftmaxCrossEntropyLoss:
    """Same call surface as the reference autograd Function.

    ``SoftmaxCrossEntropyLoss.apply(logits, labels, smoothing=0.0,
    padding_idx=0, half_to_float=False)`` returns per-row losses.
    """

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        losses = _ops.softmax_cross_entropy(
            logits, labels, smoothing=smoothing, padding_idx=padding_idx)
        if not half_to_float and losses.dtype != logits.dtype:
            # reference keeps fp16 losses unless half_to_float=True
            losses = losses.astype(logits.dtype)
        return losses

    def __call__(self, logits, labels, smoothing=0.0, padding_idx=0,
                 half_to_float=False):
        return self.apply(logits, labels, smoothing, padding_idx,
                          half_to_float)


def softmax_cross_entropy_loss(logits, labels, smoothing=0.0, padding_idx=0,
                               half_to_float=False):
    return SoftmaxCrossEntropyLoss.apply(logits, labels, smoothing,
                                         padding_idx, half_to_float)
