"""apex_tpu.contrib — rebuild of apex.contrib (SURVEY.md §2.3).

Subpackages (import explicitly, as with the reference's optional builds):
  multihead_attn, xentropy, clip_grad, fmha,
  optimizers (DistributedFusedAdam/LAMB — ZeRO),
  sparsity (ASP 2:4), layer_norm (FastLayerNorm shim),
  group_norm (NHWC GroupNorm+SiLU), groupbn (BatchNorm2d_NHWC),
  focal_loss, index_mul_2d, transducer (RNN-T joint/loss),
  peer_memory (1-D halo exchange over ppermute),
  conv_bias_relu (XLA-fused conv epilogues).
"""
