"""apex_tpu.contrib — rebuild of apex.contrib (SURVEY.md §2.3).

Subpackages import lazily:
  multihead_attn, xentropy, clip_grad, optimizers (distributed/ZeRO),
  sparsity (ASP), layer_norm, fmha, group_norm, focal_loss, index_mul_2d,
  transducer.
"""
