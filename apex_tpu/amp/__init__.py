"""apex_tpu.amp — mixed precision with apex.amp's API shape.

Reference: apex/amp/ — ``initialize()`` (frontend.py), ``scale_loss()``
(handle.py), ``master_params()``, ``state_dict()`` (+ the O0-O3 opt-level
system). The TPU translation (SURVEY.md §3.1): the O1 monkey-patch machinery
becomes a dtype Policy consulted by modules; O2's master weights are the flat
fp32 master the fused optimizers already hold; dynamic loss scaling exists
for fp16-parity runs and is fused into the optimizer step (found-inf from the
stats kernel, scaler state updated on device).

Typical use:

    params, optimizer = amp.initialize(params, optimizer, opt_level="O2")
    ...
    with amp.scale_loss(loss, optimizer) as scaled_loss:
        grads = jax.grad(loss_fn)(...)   # of the scaled loss
    new_params = optimizer.step(grads)   # unscale + inf-skip fused
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import (Policy, is_norm_param_name, make_policy,
                                 resolve_compute_dtype)
from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.optimizers.common import path_name as _path_name

__all__ = ["initialize", "scale_loss", "unscale_and_combine", "master_params",
           "current_policy", "state_dict", "load_state_dict", "Policy",
           "make_policy", "LossScaler", "resolve_compute_dtype"]

# module-level amp state (reference: apex/amp/_amp_state.py)
_current_policy: Optional[Policy] = None
_loss_scalers: list = []


def current_policy() -> Optional[Policy]:
    """The active Policy (modules consult this for compute dtypes)."""
    return _current_policy


def initialize(models, optimizers=None, enabled=True, opt_level="O1",
               cast_model_type=None, patch_torch_functions=None,
               keep_batchnorm_fp32=None, master_weights=None, loss_scale=None,
               cast_model_outputs=None, num_losses=1, verbosity=1,
               min_loss_scale=1.0, max_loss_scale=2.0 ** 24,
               half_dtype=jnp.bfloat16, keep_fp32_predicate=None,
               hysteresis=1):
    """Reference: apex/amp/frontend.py:initialize (same signature shape;
    torch-only knobs like patch_torch_functions are accepted and ignored).

    ``models`` is a parameter pytree (or list of pytrees); returns the
    policy-cast pytree(s) and the optimizer(s) with a LossScaler attached.
    With multiple losses AND multiple optimizers, scaler i is attached to
    optimizer i (the DCGAN pattern: one loss per loss_id=i optimizer). A
    single optimizer driven by several dynamically-scaled losses (reference:
    handle.py scale_loss(loss, opt, loss_id=i) with num_losses > 1) keeps
    one independent scaler per loss; combine the per-loss grads with
    ``amp.unscale_and_combine`` and pass its noop flag to ``step``.
    """
    global _current_policy, _loss_scalers
    if not enabled:
        if optimizers is None:
            return models
        return models, optimizers

    policy = make_policy(opt_level, half_dtype=half_dtype,
                         cast_model_type=cast_model_type,
                         keep_batchnorm_fp32=keep_batchnorm_fp32,
                         master_weights=master_weights, loss_scale=loss_scale)
    _current_policy = policy

    keep_fp32 = keep_fp32_predicate or is_norm_param_name

    def cast_params(tree):
        if policy.param_dtype == jnp.float32:
            return tree
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        out = []
        for path, leaf in flat:
            if (policy.keep_norm_fp32 and keep_fp32(_path_name(path))) or not jnp.issubdtype(leaf.dtype, jnp.floating):
                out.append(leaf)
            else:
                out.append(leaf.astype(policy.param_dtype))
        return jax.tree_util.tree_unflatten(jax.tree.structure(tree), out)

    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    model_list = [cast_params(m) for m in model_list]

    _loss_scalers = [
        LossScaler(policy.loss_scale, min_loss_scale=min_loss_scale,
                   max_loss_scale=max_loss_scale, hysteresis=hysteresis)
        for _ in range(num_losses)
    ]
    _combine_cache.clear()

    if optimizers is not None:
        single_opt = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if single_opt else list(optimizers)
        if num_losses > 1 and len(opt_list) not in (1, num_losses):
            raise ValueError("num_losses must be 1 or match the optimizer count")
        # Multi-loss on ONE optimizer with DYNAMIC scaling: the per-loss
        # unscale happens in amp.unscale_and_combine (each loss's scale
        # diverges), so no scaler is fused into the step — it receives
        # pre-unscaled grads plus the union found-inf noop flag. With a
        # STATIC scale every loss shares one value, so the fused in-step
        # unscale remains correct (and remains attached — pre-round-3
        # behavior; such callers must NOT also use unscale_and_combine).
        multi_loss_dynamic_single_opt = (num_losses > 1 and len(opt_list) == 1
                                         and _loss_scalers[0].dynamic)
        for i, opt in enumerate(opt_list):
            scaler = _loss_scalers[min(i, num_losses - 1)]
            # skip the no-op scaler entirely: static scale 1.0 needs neither
            # an unscale nor a found-inf pass (saves a full grad-buffer read
            # per step and keeps inf grads loud instead of silently skipping)
            if (hasattr(opt, "attach_amp_scaler")
                    and not multi_loss_dynamic_single_opt
                    and (scaler.dynamic or float(scaler.state.scale) != 1.0)):
                opt.attach_amp_scaler(scaler)
            # no scaler is fused into step in multi-loss dynamic mode, so a
            # caller skipping the unscale_and_combine protocol would apply
            # ~2**16-scaled grads silently; the noop kwarg is the protocol's
            # receipt, and the optimizer refuses to step without it.
            # Unconditional assignment: re-initialize in another mode must
            # clear a stale flag.
            opt._amp_require_noop = multi_loss_dynamic_single_opt
            # O2/O3: the optimizer must hand back params in the cast dtypes
            if hasattr(opt, "set_output_dtypes") and policy.param_dtype != jnp.float32:
                model_idx = min(i, len(model_list) - 1)
                opt.set_output_dtypes(
                    [l.dtype for l in jax.tree.leaves(model_list[model_idx])]
                )
        out_opt = opt_list[0] if single_opt else opt_list
        return (model_list[0] if single_model else model_list), out_opt
    return model_list[0] if single_model else model_list


@contextlib.contextmanager
def scale_loss(loss, optimizers=None, loss_id=0, model=None, delay_unscale=False,
               delay_overflow_check=False):
    """Reference: apex/amp/handle.py:scale_loss. Yields ``loss * scale``;
    the unscale (and overflow skip) is fused into ``optimizer.step``.

    Usable inside jit — it is pure arithmetic on the traced loss value.
    """
    if not _loss_scalers:
        yield loss
        return
    scaler = _loss_scalers[loss_id]
    yield scaler.scale_loss(loss)


# jit cache for unscale_and_combine: keyed by (loss ids, grad tree structure)
_combine_cache: dict = {}


def unscale_and_combine(grads_list, loss_ids=None):
    """Combine per-loss scaled grads for ONE optimizer (reference:
    apex/amp/handle.py scale_loss(..., loss_id=i) with num_losses > 1 —
    each ctx exit unscales that loss's grads by ITS scaler and accumulates
    into param.grad; optimizer.step skips if ANY loss overflowed, and each
    scaler's scale updates independently).

    Args:
      grads_list: per-loss grad pytrees, each of the SCALED loss ``i`` (as
        produced by ``jax.grad`` of the ``scale_loss(..., loss_id=i)``
        value).
      loss_ids: which scaler each entry belongs to (default: 0..N-1).

    Returns ``(grads, noop)``: the summed unscaled grads and the union
    found-inf flag — pass both to ``optimizer.step(grads, noop=noop)``.
    Updates each involved scaler's state (halve on its own overflow, grow on
    its own clean streak), so scalers diverge per loss exactly like the
    reference's per-loss LossScaler instances.
    """
    ids = tuple(loss_ids) if loss_ids is not None else tuple(
        range(len(grads_list)))
    if len(ids) != len(grads_list):
        raise ValueError("loss_ids must match grads_list length")
    if not _loss_scalers:
        # amp disabled / uninitialized: keep call sites working like
        # scale_loss does — no scaling happened, so just sum
        total = grads_list[0]
        for g in grads_list[1:]:
            total = jax.tree.map(jnp.add, total, g)
        return total, jnp.zeros((), jnp.float32)
    scalers = tuple(_loss_scalers[i] for i in ids)
    if not any(s.dynamic for s in scalers):
        # with a STATIC loss_scale, initialize() fused the (single, shared)
        # scale into optimizer.step — unscaling here too would shrink every
        # update by the scale a second time
        raise RuntimeError(
            "unscale_and_combine is for dynamically-scaled multi-loss "
            "training; with a static loss_scale the unscale is fused into "
            "optimizer.step, so sum the raw scaled grads and call step "
            "directly")
    treedef = jax.tree.structure(grads_list[0])
    # key on the scalers' STATIC behavior (growth params), not identity:
    # every distinct configuration compiles once, re-initialize() with the
    # same config reuses the entry (the closure's stale scaler objects only
    # contribute these same statics — states ride in as arguments), and the
    # cache stays bounded by distinct configurations
    statics = tuple((s._scale_factor, s._scale_window, s._min_scale,
                     s._max_scale, s._hysteresis) for s in scalers)
    key = (ids, str(treedef), statics)
    if key not in _combine_cache:
        def _pure(g_list, states):
            total = None
            noop = jnp.zeros((), jnp.float32)
            new_states = []
            for g, st, sc in zip(g_list, states, scalers):
                nonfinite = sum(
                    jnp.sum(~jnp.isfinite(leaf.astype(jnp.float32)))
                    for leaf in jax.tree.leaves(g))
                found = (nonfinite > 0).astype(jnp.float32)
                inv = (1.0 / st.scale)
                g_un = jax.tree.map(
                    lambda x: x * inv.astype(x.dtype), g)
                total = (g_un if total is None
                         else jax.tree.map(jnp.add, total, g_un))
                noop = jnp.maximum(noop, found)
                new_states.append(sc.update(st, found))
            return total, new_states, noop

        _combine_cache[key] = jax.jit(_pure)

    states = [s.state for s in scalers]
    total, new_states, noop = _combine_cache[key](list(grads_list), states)
    for s, ns in zip(scalers, new_states):
        s.state = ns
    return total, noop


def master_params(optimizer):
    """Reference: apex/amp/__init__.py:master_params — the fp32 master
    parameter pytree held by a fused optimizer."""
    from apex_tpu.ops import flat_buffer

    fp32_dtypes = [jnp.float32] * optimizer.spec.num_tensors
    return flat_buffer.unflatten(optimizer.master, optimizer.spec, dtypes=fp32_dtypes)


def state_dict(destination=None):
    """Reference: apex/amp/frontend.py:state_dict — loss-scaler state."""
    return {f"loss_scaler{i}": s.state_dict() for i, s in enumerate(_loss_scalers)}


def load_state_dict(sd):
    for i, s in enumerate(_loss_scalers):
        key = f"loss_scaler{i}"
        if key in sd:
            s.load_state_dict(sd[key])
