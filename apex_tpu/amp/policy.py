"""Precision policies — the TPU re-expression of apex.amp opt levels.

Reference: apex/amp/frontend.py opt-level Properties:
  O0 = fp32 everything;
  O1 = per-op cast lists (GEMM/conv in fp16, softmax/norm/loss in fp32) via
       monkey-patching torch (apex/amp/lists/*_overrides.py, amp.py:init);
  O2 = fp16 model weights + fp32 master weights + fp32 batchnorm;
  O3 = pure fp16.

On TPU the per-op patch machinery collapses into a dtype policy consulted by
modules: params dtype, compute dtype, and whether normalization/softmax/loss
run in fp32 (they always accumulate fp32 in our kernels regardless). bf16 is
the native 16-bit type (no loss scaling needed); fp16 is allowed for parity
experiments and engages the dynamic LossScaler.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


# parameter-name tokens treated as normalization params for keep_batchnorm_fp32
# (shared by amp.initialize and fp16_utils.BN_convert_float)
NORM_NAME_TOKENS = ("norm", "bn", "batchnorm", "layernorm")


def is_norm_param_name(path_name: str) -> bool:
    n = path_name.lower()
    return any(t in n for t in NORM_NAME_TOKENS)


@dataclasses.dataclass(frozen=True)
class Policy:
    """What dtype each tensor class uses (jmp-style, apex-shaped)."""

    opt_level: str
    param_dtype: jnp.dtype
    compute_dtype: jnp.dtype
    output_dtype: jnp.dtype
    keep_norm_fp32: bool  # keep_batchnorm_fp32 in the reference
    master_weights: bool
    loss_scale: Optional[object]  # None, float, or "dynamic"

    def cast_to_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )

    def cast_to_param(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.param_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )

    def cast_to_output(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.output_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )


def resolve_compute_dtype(default):
    """The dtype modules should compute in: the active amp Policy's compute
    dtype if ``amp.initialize`` has been called, else ``default``.

    This is the TPU seam replacing the reference's O1 monkey-patching
    (apex/amp/amp.py:init patches torch functions to cast per-op): every
    module calls this at trace time, so ``amp.initialize(opt_level="O1")``
    flips compute dtypes without touching any config. Traces are re-built
    after amp.initialize (amp-then-jit, the reference's required order).
    """
    from apex_tpu import amp as _amp

    pol = _amp.current_policy()
    return default if pol is None else pol.compute_dtype


def make_policy(opt_level: str, half_dtype=jnp.bfloat16,
                cast_model_type=None, keep_batchnorm_fp32=None,
                master_weights=None, loss_scale=None) -> Policy:
    """Map an apex opt_level (+ overrides) to a Policy.

    Mirrors apex/amp/frontend.py: explicit kwargs override the opt-level
    defaults, as in the reference's Properties handling.
    """
    opt_level = opt_level.upper()
    if opt_level == "O0":
        p = dict(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                 output_dtype=jnp.float32, keep_norm_fp32=False,
                 master_weights=False, loss_scale=1.0)
    elif opt_level == "O1":
        p = dict(param_dtype=jnp.float32, compute_dtype=half_dtype,
                 output_dtype=jnp.float32, keep_norm_fp32=True,
                 master_weights=False,
                 loss_scale="dynamic" if half_dtype == jnp.float16 else 1.0)
    elif opt_level == "O2":
        p = dict(param_dtype=half_dtype, compute_dtype=half_dtype,
                 output_dtype=jnp.float32, keep_norm_fp32=True,
                 master_weights=True,
                 loss_scale="dynamic" if half_dtype == jnp.float16 else 1.0)
    elif opt_level == "O3":
        p = dict(param_dtype=half_dtype, compute_dtype=half_dtype,
                 output_dtype=half_dtype, keep_norm_fp32=False,
                 master_weights=False, loss_scale=1.0)
    else:
        raise ValueError(f"Unexpected optimization level {opt_level}; "
                         "options are 'O0', 'O1', 'O2', 'O3'.")
    if cast_model_type is not None:
        p["param_dtype"] = cast_model_type
    if keep_batchnorm_fp32 is not None:
        p["keep_norm_fp32"] = keep_batchnorm_fp32
    if master_weights is not None:
        p["master_weights"] = master_weights
    if loss_scale is not None:
        p["loss_scale"] = loss_scale
    return Policy(opt_level=opt_level, **p)
