"""Dynamic loss scaler — reference: apex/amp/scaler.py:LossScaler.

Keeps the reference's algorithm (init 2**16, halve on overflow, double after
``growth_interval=2000`` clean steps — frontend.py dynamic defaults) but the
state lives as device scalars updated functionally inside the jitted
optimizer step, so no host sync is needed per step. ``found_inf`` comes from
the fused stats kernel (the noop_flag analog of multi_tensor_scale).

On TPU the default precision is bf16 (same exponent range as fp32), so the
scaler is a no-op unless an fp16 policy or explicit scale is requested —
matching SURVEY.md §3.1's translation note.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp


class ScalerState(NamedTuple):
    scale: jax.Array            # f32 scalar
    growth_tracker: jax.Array   # i32 scalar — clean steps since last growth
    dynamic: jax.Array          # f32 0/1 flag (static per scaler, kept for pytree)
    hysteresis_tracker: jax.Array  # i32 scalar — overflows left before a halve


class LossScaler:
    """API mirror of apex/amp/scaler.py:LossScaler.

    ``hysteresis`` (reference: csrc/update_scale_hysteresis.cu, consumed by
    DistributedFusedAdam): tolerate that many overflow steps before halving
    the scale — the tracker decrements on overflow, the scale halves only
    once it reaches zero, and the tracker refills ONLY when the scale grows
    after ``scale_window`` clean steps (the .cu kernel resets it inside the
    growth branch, so intermittent overflows accumulate rather than being
    forgiven by the next clean step). The default of 1 is the classic
    halve-on-every-overflow behavior.
    """

    def __init__(self, loss_scale: Union[float, str] = 1.0,
                 init_scale: float = 2.0 ** 16,
                 scale_factor: float = 2.0,
                 scale_window: int = 2000,
                 min_loss_scale: float = 1.0,
                 max_loss_scale: float = 2.0 ** 24,
                 hysteresis: int = 1):
        self.dynamic = loss_scale == "dynamic"
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._min_scale = min_loss_scale
        self._max_scale = max_loss_scale  # reference default cap (frontend.py)
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
        self._hysteresis = hysteresis
        init = init_scale if self.dynamic else float(loss_scale)
        self.state = ScalerState(
            scale=jnp.asarray(init, jnp.float32),
            growth_tracker=jnp.zeros((), jnp.int32),
            dynamic=jnp.asarray(1.0 if self.dynamic else 0.0, jnp.float32),
            hysteresis_tracker=jnp.asarray(hysteresis, jnp.int32),
        )

    def loss_scale(self) -> jax.Array:
        return self.state.scale

    def scale_loss(self, loss):
        return loss * self.state.scale.astype(loss.dtype)

    def update(self, state: ScalerState, found_inf) -> ScalerState:
        """Pure update (traceable): on overflow decrement the hysteresis
        tracker and halve only once it reaches zero; double after
        scale_window clean steps (which also reset the hysteresis tracker),
        clamped to [min, max] (reference update_scale semantics incl. the
        2**24 cap and update_scale_hysteresis.cu's tolerance counter).
        Branches on the traced ``state.dynamic`` flag, so a checkpoint
        restore that flips dynamic does not require re-tracing callers."""
        found = found_inf.astype(jnp.bool_)
        hyst = jnp.where(found,
                         jnp.maximum(state.hysteresis_tracker - 1, 0),
                         state.hysteresis_tracker)
        halve = found & (hyst <= 0)
        new_scale = jnp.where(halve, state.scale / self._scale_factor,
                              state.scale)
        tracker = jnp.where(found, 0, state.growth_tracker + 1)
        grow = tracker >= self._scale_window
        new_scale = jnp.where(grow, new_scale * self._scale_factor, new_scale)
        tracker = jnp.where(grow, 0, tracker)
        # the .cu kernel refills the hysteresis budget only on growth
        hyst = jnp.where(grow, jnp.asarray(self._hysteresis, jnp.int32), hyst)
        new_scale = jnp.clip(new_scale, self._min_scale, self._max_scale)
        is_dyn = state.dynamic > 0.0
        return ScalerState(
            scale=jnp.where(is_dyn, new_scale, state.scale),
            growth_tracker=jnp.where(is_dyn, tracker, state.growth_tracker),
            dynamic=state.dynamic,
            hysteresis_tracker=jnp.where(is_dyn, hyst,
                                         state.hysteresis_tracker),
        )

    # -- checkpointing (reference: amp.state_dict saves loss scalers) ---------
    def state_dict(self):
        return {"scale": self.state.scale,
                "growth_tracker": self.state.growth_tracker,
                "dynamic": self.dynamic,
                "hysteresis_tracker": self.state.hysteresis_tracker}

    def load_state_dict(self, sd):
        self.dynamic = bool(sd["dynamic"])
        self.state = ScalerState(
            scale=jnp.asarray(sd["scale"], jnp.float32),
            growth_tracker=jnp.asarray(sd["growth_tracker"], jnp.int32),
            dynamic=jnp.asarray(1.0 if self.dynamic else 0.0, jnp.float32),
            # pre-hysteresis checkpoints restore to a full tracker
            hysteresis_tracker=jnp.asarray(
                sd.get("hysteresis_tracker", self._hysteresis), jnp.int32),
        )
