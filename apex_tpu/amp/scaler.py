"""Dynamic loss scaler — reference: apex/amp/scaler.py:LossScaler.

Keeps the reference's algorithm (init 2**16, halve on overflow, double after
``growth_interval=2000`` clean steps — frontend.py dynamic defaults) but the
state lives as device scalars updated functionally inside the jitted
optimizer step, so no host sync is needed per step. ``found_inf`` comes from
the fused stats kernel (the noop_flag analog of multi_tensor_scale).

On TPU the default precision is bf16 (same exponent range as fp32), so the
scaler is a no-op unless an fp16 policy or explicit scale is requested —
matching SURVEY.md §3.1's translation note.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp


class ScalerState(NamedTuple):
    scale: jax.Array            # f32 scalar
    growth_tracker: jax.Array   # i32 scalar — clean steps since last growth
    dynamic: jax.Array          # f32 0/1 flag (static per scaler, kept for pytree)


class LossScaler:
    """API mirror of apex/amp/scaler.py:LossScaler."""

    def __init__(self, loss_scale: Union[float, str] = 1.0,
                 init_scale: float = 2.0 ** 16,
                 scale_factor: float = 2.0,
                 scale_window: int = 2000,
                 min_loss_scale: float = 1.0,
                 max_loss_scale: float = 2.0 ** 24):
        self.dynamic = loss_scale == "dynamic"
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._min_scale = min_loss_scale
        self._max_scale = max_loss_scale  # reference default cap (frontend.py)
        init = init_scale if self.dynamic else float(loss_scale)
        self.state = ScalerState(
            scale=jnp.asarray(init, jnp.float32),
            growth_tracker=jnp.zeros((), jnp.int32),
            dynamic=jnp.asarray(1.0 if self.dynamic else 0.0, jnp.float32),
        )

    def loss_scale(self) -> jax.Array:
        return self.state.scale

    def scale_loss(self, loss):
        return loss * self.state.scale.astype(loss.dtype)

    def update(self, state: ScalerState, found_inf) -> ScalerState:
        """Pure update (traceable): halve on overflow, double after
        scale_window clean steps, clamped to [min, max] (reference
        update_scale semantics incl. the 2**24 cap). Branches on the traced
        ``state.dynamic`` flag, so a checkpoint restore that flips dynamic
        does not require re-tracing callers."""
        found = found_inf.astype(jnp.bool_)
        new_scale = jnp.where(found, state.scale / self._scale_factor, state.scale)
        tracker = jnp.where(found, 0, state.growth_tracker + 1)
        grow = tracker >= self._scale_window
        new_scale = jnp.where(grow, new_scale * self._scale_factor, new_scale)
        tracker = jnp.where(grow, 0, tracker)
        new_scale = jnp.clip(new_scale, self._min_scale, self._max_scale)
        is_dyn = state.dynamic > 0.0
        return ScalerState(
            scale=jnp.where(is_dyn, new_scale, state.scale),
            growth_tracker=jnp.where(is_dyn, tracker, state.growth_tracker),
            dynamic=state.dynamic,
        )

    # -- checkpointing (reference: amp.state_dict saves loss scalers) ---------
    def state_dict(self):
        return {"scale": self.state.scale,
                "growth_tracker": self.state.growth_tracker,
                "dynamic": self.dynamic}

    def load_state_dict(self, sd):
        self.dynamic = bool(sd["dynamic"])
        self.state = ScalerState(
            scale=jnp.asarray(sd["scale"], jnp.float32),
            growth_tracker=jnp.asarray(sd["growth_tracker"], jnp.int32),
            dynamic=jnp.asarray(1.0 if self.dynamic else 0.0, jnp.float32),
        )
