"""Host input pipeline — native C++ prefetch path (reference: the apex
examples' DALI / torch-DataLoader native loaders)."""

from apex_tpu.data.loader import FastLoader, write_token_shard

__all__ = ["FastLoader", "write_token_shard"]
