"""Token-shard dataset + prefetching loader (native C++ fast path).

Reference: the reference's input pipelines are native — apex
examples/imagenet/main_amp.py drives NVIDIA DALI with a torch-DataLoader
(C++ worker) fallback. The TPU restatement: training shards are flat
int32 token files (memory-mapped), and batch assembly (random window
gather) runs in a C++ prefetch thread (`_native.cpp`, built on first use
with g++) that double-buffers against the training step — the host input
path never blocks on the Python interpreter. A pure-numpy fallback with
the IDENTICAL PCG32 index stream serves environments without a compiler
and is the parity ground truth for the native path.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
from typing import Optional

import numpy as np

_NATIVE = None
_NATIVE_TRIED = False
_BUILD_ERROR: Optional[str] = None


def _build_native() -> Optional[object]:
    """Compile + import the extension; None when no toolchain is available."""
    global _NATIVE, _NATIVE_TRIED
    if _NATIVE_TRIED:
        return _NATIVE
    _NATIVE_TRIED = True
    global _BUILD_ERROR
    src = os.path.join(os.path.dirname(__file__), "_native.cpp")
    out_dir = os.path.join(os.path.dirname(__file__), "_build")
    ext = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(out_dir, f"_native{ext}")
    try:
        if not (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            os.makedirs(out_dir, exist_ok=True)
            include = sysconfig.get_paths()["include"]
            # compile to a temp name + atomic rename: concurrent first-use
            # builders (multi-process tests) must never dlopen a half-
            # written .so
            tmp = f"{out}.{os.getpid()}.tmp"
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   f"-I{include}", src, "-o", tmp, "-lpthread"]
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)
        import importlib.util

        # the loader derives the PyInit_* symbol from the module NAME —
        # it must be "_native" to match PyInit__native
        spec = importlib.util.spec_from_file_location("_native", out)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _NATIVE = mod
    except Exception as e:  # no toolchain / sandboxed: numpy fallback
        stderr = getattr(e, "stderr", b"")
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        _BUILD_ERROR = f"{type(e).__name__}: {e}" + (
            f"\n{stderr[-1500:]}" if stderr else "")
        _NATIVE = None
    return _NATIVE


def write_token_shard(path: str, tokens: np.ndarray) -> None:
    """Serialize a 1D int32 token stream as a flat binary shard."""
    np.asarray(tokens, np.int32).ravel().tofile(path)


class _Pcg32:
    """PCG-XSH-RR 64/32 — bit-identical to _native.cpp's Pcg32."""

    MUL = 6364136223846793005
    INC = 1442695040888963407
    MASK = (1 << 64) - 1

    def __init__(self, seed: int):
        self.state = (seed * self.MUL + self.INC) & self.MASK

    def next(self) -> int:
        old = self.state
        self.state = (old * self.MUL + self.INC) & self.MASK
        xorshifted = (((old >> 18) ^ old) >> 27) & 0xFFFFFFFF
        rot = old >> 59
        return ((xorshifted >> rot) | (xorshifted << ((-rot) & 31))) \
            & 0xFFFFFFFF


class FastLoader:
    """Iterable of ``[batch, seq_len]`` int32 batches from a token shard.

    ``native=None`` (default) uses the C++ prefetcher when it builds,
    else the numpy fallback; both draw the same PCG32 window-index
    stream, so swapping paths never changes the data order
    (tests/test_data_loader.py asserts bit-equality).
    """

    def __init__(self, path: str, batch: int, seq_len: int, seed: int = 0,
                 native: Optional[bool] = None):
        self.path, self.batch, self.seq_len = path, int(batch), int(seq_len)
        self.seed = int(seed)
        if self.batch <= 0 or self.seq_len <= 0:
            # validated HERE so both paths fail identically (the C++ side
            # double-checks; an unchecked negative would std::terminate in
            # the worker thread)
            raise ValueError("batch and seq_len must be positive")
        mod = _build_native() if native in (None, True) else None
        if native is True and mod is None:
            raise RuntimeError(
                "native loader requested but the extension failed to "
                f"build:\n{_BUILD_ERROR}")
        self._mod = mod
        if mod is not None:
            self._handle = mod.loader_open(path, self.batch, self.seq_len,
                                           self.seed)
        else:
            self._tokens = np.memmap(path, np.int32, mode="r")
            if self._tokens.size < self.seq_len:
                raise ValueError("shard smaller than one sequence")
            self._rng = _Pcg32(self.seed)

    @property
    def is_native(self) -> bool:
        return self._mod is not None

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        if self._mod is not None:
            raw = self._mod.loader_next(self._handle)
            arr = np.frombuffer(raw, np.int32)
        else:
            # inclusive of the final window (mirrors _native.cpp)
            n_windows = self._tokens.size - self.seq_len + 1
            arr = np.empty((self.batch, self.seq_len), np.int32)
            for b in range(self.batch):
                start = self._rng.next() % n_windows
                arr[b] = self._tokens[start:start + self.seq_len]
        return arr.reshape(self.batch, self.seq_len)
