// Native batch assembler for token datasets — the TPU-side analog of the
// reference's native input pipelines (apex examples/imagenet/main_amp.py
// drives NVIDIA DALI, with a torch DataLoader C++-worker fallback). JAX has
// no torch DataLoader; this extension keeps the host input path off the
// Python interpreter: a memory-mapped int32 token shard, a PCG32 index
// stream, and one std::thread assembling the NEXT batch (random window
// gather into a contiguous buffer) while the trainer consumes the current
// one — double-buffered prefetch, handed to numpy without copies.
//
// CPython C API only (pybind11 is not vendored in this environment).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Pcg32 {
  // PCG-XSH-RR 64/32 — tiny, seedable, identical to the numpy-side
  // reference implementation in loader.py (parity-tested).
  uint64_t state;
  explicit Pcg32(uint64_t seed) : state(seed * 6364136223846793005ULL + 1442695040888963407ULL) {}
  uint32_t next() {
    uint64_t old = state;
    state = old * 6364136223846793005ULL + 1442695040888963407ULL;
    uint32_t xorshifted = static_cast<uint32_t>(((old >> 18u) ^ old) >> 27u);
    uint32_t rot = static_cast<uint32_t>(old >> 59u);
    return (xorshifted >> rot) | (xorshifted << ((-rot) & 31));
  }
};

struct Loader {
  int fd = -1;
  const int32_t* tokens = nullptr;  // mmap'd
  size_t n_tokens = 0;
  size_t map_bytes = 0;
  int64_t batch = 0, seq_len = 0;
  Pcg32 rng;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> ready;      // assembled batch waiting for Python
  bool has_ready = false;
  std::atomic<bool> stop{false};

  explicit Loader(uint64_t seed) : rng(seed) {}

  void assemble(std::vector<int32_t>& out) {
    out.resize(static_cast<size_t>(batch) * seq_len);
    // inclusive of the final window so the last token is reachable
    const size_t n_windows = n_tokens - static_cast<size_t>(seq_len) + 1;
    for (int64_t b = 0; b < batch; ++b) {
      const size_t start = rng.next() % n_windows;
      std::memcpy(out.data() + b * seq_len, tokens + start,
                  sizeof(int32_t) * static_cast<size_t>(seq_len));
    }
  }

  void run() {
    std::vector<int32_t> buf;
    while (!stop.load()) {
      assemble(buf);
      std::unique_lock<std::mutex> lk(mu);
      cv.wait(lk, [&] { return !has_ready || stop.load(); });
      if (stop.load()) return;
      ready.swap(buf);
      has_ready = true;
      cv.notify_all();
    }
  }
};

void loader_capsule_destroy(PyObject* cap) {
  auto* ld = static_cast<Loader*>(PyCapsule_GetPointer(cap, "apex_tpu.Loader"));
  if (!ld) return;
  ld->stop.store(true);
  ld->cv.notify_all();
  if (ld->worker.joinable()) ld->worker.join();
  if (ld->tokens) munmap(const_cast<int32_t*>(ld->tokens), ld->map_bytes);
  if (ld->fd >= 0) close(ld->fd);
  delete ld;
}

PyObject* loader_open(PyObject*, PyObject* args) {
  const char* path;
  long long batch, seq_len;
  unsigned long long seed;
  if (!PyArg_ParseTuple(args, "sLLK", &path, &batch, &seq_len, &seed))
    return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    PyErr_SetFromErrnoWithFilename(PyExc_OSError, path);
    return nullptr;
  }
  if (batch <= 0 || seq_len <= 0) {
    close(fd);
    PyErr_SetString(PyExc_ValueError, "batch and seq_len must be positive");
    return nullptr;
  }
  if (st.st_size % static_cast<off_t>(sizeof(int32_t)) != 0) {
    close(fd);
    PyErr_SetString(PyExc_ValueError,
                    "shard size is not a multiple of int32 (corrupt/truncated"
                    " file) — parity with the numpy memmap path");
    return nullptr;
  }
  size_t n_tokens = static_cast<size_t>(st.st_size) / sizeof(int32_t);
  if (n_tokens < static_cast<size_t>(seq_len)) {
    close(fd);
    PyErr_SetString(PyExc_ValueError, "shard smaller than one sequence");
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                   MAP_PRIVATE, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    PyErr_SetFromErrno(PyExc_OSError);
    return nullptr;
  }
  auto* ld = new Loader(seed);
  ld->fd = fd;
  ld->tokens = static_cast<const int32_t*>(mem);
  ld->n_tokens = n_tokens;
  ld->map_bytes = static_cast<size_t>(st.st_size);
  ld->batch = batch;
  ld->seq_len = seq_len;
  ld->worker = std::thread([ld] { ld->run(); });
  return PyCapsule_New(ld, "apex_tpu.Loader", loader_capsule_destroy);
}

PyObject* loader_next(PyObject*, PyObject* args) {
  PyObject* cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  auto* ld = static_cast<Loader*>(PyCapsule_GetPointer(cap, "apex_tpu.Loader"));
  if (!ld) return nullptr;
  std::vector<int32_t> out;
  {
    // release the GIL while waiting on the prefetch thread
    Py_BEGIN_ALLOW_THREADS
    {
      // inner scope: the loader mutex must drop BEFORE the GIL is
      // reacquired — the capsule destructor (GIL held) joins a worker
      // that needs this mutex, so holding both orders would deadlock
      std::unique_lock<std::mutex> lk(ld->mu);
      ld->cv.wait(lk, [&] { return ld->has_ready; });
      out.swap(ld->ready);
      ld->has_ready = false;
      ld->cv.notify_all();
    }
    Py_END_ALLOW_THREADS
  }
  // hand back as a bytearray: numpy's frombuffer view of it is WRITABLE
  // (parity with the numpy fallback's np.empty batches); one copy total,
  // same as DataLoader's collate
  return PyByteArray_FromStringAndSize(
      reinterpret_cast<const char*>(out.data()),
      static_cast<Py_ssize_t>(out.size() * sizeof(int32_t)));
}

PyMethodDef methods[] = {
    {"loader_open", loader_open, METH_VARARGS,
     "loader_open(path, batch, seq_len, seed) -> capsule"},
    {"loader_next", loader_next, METH_VARARGS,
     "loader_next(capsule) -> bytes of int32 [batch*seq_len]"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_native",
                         "native token-batch prefetcher", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__native(void) { return PyModule_Create(&moduledef); }
