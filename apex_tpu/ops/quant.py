"""int8 quantized matmul (W8A8): true int8 MXU dots for serving.

Beyond reference (apex has no quantization/inference story) — this is the
TPU-native int8 recipe (the AQT pattern): per-output-channel symmetric
weight scales computed offline, DYNAMIC per-token activation scales
computed on the fly, ``int8 x int8 -> int32`` accumulation on the MXU,
then one fused dequant multiply. Weights stream from HBM at 1 byte/elem —
a 4x (vs fp32) / 2x (vs bf16) cut in the weight-fetch bandwidth that
bounds single-token decode.

Inference-only: ``round`` has zero gradient, so a quantized layer cannot
train (the tensor-parallel layers raise if asked to).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops._dispatch import interpret, pallas_call, round_up


def quantize_weight(w, *, axis: int = 1):
    """Symmetric per-output-channel int8: ``w (out, in) -> (q int8 (out,
    in), scale f32 (out,))`` with ``w ≈ q * scale[:, None]``."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.squeeze(axis).astype(jnp.float32)


# --------------------------------------------------------------------------
# quantized KV pages (docs/serving.md "Quantized KV pages")
# --------------------------------------------------------------------------
# The paged pool stores K/V narrow (int8 or fp8 e4m3) with one symmetric
# f32 scale per (page, kv_head) living beside the block table; the paged
# kernel folds the scale into its score/value dots, so a full-precision
# pool is never materialized. Same AQT recipe as the W8A8 path above,
# page-granular instead of channel-granular.

_KV_QMAX = {"int8": 127.0, "fp8": 448.0}          # e4m3 finite max


def resolve_kv_dtype(kv_dtype):
    """Map a user-facing ``kv_dtype`` to ``(jnp dtype, qmax)``.

    ``None`` -> ``None`` (full-precision pool). Accepts ``"int8"`` /
    ``jnp.int8`` and ``"fp8"`` / ``"e4m3"`` / ``jnp.float8_e4m3fn``.
    Raises a NAMED ValueError for anything else — never a silent
    full-precision fallback — and for fp8 on a jax/ml_dtypes build that
    lacks ``float8_e4m3fn``.
    """
    if kv_dtype is None:
        return None
    name = kv_dtype if isinstance(kv_dtype, str) else \
        jnp.dtype(kv_dtype).name
    if name == "int8":
        return jnp.int8, _KV_QMAX["int8"]
    if name in ("fp8", "e4m3", "float8_e4m3fn"):
        if not hasattr(jnp, "float8_e4m3fn"):
            raise ValueError(
                "kv-dtype-unsupported: fp8 KV pages need "
                "jnp.float8_e4m3fn (ml_dtypes); this build lacks it — "
                "use kv_dtype='int8'")
        return jnp.float8_e4m3fn, _KV_QMAX["fp8"]
    raise ValueError(
        f"kv-dtype-unsupported: kv_dtype={kv_dtype!r} is not a "
        f"quantized page dtype (expected None, 'int8', or 'fp8'/'e4m3')")


def kv_qmax(dtype) -> float:
    """qmax of a quantized page dtype already in the pool (int8 -> 127,
    e4m3 -> 448); raises on a non-quantized dtype."""
    name = jnp.dtype(dtype).name
    if name == "int8":
        return _KV_QMAX["int8"]
    if name == "float8_e4m3fn":
        return _KV_QMAX["fp8"]
    raise ValueError(f"kv-dtype-unsupported: {name} is not a quantized "
                     f"KV page dtype")


def is_quantized_kv(dtype) -> bool:
    name = jnp.dtype(dtype).name
    return name == "int8" or name.startswith("float8")


def kv_cast(x, qdtype, qmax):
    """Cast an already-scale-normalized tensor to the page dtype:
    round+clip for int8, saturate-clip for fp8 (the cast rounds)."""
    if jnp.dtype(qdtype) == jnp.int8:
        return jnp.clip(jnp.round(x), -qmax, qmax).astype(jnp.int8)
    return jnp.clip(x, -qmax, qmax).astype(qdtype)


def kv_quantize(x, qdtype, qmax, *, axes):
    """Symmetric quantization over ``axes``: returns ``(q, scale)`` with
    ``x ≈ q.astype(f32) * scale`` (scale broadcast over ``axes``). An
    all-zero group gets scale 0 and quantizes to exact zeros (dequant by
    multiply restores them exactly)."""
    xf = jnp.asarray(x, jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    scale = amax / qmax
    inv = jnp.where(scale > 0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    return kv_cast(xf * inv, qdtype, qmax), scale


def int8_matmul(x, qw, scale):
    """``y = x @ dequant(qw).T`` via an int8 MXU dot.

    x: (..., in) float; qw: (out, in) int8; scale: (out,) f32 per-channel.
    Per-token activation scales (amax/127) quantize x on the fly; the
    contraction accumulates in int32; the result dequantizes by
    ``sx * scale`` and casts back to x.dtype.
    """
    xf = x.astype(jnp.float32)
    sx = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                     1e-8) / 127.0
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, qw,
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * scale.astype(jnp.float32)) \
        .astype(x.dtype)


# --------------------------------------------------------------------------
# quantized weight streaming (docs/serving.md "Quantized weight streaming")
# --------------------------------------------------------------------------
# The serving decode step is weight-bound: every step streams the full
# block-linear weight set from HBM. These helpers store those weights
# narrow — int8 / fp8 e4m3 per-output-channel, or int4 nibbles with
# per-(out-channel, group) scales — and the fused Pallas kernel below
# dequantizes in VMEM right next to the contraction, so a full-precision
# weight tree is never materialized (the weight analog of the quantized
# KV pages above).

_WEIGHT_QMAX = {"int8": 127.0, "fp8": 448.0, "int4": 7.0}


def resolve_weight_dtype(mode) -> Optional[str]:
    """Map a user-facing weight-quantization ``mode`` to its canonical
    kind: ``"int8"``, ``"fp8"``, or ``"int4"``.

    ``None``/``False`` -> ``None`` (full-precision weights); ``True`` is
    the back-compat alias for ``"int8"`` (the historical ``quantize_int8``
    switch). Accepts ``"int8"``/``jnp.int8`` and ``"fp8"``/``"e4m3"``/
    ``jnp.float8_e4m3fn``. Raises a NAMED ValueError for anything else —
    never a silent full-precision fallback — and for fp8 on a
    jax/ml_dtypes build that lacks ``float8_e4m3fn``.
    """
    if mode is None or mode is False:
        return None
    if mode is True:
        return "int8"
    name = mode if isinstance(mode, str) else jnp.dtype(mode).name
    if name == "int8":
        return "int8"
    if name in ("fp8", "e4m3", "float8_e4m3fn"):
        if not hasattr(jnp, "float8_e4m3fn"):
            raise ValueError(
                "weight-dtype-unsupported: fp8 weight buffers need "
                "jnp.float8_e4m3fn (ml_dtypes); this build lacks it — "
                "use 'int8'")
        return "fp8"
    if name == "int4":
        return "int4"
    raise ValueError(
        f"weight-dtype-unsupported: mode={mode!r} is not a quantized "
        f"weight dtype (expected None, 'int8', 'fp8'/'e4m3', or 'int4')")


def weight_storage_dtype(kind: str):
    """jnp dtype a quantized weight buffer is stored as (int4 packs two
    nibbles per uint8 byte)."""
    return {"int8": jnp.int8,
            "fp8": getattr(jnp, "float8_e4m3fn", None),
            "int4": jnp.uint8}[kind]


def validate_int4_group(in_features: int, group_size: int) -> None:
    """Named errors for the int4 grouping contract: power-of-two group,
    ``in_features`` an exact multiple of it."""
    if group_size < 2 or (group_size & (group_size - 1)) != 0:
        raise ValueError(
            f"int4-group-invalid: group_size={group_size} must be a "
            "power of two >= 2")
    if in_features % group_size:
        raise ValueError(
            f"int4-group-invalid: in_features={in_features} is not a "
            f"multiple of group_size={group_size}")


def quantize_weight_fp8(w, *, axis: int = 1):
    """Symmetric per-output-channel fp8 e4m3: ``w (out, in) -> (q e4m3,
    scale f32 (out,))`` with ``w ≈ q.astype(f32) * scale[:, None]``."""
    resolve_weight_dtype("fp8")            # raises on builds without e4m3
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / _WEIGHT_QMAX["fp8"]
    q = jnp.clip(w / scale, -_WEIGHT_QMAX["fp8"], _WEIGHT_QMAX["fp8"]) \
        .astype(jnp.float8_e4m3fn)
    return q, scale.squeeze(axis).astype(jnp.float32)


def pack_int4(q, *, group_size: int):
    """Pack int4 values ``q (out, in)`` (each in [-8, 7]) into uint8
    nibbles, GROUP-LOCALLY: byte ``j`` of a group's ``group_size // 2``
    bytes holds the group's value ``j`` (low nibble, biased +8) and its
    value ``j + group_size//2`` (high nibble). Packing never crosses a
    group boundary, so a contiguous slice of whole groups along the
    packed axis IS the packed form of those groups — tensor-parallel
    row-sharding slices packed weights directly (serving/tp.py)."""
    out, n = q.shape
    validate_int4_group(n, group_size)
    h = group_size // 2
    qg = q.astype(jnp.int32).reshape(out, n // group_size, group_size)
    packed = (qg[..., :h] + 8) | ((qg[..., h:] + 8) << 4)
    return packed.astype(jnp.uint8).reshape(out, n // 2)


def unpack_int4(packed, *, group_size: int):
    """Inverse of :func:`pack_int4`: ``(out, n//2) uint8 -> (out, n)
    int8`` values in [-8, 7], same group-local layout."""
    out, half = packed.shape
    h = group_size // 2
    p = packed.astype(jnp.int32).reshape(out, half // h, h)
    lo = (p & 15) - 8
    hi = (p >> 4) - 8
    return jnp.concatenate([lo, hi], axis=-1).astype(jnp.int8) \
        .reshape(out, 2 * half)


def quantize_weight_int4(w, *, group_size: int = 128):
    """Symmetric per-(out-channel, group) int4: ``w (out, in) ->
    (packed uint8 (out, in//2), scales f32 (n_groups, out))`` with
    ``n_groups = in // group_size`` and, within group ``g``,
    ``w[o, g*gs:(g+1)*gs] ≈ q * scales[g, o]``.

    The scale layout keeps the OUT channel minor (lane-friendly Mosaic
    blocks; shards ``P(model)`` with the output axis under column-
    parallel TP) and the group axis major (contiguous slices of whole
    groups are a row-parallel rank's exact scales). Each group packs its
    own two halves together (:func:`pack_int4`), so the packed bytes of
    group ``g`` are the contiguous columns ``[g*gs//2, (g+1)*gs//2)``.
    """
    w = jnp.asarray(w, jnp.float32)
    out, n = w.shape
    validate_int4_group(n, group_size)
    ng = n // group_size
    wg = w.reshape(out, ng, group_size)
    amax = jnp.max(jnp.abs(wg), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / _WEIGHT_QMAX["int4"]
    q = jnp.clip(jnp.round(wg / scale), -7, 7).reshape(out, n)
    return pack_int4(q.astype(jnp.int8), group_size=group_size), \
        scale[:, :, 0].T.astype(jnp.float32)


def dequantize_weight(qw, scale):
    """Reference dequantizer for every storage kind — the parity oracle
    for the fused kernel. int8/fp8: ``(out, in) x (out,)``; int4-packed:
    ``(out, in//2) uint8 x (n_groups, out)``. Returns f32 ``(out, in)``."""
    if qw.dtype == jnp.uint8:
        out, half = qw.shape
        ng = scale.shape[0]
        gs = 2 * half // ng
        vals = unpack_int4(qw, group_size=gs).reshape(out, ng, gs)
        return (vals.astype(jnp.float32)
                * scale.T[:, :, None]).reshape(out, 2 * half)
    return qw.astype(jnp.float32) * scale[:, None].astype(jnp.float32)


# --- the fused dequant-matmul decode kernel -------------------------------

def _block_out(out: int) -> int:
    """Output-channel tile: 256 when it divides (two 128-lane registers),
    else 128, else the full dim (sub-tile dims must equal the array's —
    tiny test models; interpret mode only)."""
    for b in (256, 128):
        if out % b == 0:
            return b
    return out


def _fused_wq_kernel(x_ref, w_ref, s_ref, o_ref):
    """Per-channel (int8/fp8) body: widen the weight block in VMEM, one
    MXU dot, scale as the output epilogue — no fp weight ever in HBM."""
    xf = x_ref[...].astype(jnp.float32)
    wf = w_ref[...].astype(jnp.float32)
    acc = jax.lax.dot_general(xf, wf, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[...] = acc * s_ref[...]          # (1, block_out) broadcasts


def _fused_w4_kernel(x_ref, w_ref, s_ref, o_ref, *, group_size: int,
                     n_groups: int):
    """int4-grouped body: unpack biased nibbles in VMEM, one small dot
    per group (statically unrolled) scaled by that group's (1, block_out)
    scale row. Group-local packing keeps every slice contiguous."""
    h = group_size // 2
    wi = w_ref[...].astype(jnp.int32)
    lo = ((wi & 15) - 8).astype(jnp.float32)
    hi = ((wi >> 4) - 8).astype(jnp.float32)
    xf = x_ref[...].astype(jnp.float32)
    acc = jnp.zeros(o_ref.shape, jnp.float32)
    for g in range(n_groups):
        wq = jnp.concatenate([lo[:, g * h:(g + 1) * h],
                              hi[:, g * h:(g + 1) * h]], axis=1)
        xg = xf[:, g * group_size:(g + 1) * group_size]
        acc += jax.lax.dot_general(
            xg, wq, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * s_ref[g:g + 1, :]
    o_ref[...] = acc


def fused_dequant_matmul(x, qw, scale):
    """``y = x @ dequant(qw).T`` with dequant fused into the kernel.

    x: ``(..., in)`` float; ``(qw, scale)`` from :func:`quantize_weight`
    (int8), :func:`quantize_weight_fp8` (e4m3), or
    :func:`quantize_weight_int4` (packed nibbles + grouped scales — the
    storage kind is inferred from the dtypes/shapes). The weights stream
    from HBM at their narrow width and widen only inside VMEM, block by
    block, next to the contraction — unlike :func:`int8_matmul` there is
    no per-call fp32 activation quantize/dequant roundtrip, so the
    result equals the dequantizing reference to f32 dot accuracy
    (weight-only quantization, W8A16-style). Result dtype follows x.
    """
    from jax.experimental import pallas as pl

    int4 = qw.dtype == jnp.uint8
    out = qw.shape[0]
    n_in = 2 * qw.shape[1] if int4 else qw.shape[1]
    lead = x.shape[:-1]
    if x.shape[-1] != n_in:
        raise ValueError(
            f"fused_dequant_matmul: x has {x.shape[-1]} features, the "
            f"quantized weight dequantizes to (out={out}, in={n_in})")
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, n_in)
    m_pad = round_up(max(m, 1), 8)
    if m_pad != m:
        x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
    bo = _block_out(out)
    grid = (out // bo,)
    if int4:
        ng = scale.shape[0]
        gs = n_in // ng
        kernel = lambda *refs: _fused_w4_kernel(*refs, group_size=gs,
                                                n_groups=ng)
        w_spec = pl.BlockSpec((bo, n_in // 2), lambda j: (j, 0))
        s2 = scale                               # (n_groups, out)
        s_spec = pl.BlockSpec((ng, bo), lambda j: (0, j))
    else:
        kernel = _fused_wq_kernel
        w_spec = pl.BlockSpec((bo, n_in), lambda j: (j, 0))
        s2 = scale.astype(jnp.float32).reshape(1, out)
        s_spec = pl.BlockSpec((1, bo), lambda j: (0, j))
    y = pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m_pad, out), jnp.float32),
        grid=grid,
        in_specs=[pl.BlockSpec((m_pad, n_in), lambda j: (0, 0)),
                  w_spec, s_spec],
        out_specs=pl.BlockSpec((m_pad, bo), lambda j: (0, j)),
        interpret=interpret(),
    )(x2, qw, s2.astype(jnp.float32))
    return y[:m].reshape(*lead, out).astype(x.dtype)


# --- per-layer-class precision policy (the amp opt-level analog) ----------

@dataclasses.dataclass(frozen=True)
class WeightPrecisionPolicy:
    """Which precision each layer CLASS serves at (PAPER.md's ``apex.amp``
    O0–O3 opt levels, restated for weight streaming):

    ==============  =============================================
    layer class     precision
    ==============  =============================================
    embeddings      fp (``param_dtype``) — lookup, never streamed hot
    norms, biases   fp (``param_dtype``) — O(hidden) bytes, accuracy-critical
    lm head         fp (``param_dtype``) — logit fidelity
    block linears   ``linears``: None | 'int8' | 'fp8' | 'int4'
    ==============  =============================================

    ``group_size`` applies to the int4-grouped path only (power of two;
    per-(out-channel, group) scales). ``quantize_int8=True`` on a model
    config is the back-compat alias for ``WeightPrecisionPolicy('int8')``.
    """

    linears: Optional[str] = "int8"
    group_size: int = 128

    def __post_init__(self):
        kind = resolve_weight_dtype(self.linears)
        object.__setattr__(self, "linears", kind)
        if kind == "int4" and (self.group_size < 2
                               or self.group_size & (self.group_size - 1)):
            raise ValueError(
                f"int4-group-invalid: group_size={self.group_size} must "
                "be a power of two >= 2")

    @staticmethod
    def resolve(policy: Optional["WeightPrecisionPolicy"],
                quantize_int8: bool) -> Optional["WeightPrecisionPolicy"]:
        """The ONE resolution rule for a model config carrying both the
        legacy ``quantize_int8`` flag and a ``weight_policy``: the flag
        is the int8-everywhere policy; setting both to conflicting
        answers is a named error, never a silent pick."""
        if policy is not None and policy.linears is None:
            policy = None
        if policy is None:
            return WeightPrecisionPolicy("int8") if quantize_int8 else None
        if quantize_int8 and policy.linears != "int8":
            raise ValueError(
                "weight-policy-conflict: quantize_int8=True is the "
                f"int8-everywhere policy but weight_policy asks for "
                f"{policy.linears!r} — set one, not both")
        return policy
