"""int8 quantized matmul (W8A8): true int8 MXU dots for serving.

Beyond reference (apex has no quantization/inference story) — this is the
TPU-native int8 recipe (the AQT pattern): per-output-channel symmetric
weight scales computed offline, DYNAMIC per-token activation scales
computed on the fly, ``int8 x int8 -> int32`` accumulation on the MXU,
then one fused dequant multiply. Weights stream from HBM at 1 byte/elem —
a 4x (vs fp32) / 2x (vs bf16) cut in the weight-fetch bandwidth that
bounds single-token decode.

Inference-only: ``round`` has zero gradient, so a quantized layer cannot
train (the tensor-parallel layers raise if asked to).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_weight(w, *, axis: int = 1):
    """Symmetric per-output-channel int8: ``w (out, in) -> (q int8 (out,
    in), scale f32 (out,))`` with ``w ≈ q * scale[:, None]``."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.squeeze(axis).astype(jnp.float32)


def int8_matmul(x, qw, scale):
    """``y = x @ dequant(qw).T`` via an int8 MXU dot.

    x: (..., in) float; qw: (out, in) int8; scale: (out,) f32 per-channel.
    Per-token activation scales (amax/127) quantize x on the fly; the
    contraction accumulates in int32; the result dequantizes by
    ``sx * scale`` and casts back to x.dtype.
    """
    xf = x.astype(jnp.float32)
    sx = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                     1e-8) / 127.0
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, qw,
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * scale.astype(jnp.float32)) \
        .astype(x.dtype)
