"""int8 quantized matmul (W8A8): true int8 MXU dots for serving.

Beyond reference (apex has no quantization/inference story) — this is the
TPU-native int8 recipe (the AQT pattern): per-output-channel symmetric
weight scales computed offline, DYNAMIC per-token activation scales
computed on the fly, ``int8 x int8 -> int32`` accumulation on the MXU,
then one fused dequant multiply. Weights stream from HBM at 1 byte/elem —
a 4x (vs fp32) / 2x (vs bf16) cut in the weight-fetch bandwidth that
bounds single-token decode.

Inference-only: ``round`` has zero gradient, so a quantized layer cannot
train (the tensor-parallel layers raise if asked to).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_weight(w, *, axis: int = 1):
    """Symmetric per-output-channel int8: ``w (out, in) -> (q int8 (out,
    in), scale f32 (out,))`` with ``w ≈ q * scale[:, None]``."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.squeeze(axis).astype(jnp.float32)


# --------------------------------------------------------------------------
# quantized KV pages (docs/serving.md "Quantized KV pages")
# --------------------------------------------------------------------------
# The paged pool stores K/V narrow (int8 or fp8 e4m3) with one symmetric
# f32 scale per (page, kv_head) living beside the block table; the paged
# kernel folds the scale into its score/value dots, so a full-precision
# pool is never materialized. Same AQT recipe as the W8A8 path above,
# page-granular instead of channel-granular.

_KV_QMAX = {"int8": 127.0, "fp8": 448.0}          # e4m3 finite max


def resolve_kv_dtype(kv_dtype):
    """Map a user-facing ``kv_dtype`` to ``(jnp dtype, qmax)``.

    ``None`` -> ``None`` (full-precision pool). Accepts ``"int8"`` /
    ``jnp.int8`` and ``"fp8"`` / ``"e4m3"`` / ``jnp.float8_e4m3fn``.
    Raises a NAMED ValueError for anything else — never a silent
    full-precision fallback — and for fp8 on a jax/ml_dtypes build that
    lacks ``float8_e4m3fn``.
    """
    if kv_dtype is None:
        return None
    name = kv_dtype if isinstance(kv_dtype, str) else \
        jnp.dtype(kv_dtype).name
    if name == "int8":
        return jnp.int8, _KV_QMAX["int8"]
    if name in ("fp8", "e4m3", "float8_e4m3fn"):
        if not hasattr(jnp, "float8_e4m3fn"):
            raise ValueError(
                "kv-dtype-unsupported: fp8 KV pages need "
                "jnp.float8_e4m3fn (ml_dtypes); this build lacks it — "
                "use kv_dtype='int8'")
        return jnp.float8_e4m3fn, _KV_QMAX["fp8"]
    raise ValueError(
        f"kv-dtype-unsupported: kv_dtype={kv_dtype!r} is not a "
        f"quantized page dtype (expected None, 'int8', or 'fp8'/'e4m3')")


def kv_qmax(dtype) -> float:
    """qmax of a quantized page dtype already in the pool (int8 -> 127,
    e4m3 -> 448); raises on a non-quantized dtype."""
    name = jnp.dtype(dtype).name
    if name == "int8":
        return _KV_QMAX["int8"]
    if name == "float8_e4m3fn":
        return _KV_QMAX["fp8"]
    raise ValueError(f"kv-dtype-unsupported: {name} is not a quantized "
                     f"KV page dtype")


def is_quantized_kv(dtype) -> bool:
    name = jnp.dtype(dtype).name
    return name == "int8" or name.startswith("float8")


def kv_cast(x, qdtype, qmax):
    """Cast an already-scale-normalized tensor to the page dtype:
    round+clip for int8, saturate-clip for fp8 (the cast rounds)."""
    if jnp.dtype(qdtype) == jnp.int8:
        return jnp.clip(jnp.round(x), -qmax, qmax).astype(jnp.int8)
    return jnp.clip(x, -qmax, qmax).astype(qdtype)


def kv_quantize(x, qdtype, qmax, *, axes):
    """Symmetric quantization over ``axes``: returns ``(q, scale)`` with
    ``x ≈ q.astype(f32) * scale`` (scale broadcast over ``axes``). An
    all-zero group gets scale 0 and quantizes to exact zeros (dequant by
    multiply restores them exactly)."""
    xf = jnp.asarray(x, jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    scale = amax / qmax
    inv = jnp.where(scale > 0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    return kv_cast(xf * inv, qdtype, qmax), scale


def int8_matmul(x, qw, scale):
    """``y = x @ dequant(qw).T`` via an int8 MXU dot.

    x: (..., in) float; qw: (out, in) int8; scale: (out,) f32 per-channel.
    Per-token activation scales (amax/127) quantize x on the fly; the
    contraction accumulates in int32; the result dequantizes by
    ``sx * scale`` and casts back to x.dtype.
    """
    xf = x.astype(jnp.float32)
    sx = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                     1e-8) / 127.0
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, qw,
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * scale.astype(jnp.float32)) \
        .astype(x.dtype)
