"""Pallas paged-attention decode kernel (vLLM-style, Kwon et al. 2023).

Serving keeps each sequence's KV cache in fixed-size PAGES of a shared
static pool (``apex_tpu/serving/kv_pool.py``) instead of one contiguous
``(batch, kv, max_len, d)`` buffer per request batch: a sequence owns
``ceil(len/page_size)`` pages named by its int32 block table, so HBM is
allocated by actual length, freed pages are reusable the moment a request
retires, and admission never reshapes anything.

This kernel computes GQA attention for a small static block of ``s``
decode queries per slot (``s=1`` is plain decode; ``s=k`` verifies a
speculative draft chunk in one pass; ``s``-sized chunks carry interleaved
prefill) directly against the page pool. The block table rides in as a
SCALAR-PREFETCH operand (``pltpu.PrefetchScalarGridSpec``) so the k/v
BlockSpec index maps resolve the physical page for grid step ``j`` —
``block_tables[b, j]`` — before the body runs: each (page_size, d) page
tile is DMA'd HBM->VMEM exactly once, and the gather never materializes a
contiguous copy of the sequence. Online softmax (m, l, acc) carries across
the sequential page axis exactly like flash_attention's k-block axis; fp32
scores and accumulation (same numerics contract). The ``s`` queries of a
slot occupy positions ``lengths[b] - s + i`` (``i`` in ``0..s-1``), so the
causal/window mask is a per-query-position band — the grid, the page
skip, and the softmax carry are untouched by the generalization.

Layout: the pool is ``(num_pages, kv_heads, page_size, head_dim)`` — the
page tile's minor two dims are then ``(page_size, head_dim)``, which
satisfies Mosaic's (sublane, lane)-or-full-dim block rule for
``page_size`` a sublane multiple and the usual head dims (64 = full minor
dim, 128 = lane multiple). GQA queries reshape to ``(b, kv, rep, d)`` and
contract against the UNexpanded kv-head pages (``rep`` = full dim), the
same no-repeat discipline as flash_attention and cached_attention.

Off-TPU the kernel runs through the Pallas interpreter
(``ops/_dispatch.interpret``), so CPU tests cover the real kernel code.

Tensor parallelism (``serving/tp.py``, docs/tp_serving.md): the kernel
is TP-native by shape, not by flag. Heads never interact — the grid's
``kv_head`` axis is embarrassingly parallel — so inside ``shard_map``
with the pool sharded along its kv-head axis, each chip calls this
kernel on its LOCAL ``(num_pages, kv_heads/tp, page_size, d)`` shard
with its local query heads and the REPLICATED block tables / lengths:
the same ``h % kv == 0`` GQA contract holds locally (both counts divide
by ``tp`` — GQA groups partition whole), no collective appears here,
and the single TP all-reduce happens after the attention out-projection
(the Megatron row-parallel layer), never inside the kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import _dispatch
from apex_tpu.ops.flash_attention import DEFAULT_MASK_VALUE

_INTERPRET = _dispatch.interpret


def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, *rest, scale,
                  page_size, max_pages, s_q, rep, window=None,
                  quantized=False):
    if quantized:
        # two extra scalar operands: this page's per-kv-head symmetric
        # dequant scales, prefetched by the same bt[b, j] index map as
        # the page tiles (docs/serving.md "Quantized KV pages")
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    seq_len = len_ref[b]

    # page j holds absolute positions [j*ps, (j+1)*ps): dead pages (at or
    # past the sequence end) skip both their FLOPs and their accumulator
    # update; their DMA fetched whatever page id the table holds (0 = the
    # reserved null page) — never read, so never wrong
    page_live = j * page_size < seq_len
    if window is not None:
        # sliding-window band: query i of the block sits at position
        # seq_len - s_q + i and attends (pos_i - window, pos_i]. A page
        # whose LAST position is at or below the EARLIEST query's band
        # floor (seq_len - s_q) - window is dead for every query in the
        # block and every later step (the band only moves forward) — the
        # serving engine drops such pages from the block table entirely
        # (kv_pool.drop_slot_pages), and this gate skips whatever the
        # dropped entry now points at (the null page)
        page_live = jnp.logical_and(
            page_live, (j + 1) * page_size + window + s_q - 1 > seq_len)

    @pl.when(page_live)
    def _body():
        q = q_ref[0, 0]                                   # (s_q*rep, d)
        k = k_ref[0, 0]                                   # (ps, d)
        if quantized:
            # dequant is a SCALAR fold, never a widened tensor: the
            # page's k-scale rides the score multiply (q.k * sk == q.
            # (k*sk)), the v-scale rides p before the value dot — the
            # narrow page is cast in VMEM, the f32 pool never exists.
            # int8 (<=127) and e4m3 (<=448) values are exact in bf16/f32
            k = k.astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (s_q*rep, ps)
        if quantized:
            # keep the scale a (1, 1) array and broadcast — extracting a
            # true scalar from a VMEM tile is an unsupported shape cast
            s = s * ks_ref[0, 0]
        pos = lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * page_size
        # rows are position-major: row r is query position seq_len - s_q
        # + r // rep (each query's rep GQA heads are adjacent rows)
        qpos = (seq_len - s_q
                + lax.broadcasted_iota(jnp.int32, s.shape, 0) // rep)
        live = pos <= qpos
        if window is not None:
            # positions inside a live page but below a query's band
            # floor mask out — exactly cached_attention_rolling's band,
            # per query position
            live = jnp.logical_and(live, pos > qpos - window)
        s = jnp.where(live, s, DEFAULT_MASK_VALUE)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(live, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = m_new
        v = v_ref[0, 0]
        if quantized:
            p_in, v_in = p * vs_ref[0, 0], v.astype(jnp.float32)
        else:
            p_in, v_in = p.astype(v.dtype), v
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p_in, v_in, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == max_pages - 1)
    def _finish():
        l = l_ref[...]
        # a zero-length slot (idle serving slot) outputs exactly 0
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def _validate(q, k_pages, v_pages, block_tables, lengths, window=None,
              k_scales=None, v_scales=None):
    if window is not None and (not isinstance(window, int) or window < 1):
        raise ValueError(f"window must be a static positive int, got "
                         f"{window!r}")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be passed together "
                         "(a quantized pool quantizes both tensors)")
    if k_scales is not None:
        want = k_pages.shape[:2]
        for name, sc in (("k_scales", k_scales), ("v_scales", v_scales)):
            if sc.shape != want:
                raise ValueError(
                    f"{name} must be (num_pages, kv_heads) = {want} "
                    f"per-page/per-kv-head scales, got {sc.shape}")
            if not jnp.issubdtype(sc.dtype, jnp.floating):
                raise ValueError(f"{name} must be float scales, got "
                                 f"{sc.dtype}")
    if q.ndim != 4:
        raise ValueError(f"q must be (batch, heads, s, d) decode-block "
                         f"queries, got {q.shape}")
    if k_pages.shape != v_pages.shape:
        raise ValueError(f"k_pages {k_pages.shape} != v_pages "
                         f"{v_pages.shape}")
    num_pages, kv, page_size, d = k_pages.shape
    b, h, s_q, qd = q.shape
    if not 1 <= s_q <= page_size:
        # the block's s queries live inside the last ceil(s/ps)+1 pages;
        # bounding s by the page size keeps the per-page band mask a
        # single iota comparison and the VMEM q tile small. Larger
        # chunks belong to the prefill path (flash attention), the same
        # split cached_attention_rolling documents for the rolling cache
        raise ValueError(
            f"paged attention takes query blocks of 1..page_size "
            f"({page_size}) positions per step, got s={s_q}; longer "
            f"chunks must use the contiguous prefill path")
    if qd != d:
        raise ValueError(f"head_dim mismatch: q {qd} vs pages {d}")
    if h % kv != 0:
        raise ValueError(f"q heads ({h}) must be a multiple of kv heads "
                         f"({kv})")
    if page_size % 8 != 0:
        raise ValueError(f"page_size must be a sublane multiple (8), got "
                         f"{page_size}")
    if block_tables.ndim != 2 or block_tables.shape[0] != b:
        raise ValueError(f"block_tables must be (batch, max_pages), got "
                         f"{block_tables.shape} for batch {b}")
    if lengths.shape != (b,):
        raise ValueError(f"lengths must be ({b},), got {lengths.shape}")


def paged_attention(q, k_pages, v_pages, block_tables, lengths, *,
                    scale: Optional[float] = None,
                    window: Optional[int] = None,
                    k_scales=None, v_scales=None):
    """Decode-block GQA attention over a paged KV pool.

    Args:
      q: ``(batch, heads, s, head_dim)`` — this step's query block,
        ``s`` consecutive tokens per sequence slot (``1 <= s <=
        page_size``; ``s=1`` is plain decode, ``s=k`` verifies a
        speculative draft chunk, ``s``-sized chunks carry interleaved
        prefill). Query ``i`` sits at absolute position
        ``lengths[b] - s + i``.
      k_pages / v_pages: ``(num_pages, kv_heads, page_size, head_dim)``
        shared page pool (``kv_heads`` divides ``heads``; GQA never
        expands). Inside a tensor-parallel ``shard_map`` region both
        counts are the LOCAL per-chip head shard (``serving/tp.py``) —
        the kernel is chip-count-blind.
      block_tables: int32 ``(batch, max_pages)``; entry ``[b, j]`` is the
        physical page holding slot ``b``'s positions
        ``[j*page_size, (j+1)*page_size)``. Entries past a sequence's
        allocation must hold a VALID page id (the pool reserves page 0 as
        a null page) — they are fetched by the pipeline but never read.
      lengths: int32 ``(batch,)`` — valid positions per slot INCLUDING
        all ``s`` current tokens (their K/V must already be written to
        the pool). Length 0 (idle slot) outputs exactly 0; a slot whose
        length is shorter than ``s`` zeroes the leading (pre-sequence)
        query rows.
      scale: softmax scale; default ``1/sqrt(head_dim)``.
      window: optional STATIC sliding-window band (Mistral-style): the
        query at position ``p_i = lengths[b] - s + i`` attends only
        positions ``(p_i - window, p_i]`` — the exact band
        ``cached_attention``/``cached_attention_rolling`` mask applied
        per query position, so a windowed model's paged decode is
        token-identical to its contiguous/rolling decode. Pages fully
        below every query's band skip their FLOPs (and may be dropped
        from the block table entirely — the serving engine's
        O(window)-HBM trick, ``kv_pool.drop_slot_pages``).
      k_scales / v_scales: f32 ``(num_pages, kv_heads)`` per-page,
        per-kv-head symmetric dequant scales of a QUANTIZED pool
        (int8 / fp8 e4m3 pages, ``kv_pool.init_paged_cache(kv_dtype=)``)
        — ``true_k[p, h] = k_pages[p, h].astype(f32) * k_scales[p, h]``.
        Both or neither. The kernel prefetches each page's two scalars
        through the same ``bt[b, j]`` index map as the page tiles and
        folds them into the score / value dots, so the dequantized pool
        is never materialized. Under TP they shard along the kv-head
        axis with the pages.

    Returns ``(batch, heads, s, head_dim)`` in ``q.dtype``.
    """
    _validate(q, k_pages, v_pages, block_tables, lengths, window,
              k_scales, v_scales)
    quantized = k_scales is not None
    num_pages, kv, page_size, d = k_pages.shape
    b, h, s_q = q.shape[0], q.shape[1], q.shape[2]
    rep = h // kv
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    # position-major row layout: row i*rep + r is query position i of
    # GQA group-member r, so the kernel recovers the position as
    # row // rep with the group's rows adjacent (one contraction for
    # all s*rep rows against the page tile — same dot shape as s=1,
    # just taller)
    qr = (q.reshape(b, kv, rep, s_q, d).transpose(0, 1, 3, 2, 4)
          .reshape(b, kv, s_q * rep, d))
    bt = block_tables.astype(jnp.int32)
    ln = lengths.astype(jnp.int32)

    in_specs = [
        pl.BlockSpec((1, 1, s_q * rep, d),
                     lambda b, h, j, bt, ln: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, page_size, d),
                     lambda b, h, j, bt, ln: (bt[b, j], h, 0, 0)),
        pl.BlockSpec((1, 1, page_size, d),
                     lambda b, h, j, bt, ln: (bt[b, j], h, 0, 0)),
    ]
    operands = [bt, ln, qr, k_pages, v_pages]
    if quantized:
        # one scalar scale block per (page, kv_head) grid step, resolved
        # by the SAME scalar-prefetched bt[b, j] map as the page tiles.
        # The (pages, kv) array is viewed as (pages, kv, 1, 1) so the
        # block's last two dims EQUAL the array's — the only legal shape
        # for a sub-(8, 128) VMEM block under Mosaic's tiling rules
        # (same trick as the upstream quantized paged-attention kernels)
        in_specs += [
            pl.BlockSpec((1, 1, 1, 1),
                         lambda b, h, j, bt, ln: (bt[b, j], h, 0, 0)),
            pl.BlockSpec((1, 1, 1, 1),
                         lambda b, h, j, bt, ln: (bt[b, j], h, 0, 0)),
        ]
        operands += [k_scales.astype(jnp.float32)[:, :, None, None],
                     v_scales.astype(jnp.float32)[:, :, None, None]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kv, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, s_q * rep, d),
                               lambda b, h, j, bt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((s_q * rep, d), jnp.float32),
            pltpu.VMEM((s_q * rep, 1), jnp.float32),
            pltpu.VMEM((s_q * rep, 1), jnp.float32),
        ],
    )
    out = _dispatch.pallas_call(
        functools.partial(_paged_kernel, scale=float(scale),
                          page_size=page_size, max_pages=max_pages,
                          s_q=s_q, rep=rep, window=window,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv, s_q * rep, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET(),
    )(*operands)
    return (out.reshape(b, kv, s_q, rep, d).transpose(0, 1, 3, 2, 4)
            .reshape(b, h, s_q, d))


def paged_attention_reference(q, k_pages, v_pages, block_tables, lengths, *,
                              scale: Optional[float] = None,
                              window: Optional[int] = None,
                              k_scales=None, v_scales=None):
    """Pure-jnp ground truth: gather every table entry into a contiguous
    ``(b, kv, max_pages*page_size, d)`` view (dequantizing with the
    gathered per-page scales when given) and run dense masked GQA
    attention — O(batch * max_len) HBM, exactly what the kernel avoids."""
    _validate(q, k_pages, v_pages, block_tables, lengths, window,
              k_scales, v_scales)
    num_pages, kv, page_size, d = k_pages.shape
    b, h, s_q = q.shape[0], q.shape[1], q.shape[2]
    rep = h // kv
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    def contig(pages, scales=None):
        g = jnp.take(pages, block_tables, axis=0)      # (b, mp, kv, ps, d)
        g = g.astype(jnp.float32)
        if scales is not None:
            sc = jnp.take(scales, block_tables, axis=0)      # (b, mp, kv)
            g = g * sc.astype(jnp.float32)[..., None, None]
        return g.transpose(0, 2, 1, 3, 4).reshape(b, kv, max_pages * page_size, d)

    k = contig(k_pages, k_scales)
    v = contig(v_pages, v_scales)
    qf = q.reshape(b, kv, rep, s_q, d).astype(jnp.float32)
    s = jnp.einsum("bkrsd,bktd->bkrst", qf, k,
                   preferred_element_type=jnp.float32) * jnp.float32(scale)
    pos = jnp.arange(max_pages * page_size, dtype=jnp.int32)[
        None, None, None, None]                        # (1,1,1,1,T)
    # query i of the block sits at absolute position lengths[b] - s + i
    qpos = (lengths[:, None, None, None, None] - s_q
            + jnp.arange(s_q, dtype=jnp.int32)[None, None, None, :, None])
    mask = pos <= qpos
    if window is not None:
        mask = jnp.logical_and(mask, pos > qpos - window)
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask, p, 0.0)  # all-dead rows: softmax(-inf row) -> NaN
    ctx = jnp.einsum("bkrst,bktd->bkrsd", p, v,
                     preferred_element_type=jnp.float32)
    return ctx.reshape(b, h, s_q, d).astype(q.dtype)
