"""Ring attention over the ``context`` mesh axis — sequence/context
parallelism beyond the reference.

The reference's only sequence-length play is Megatron sequence parallelism
(apex/transformer/tensor_parallel/layers.py ``sequence_parallel_enabled``);
attention itself is never sharded over sequence, and its fmha kernel caps
seqlen at 512 (SURVEY.md §5 long-context row). This module removes that
ceiling: the sequence is sharded over the ``context`` axis, each device holds
a [B, H, S/cp, D] chunk of q/k/v, and K/V chunks rotate around the ring via
``lax.ppermute`` (ICI neighbor hops) while each device accumulates its
queries' attention over every chunk with an online logsumexp merge — the
blockwise/ring-attention formulation (Liu et al.), built on the flash
kernel's ``(o, lse)`` output (apex_tpu/ops/flash_attention.py
``flash_attention_with_lse``).

Differentiability: each partial is a ``custom_vjp`` flash call (including the
lse cotangent, which folds into the backward's delta correction) and the
merge is plain jnp — so ``jax.grad`` through the scan + ppermute yields the
exact ring backward (grads ride the reverse ring automatically via
ppermute's transpose) with no hand-written outer VJP.

Causal load note: chunks are laid out in sequence order, so rotation step 0
is exactly the causal diagonal for every device (a *static* branch) and later
steps are all-or-nothing (device i attends chunk j iff j < i). Devices late
in the ring discard more work — the classic ring-attention imbalance;
zigzag/striped layouts could fix it but complicate the story, and the wasted
kernels are uniform SPMD work that XLA overlaps with the permutes.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import CONTEXT_AXIS
from apex_tpu.ops.flash_attention import flash_attention_with_lse


def _rotate(x, axis_name, cp):
    """Shift chunks one step around the ring: device i -> i+1 (mod cp)."""
    return lax.ppermute(x, axis_name, [(i, (i + 1) % cp) for i in range(cp)])


def _merge(o1, lse1, o2, lse2):
    """Numerically-stable combine of two normalized partial attentions.

    Given o_i = softmax_i @ v over key-subset i with row logsumexp lse_i,
    the exact combined result is a convex combination weighted by
    exp(lse_i - lse_tot). Rows where a partial saw no live keys carry
    lse = -inf and drop out with weight 0.
    """
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    w1 = jnp.where(jnp.isneginf(lse1), 0.0, jnp.exp(lse1 - m_safe))
    w2 = jnp.where(jnp.isneginf(lse2), 0.0, jnp.exp(lse2 - m_safe))
    den = w1 + w2
    den_safe = jnp.where(den == 0.0, 1.0, den)
    o = (w1[..., None] * o1 + w2[..., None] * o2) / den_safe[..., None]
    lse = jnp.where(den == 0.0, -jnp.inf, m_safe + jnp.log(den_safe))
    return o, lse


def ring_attention(
    q,
    k,
    v,
    *,
    axis_name: str = CONTEXT_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """Flash attention over a sequence sharded on ``axis_name``.

    Must be called inside ``shard_map`` (or ``pjit``-manual) with the
    sequence dimension of q/k/v sharded IN ORDER over ``axis_name``:
    device i holds tokens [i*S_loc, (i+1)*S_loc).

    Args:
      q, k, v: local chunks [B, H, S_loc, D] (self-attention ring: q and kv
        share the sequence sharding; cross-attention rings are out of scope).
      causal: global causal masking across the full (unsharded) sequence.
      scale: softmax scale, default 1/sqrt(D).

    Returns the local output chunk [B, H, S_loc, D] in q.dtype — numerically
    identical (up to fp accumulation order) to single-device
    ``flash_attention`` on the gathered sequence.
    """
    if q.shape != k.shape or k.shape != v.shape:
        raise ValueError(
            f"ring self-attention needs equal q/k/v chunk shapes, got "
            f"{q.shape}/{k.shape}/{v.shape}")
    d = q.shape[-1]
    scale = (1.0 / (d ** 0.5)) if scale is None else float(scale)
    cp = lax.psum(1, axis_name)  # static axis size inside shard_map
    idx = lax.axis_index(axis_name)

    # step 0: own chunk — for causal layouts this IS the diagonal block
    o0, lse0 = flash_attention_with_lse(
        q, k, v, scale=scale, causal=causal, block_q=block_q, block_k=block_k)
    o, lse = o0.astype(jnp.float32), lse0
    if cp == 1:
        return o0

    kc, vc = _rotate(k, axis_name, cp), _rotate(v, axis_name, cp)

    def body(carry, r):
        kc, vc, o, lse = carry
        # at step r device idx holds chunk j = (idx - r) mod cp
        o_r, lse_r = flash_attention_with_lse(
            q, kc, vc, scale=scale, causal=False,
            block_q=block_q, block_k=block_k)
        if causal:
            # include iff source chunk j is strictly before ours (j < idx
            # ⇔ r <= idx); excluded partials get weight exp(-inf) = 0
            lse_r = jnp.where(r <= idx, lse_r, -jnp.inf)
        o, lse = _merge(o, lse, o_r.astype(jnp.float32), lse_r)
        return (_rotate(kc, axis_name, cp), _rotate(vc, axis_name, cp),
                o, lse), None

    (_, _, o, lse), _ = lax.scan(body, (kc, vc, o, lse), jnp.arange(1, cp))
    return o.astype(q.dtype)
