"""Ring attention over the ``context`` mesh axis — sequence/context
parallelism beyond the reference.

The reference's only sequence-length play is Megatron sequence parallelism
(apex/transformer/tensor_parallel/layers.py ``sequence_parallel_enabled``);
attention itself is never sharded over sequence, and its fmha kernel caps
seqlen at 512 (SURVEY.md §5 long-context row). This module removes that
ceiling: the sequence is sharded over the ``context`` axis, each device holds
a [B, H, S/cp, D] chunk of q/k/v, and K/V chunks rotate around the ring via
``lax.ppermute`` (ICI neighbor hops) while each device accumulates its
queries' attention over every chunk with an online logsumexp merge — the
blockwise/ring-attention formulation (Liu et al.), built on the flash
kernel's ``(o, lse)`` output (apex_tpu/ops/flash_attention.py
``flash_attention_with_lse``).

Differentiability: each partial is a ``custom_vjp`` flash call (including the
lse cotangent, which folds into the backward's delta correction) and the
merge is plain jnp — so ``jax.grad`` through the scan + ppermute yields the
exact ring backward (grads ride the reverse ring automatically via
ppermute's transpose) with no hand-written outer VJP.

Causal load note: ``ring_attention``'s chunks are laid out in sequence
order, so rotation step 0 is exactly the causal diagonal for every device
(a *static* branch) and later steps are all-or-nothing (device i attends
chunk j iff j < i) — devices late in the ring discard more work, the
classic ring-attention imbalance. ``ring_attention_zigzag`` (below) fixes
it for causal masks: each device holds one early + one late half-chunk
(``to_zigzag``/``from_zigzag`` layout helpers) so every rotation step does
exactly two live half-chunk kernels.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.mesh import CONTEXT_AXIS
from apex_tpu.ops.flash_attention import flash_attention_with_lse


def _rotate(x, axis_name, cp):
    """Shift chunks one step around the ring: device i -> i+1 (mod cp)."""
    return lax.ppermute(x, axis_name, [(i, (i + 1) % cp) for i in range(cp)])


def _check_ring_shapes(q, k, v, kind: str):
    """Self-attention ring contract, GQA-aware: q [B, H, S_loc, D] with k/v
    [B, Hkv, S_loc, D], Hkv dividing H (the flash kernel indexes kv heads
    natively, so the ring rotates the UNEXPANDED K/V — ppermute payload
    shrinks by H/Hkv under grouped-query attention)."""
    if k.shape != v.shape:
        raise ValueError(f"{kind}: k/v shapes differ: {k.shape}/{v.shape}")
    if (q.shape[0], q.shape[2], q.shape[3]) != (k.shape[0], k.shape[2],
                                                k.shape[3]):
        raise ValueError(
            f"{kind} self-attention needs matching batch/seq/head-dim, got "
            f"q {q.shape} vs kv {k.shape}")
    if q.shape[1] % k.shape[1] != 0:
        raise ValueError(
            f"{kind}: q heads ({q.shape[1]}) must be a multiple of kv heads "
            f"({k.shape[1]})")


def _merge(o1, lse1, o2, lse2):
    """Numerically-stable combine of two normalized partial attentions.

    Given o_i = softmax_i @ v over key-subset i with row logsumexp lse_i,
    the exact combined result is a convex combination weighted by
    exp(lse_i - lse_tot). Rows where a partial saw no live keys carry
    lse = -inf and drop out with weight 0.
    """
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    w1 = jnp.where(jnp.isneginf(lse1), 0.0, jnp.exp(lse1 - m_safe))
    w2 = jnp.where(jnp.isneginf(lse2), 0.0, jnp.exp(lse2 - m_safe))
    den = w1 + w2
    den_safe = jnp.where(den == 0.0, 1.0, den)
    o = (w1[..., None] * o1 + w2[..., None] * o2) / den_safe[..., None]
    lse = jnp.where(den == 0.0, -jnp.inf, m_safe + jnp.log(den_safe))
    return o, lse


def ring_attention(
    q,
    k,
    v,
    *,
    axis_name: str = CONTEXT_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    window: Optional[int] = None,
    dropout_rate: float = 0.0,
    dropout_seed=0,
):
    """Flash attention over a sequence sharded on ``axis_name``.

    Must be called inside ``shard_map`` (or ``pjit``-manual) with the
    sequence dimension of q/k/v sharded IN ORDER over ``axis_name``:
    device i holds tokens [i*S_loc, (i+1)*S_loc).

    Args:
      q, k, v: local chunks [B, H, S_loc, D]; k/v may carry FEWER heads
        (GQA/MQA — see ``_check_ring_shapes``). Self-attention ring: q and
        kv share the sequence sharding; cross-attention rings are out of
        scope.
      causal: global causal masking across the full (unsharded) sequence.
      scale: softmax scale, default 1/sqrt(D).
      dropout_rate/dropout_seed: attention-probability dropout. Each ring
        step seeds the counter-based kernel PRNG at the chunk's GLOBAL
        (row, col) coordinates, so the keep mask is EXACTLY the one a
        single-device ``flash_attention`` call with the same seed draws —
        CP training reproduces ``multihead_attn``'s fused softmax-dropout
        semantics bit-for-bit (up to merge-order fp).

    Returns the local output chunk [B, H, S_loc, D] in q.dtype — numerically
    identical (up to fp accumulation order) to single-device
    ``flash_attention`` on the gathered sequence.
    """
    _check_ring_shapes(q, k, v, "ring")
    if window is not None and not causal:
        raise ValueError("window requires causal=True (same contract as "
                         "flash_attention)")
    d = q.shape[-1]
    s_loc = q.shape[2]
    scale = (1.0 / (d ** 0.5)) if scale is None else float(scale)
    cp = lax.psum(1, axis_name)  # static axis size inside shard_map
    idx = lax.axis_index(axis_name)
    row0 = idx * s_loc  # this device's global first q row

    def attend(kk, vv, src, **kw):
        """Flash over the local q vs chunk ``src``'s k/v (global dropout
        coordinates ride along)."""
        return flash_attention_with_lse(
            q, kk, vv, scale=scale, block_q=block_q, block_k=block_k,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
            dropout_row0=row0, dropout_col0=src * s_loc, **kw)

    # step 0: own chunk — for causal layouts this IS the diagonal block
    o0, lse0 = attend(k, v, idx, causal=causal, window=window)
    o, lse = o0.astype(jnp.float32), lse0
    if cp == 1:
        return o0

    if window is not None:
        # window-aware ring: at step r the received chunk sits r*s_loc rows
        # upstream — a STATIC offset — and chunks wholly outside the band
        # need neither compute nor further rotation, so the ring is
        # statically SHORTENED to ceil((window-1)/s_loc) hops (fewer
        # ppermutes, the CP analog of the kernel's band-restricted grid).
        n_hops = min(int(cp) - 1, (window - 2 + s_loc) // s_loc)
        kc, vc = k, v
        for r in range(1, n_hops + 1):
            kc, vc = _rotate(kc, axis_name, cp), _rotate(vc, axis_name, cp)
            o_r, lse_r = attend(kc, vc, jnp.mod(idx - r, cp), causal=True,
                                causal_offset=r * s_loc, window=window)
            # ring wrap: chunks logically AFTER ours (r > idx) are excluded
            lse_r = jnp.where(r <= idx, lse_r, -jnp.inf)
            o, lse = _merge(o, lse, o_r.astype(jnp.float32), lse_r)
        return o.astype(q.dtype)

    kc, vc = _rotate(k, axis_name, cp), _rotate(v, axis_name, cp)

    def body(carry, r):
        kc, vc, o, lse = carry
        # at step r device idx holds chunk j = (idx - r) mod cp
        o_r, lse_r = attend(kc, vc, jnp.mod(idx - r, cp), causal=False)
        if causal:
            # include iff source chunk j is strictly before ours (j < idx
            # ⇔ r <= idx); excluded partials get weight exp(-inf) = 0
            lse_r = jnp.where(r <= idx, lse_r, -jnp.inf)
        o, lse = _merge(o, lse, o_r.astype(jnp.float32), lse_r)
        return (_rotate(kc, axis_name, cp), _rotate(vc, axis_name, cp),
                o, lse), None

    (_, _, o, lse), _ = lax.scan(body, (kc, vc, o, lse), jnp.arange(1, cp))
    return o.astype(q.dtype)


# =============================================================================
# zigzag layout — load-balanced CAUSAL ring attention
# =============================================================================

def zigzag_chunk_indices(cp: int):
    """Global chunk ids (out of 2*cp) held by each device: (i, 2cp-1-i)."""
    return [(i, 2 * cp - 1 - i) for i in range(cp)]


def to_zigzag(x, cp: int, axis: int = 2):
    """Permute a GLOBAL sequence into zigzag device order (call before
    sharding over ``context``): device i's slice holds chunks (i, 2cp-1-i),
    so each device owns one early and one late chunk and the causal-mask
    work is uniform around the ring."""
    s = x.shape[axis]
    if s % (2 * cp):
        raise ValueError(f"sequence {s} not divisible by 2*cp={2 * cp}")
    chunks = jnp.split(x, 2 * cp, axis=axis)
    return jnp.concatenate(
        [chunks[c] for pair in zigzag_chunk_indices(cp) for c in pair],
        axis=axis)

def from_zigzag(x, cp: int, axis: int = 2):
    """Inverse of ``to_zigzag``."""
    order = [c for pair in zigzag_chunk_indices(cp) for c in pair]
    inv = [order.index(c) for c in range(2 * cp)]
    chunks = jnp.split(x, 2 * cp, axis=axis)
    return jnp.concatenate([chunks[i] for i in inv], axis=axis)


def ring_attention_zigzag(
    q,
    k,
    v,
    *,
    axis_name: str = CONTEXT_AXIS,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    window: Optional[int] = None,
    dropout_rate: float = 0.0,
    dropout_seed=0,
):
    """CAUSAL ring attention over a zigzag-sharded sequence.

    The sequence-ordered layout of ``ring_attention`` wastes ~half the
    kernel work under a causal mask: late-ring devices discard most
    arriving K/V chunks (its own docstring concedes the imbalance). The
    zigzag layout fixes it: the sequence is split into 2*cp chunks and
    device i holds the PAIR (chunk i, chunk 2cp-1-i) — one early chunk
    (few causal keys) and one late chunk (many), so every device computes
    exactly two half-chunk flash calls per rotation step:

      step 0 (own pair, static): early-diag, late-vs-early full, late-diag;
      step r>0 receiving device j's pair: late-q vs early-kv is ALWAYS a
      live full block, plus ONE more — early-q vs early-kv when j < i,
      late-q vs late-kv when j > i (a per-device ``lax.cond``; Pallas
      calls are local compute, so divergent branches are safe — unlike
      collectives, see schedules._stage_issues_ppermute).

    With ``window`` (sliding-window causal attention, VERDICT r3 weak #5):
    the EE/LL interactions' chunk distances are STATIC per hop (r and cp-r
    — the kernel's band-restricted grid applies unchanged), while the
    late-q-vs-early-k block's distance depends on the device index, so it
    passes the offset as a TRACED kernel scalar (full grid, dead blocks
    skip their FLOPs). Hops where every interaction is out-of-band don't
    run at all — skipped rotations compose into one multi-step ppermute,
    so a short window costs O(window/s_h) collectives, not O(cp).

    Dropout seeds the kernel PRNG at GLOBAL coordinates (chunk id × s_h),
    so zigzag CP dropout reproduces the single-device keep mask exactly
    (same contract as ``ring_attention``).

    Inputs are the LOCAL zigzag slice [B, H, 2*S_h, D] (produce the global
    layout with ``to_zigzag`` before sharding; undo with ``from_zigzag``).
    Fully differentiable (custom_vjp flash + jnp merges + ppermute
    transpose).
    """
    _check_ring_shapes(q, k, v, "zigzag ring")
    if q.shape[2] % 2:
        raise ValueError("local zigzag slice must hold two half-chunks")
    d = q.shape[-1]
    scale = (1.0 / (d ** 0.5)) if scale is None else float(scale)
    cp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    s_h = q.shape[2] // 2

    def halves(t):
        return t[:, :, :s_h], t[:, :, s_h:]

    q_e, q_l = halves(q)
    cq_e = idx               # global chunk id (of 2*cp) of the early q half
    cq_l = 2 * cp - 1 - idx  # ... and the late q half

    def attend(qq, kk, vv, causal, cq, ck, off=None, win=None):
        """One half-chunk flash call; ``cq``/``ck`` are the GLOBAL chunk
        ids (units of s_h) of the q and kv halves — they anchor the
        dropout PRNG's global coordinates; ``off`` positions causal/window
        masking at global rows."""
        return flash_attention_with_lse(
            qq, kk, vv, scale=scale, causal=causal, causal_offset=off,
            window=win, block_q=block_q, block_k=block_k,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
            dropout_row0=cq * s_h, dropout_col0=ck * s_h)

    k_e, k_l = halves(k)
    v_e, v_l = halves(v)

    if window is not None:
        return _zigzag_windowed(
            q_e, q_l, k, v, attend=attend,
            axis_name=axis_name, cp=cp, idx=idx, s_h=s_h,
            cq_e=cq_e, cq_l=cq_l, window=window, out_dtype=q.dtype)

    # ---- step 0: own pair (static diagonal structure) ----
    o_e, lse_e = attend(q_e, k_e, v_e, True, cq_e, cq_e)  # early diag
    acc_e = (o_e.astype(jnp.float32), lse_e)
    o_l0, lse_l0 = attend(q_l, k_e, v_e, False, cq_l, cq_e)  # late q, early k
    o_l1, lse_l1 = attend(q_l, k_l, v_l, True, cq_l, cq_l)   # late diag
    acc_l = _merge(o_l0.astype(jnp.float32), lse_l0,
                   o_l1.astype(jnp.float32), lse_l1)
    if cp == 1:
        return jnp.concatenate([acc_e[0], acc_l[0]], axis=2).astype(q.dtype)

    kc, vc = _rotate(k, axis_name, cp), _rotate(v, axis_name, cp)

    def body(carry, r):
        kc, vc, acc_e, acc_l = carry
        # at step r this device holds device j = (idx - r) mod cp's pair:
        # global chunks (j, 2cp-1-j)
        j = jnp.mod(idx - r, cp)
        kc_e, kc_l = halves(kc)
        vc_e, vc_l = halves(vc)
        # always live: late q (chunk 2cp-1-i) vs j's early kv (chunk j < cp)
        o_a, lse_a = attend(q_l, kc_e, vc_e, False, cq_l, j)
        acc_l = _merge(acc_l[0], acc_l[1], o_a.astype(jnp.float32), lse_a)
        # the second block depends on ring position (balanced: always ONE)
        o_b, lse_b = lax.cond(
            j < idx,
            lambda: attend(q_e, kc_e, vc_e, False, cq_e, j),
            lambda: attend(q_l, kc_l, vc_l, False, cq_l, 2 * cp - 1 - j))
        cand_e = _merge(acc_e[0], acc_e[1], o_b.astype(jnp.float32), lse_b)
        cand_l = _merge(acc_l[0], acc_l[1], o_b.astype(jnp.float32), lse_b)
        sel = lambda a, b: jax.tree.map(  # noqa: E731
            lambda x, y: jnp.where(j < idx, x, y), a, b)
        acc_e = sel(cand_e, acc_e)
        acc_l = sel(acc_l, cand_l)
        return (_rotate(kc, axis_name, cp), _rotate(vc, axis_name, cp),
                acc_e, acc_l), None

    (_, _, acc_e, acc_l), _ = lax.scan(
        body, (kc, vc, acc_e, acc_l), jnp.arange(1, cp))
    return jnp.concatenate([acc_e[0], acc_l[0]], axis=2).astype(q.dtype)


def _zigzag_windowed(q_e, q_l, k, v, *, attend,
                     axis_name, cp, idx, s_h, cq_e, cq_l, window, out_dtype):
    """Sliding-window zigzag ring (see ring_attention_zigzag's docstring).

    Chunk-distance bound: global q row cq*s_h+a sees global k row cs*s_h+b
    iff 0 <= (cq-cs)*s_h + a - b <= window-1; the minimum gap across a pair
    at distance d = cq-cs >= 1 is (d-1)*s_h + 1, so pairs with d > d_max =
    1 + floor((window-2)/s_h) are wholly out-of-band.
    """
    d_max = (window - 2 + s_h) // s_h if window >= 2 else 0
    cpi = int(cp)

    def halves(t):
        return t[:, :, :s_h], t[:, :, s_h:]

    k_e, k_l = halves(k)
    v_e, v_l = halves(v)

    def dead(qq):
        return (jnp.zeros_like(qq),
                jnp.full(qq.shape[:3], -jnp.inf, jnp.float32))

    # ---- step 0: own pair ----
    o_e0, lse_e0 = attend(q_e, k_e, v_e, True, cq_e, cq_e, win=window)
    acc_e = (o_e0.astype(jnp.float32), lse_e0)
    o_l1, lse_l1 = attend(q_l, k_l, v_l, True, cq_l, cq_l, win=window)
    acc_l = (o_l1.astype(jnp.float32), lse_l1)
    if d_max >= 1:
        # late q vs own early k: distance (2cp-1-2i) chunks — per-device,
        # so the offset rides the kernel's dynamic-offset scalar
        o_l0, lse_l0 = attend(q_l, k_e, v_e, True, cq_l, cq_e,
                              off=(cq_l - cq_e) * s_h, win=window)
        acc_l = _merge(acc_l[0], acc_l[1], o_l0.astype(jnp.float32), lse_l0)
    if cpi == 1:
        return jnp.concatenate([acc_e[0], acc_l[0]],
                               axis=2).astype(out_dtype)

    # hop r carries live work iff the EE band (distance r), the LL band
    # (distance cp-r), or the late-early block (min distance
    # min(r+1, cp-r+1)) is within d_max; the third is subsumed by the
    # first two. Skipped hops fold into the next live hop's ppermute.
    live_hops = [r for r in range(1, cpi)
                 if r <= d_max or cpi - r <= d_max]
    rot = 0
    kc, vc = k, v
    for r in live_hops:
        delta = r - rot
        perm = [(i, (i + delta) % cpi) for i in range(cpi)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        rot = r
        kc_e, kc_l = halves(kc)
        vc_e, vc_l = halves(vc)
        j = jnp.mod(idx - r, cp)      # source device of the held pair
        ck_l = 2 * cp - 1 - j
        ee = r <= d_max               # live on devices with j < idx
        ll = cpi - r <= d_max         # live on devices with j > idx
        if ee and ll:
            # balanced: exactly one of the two per device, as in the
            # unwindowed ring; EE distance r and LL distance cp-r are
            # static, so both branches keep the banded kernel grid
            o_b, lse_b = lax.cond(
                j < idx,
                lambda: attend(q_e, kc_e, vc_e, True, cq_e, j,
                               off=r * s_h, win=window),
                lambda: attend(q_l, kc_l, vc_l, True, cq_l, ck_l,
                               off=(cpi - r) * s_h, win=window))
            cand_e = _merge(acc_e[0], acc_e[1],
                            o_b.astype(jnp.float32), lse_b)
            cand_l = _merge(acc_l[0], acc_l[1],
                            o_b.astype(jnp.float32), lse_b)
            sel = lambda a, b: jax.tree.map(  # noqa: E731
                lambda x, y: jnp.where(j < idx, x, y), a, b)
            acc_e = sel(cand_e, acc_e)
            acc_l = sel(acc_l, cand_l)
        elif ee:
            o_b, lse_b = lax.cond(
                j < idx,
                lambda: attend(q_e, kc_e, vc_e, True, cq_e, j,
                               off=r * s_h, win=window),
                lambda: dead(q_e))
            acc_e = _merge(acc_e[0], acc_e[1],
                           o_b.astype(jnp.float32), lse_b)
        elif ll:
            o_b, lse_b = lax.cond(
                j > idx,
                lambda: attend(q_l, kc_l, vc_l, True, cq_l, ck_l,
                               off=(cpi - r) * s_h, win=window),
                lambda: dead(q_l))
            acc_l = _merge(acc_l[0], acc_l[1],
                           o_b.astype(jnp.float32), lse_b)
        # late q vs received early k: distance (2cp-1-idx) - j chunks,
        # device-dependent -> dynamic offset; devices out of band get
        # all-dead blocks (lse -> -inf rows, merge weight 0)
        if min(r + 1, cpi - r + 1) <= d_max:
            o_a, lse_a = attend(q_l, kc_e, vc_e, True, cq_l, j,
                                off=(cq_l - j) * s_h, win=window)
            acc_l = _merge(acc_l[0], acc_l[1],
                           o_a.astype(jnp.float32), lse_a)
    return jnp.concatenate([acc_e[0], acc_l[0]], axis=2).astype(out_dtype)
