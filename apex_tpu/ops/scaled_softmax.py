"""Pallas fused scale+mask+softmax (fwd + bwd).

TPU rebuild of the three megatron softmax extensions (SURVEY.md §2.2):
``scaled_masked_softmax_cuda``, ``scaled_upper_triang_masked_softmax_cuda``,
``generic_scaled_masked_softmax_cuda`` (csrc/megatron/scaled_masked_softmax.h
and siblings — scale + {arbitrary | causal} mask + softmax, fwd/bwd, saving
the softmax output for backward). Unlike the reference there is no seqlen cap
(the CUDA fast path required sk <= 2k/4k); one kernel serves all shapes.

Used standalone by ``FusedScaleMaskSoftmax``
(apex/transformer/functional/fused_softmax.py); for full attention blocks the
softmax is folded into apex_tpu.ops.flash_attention instead.

Layout: x [b, np, sq, sk] (the reference's layout); mask broadcastable
[b or 1, 1, sq, sk], **True = masked out** (reference convention).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import _dispatch

_INTERPRET = _dispatch.interpret

# the reference fills masked scores with -10000 (scaled_masked_softmax.h)
MASK_FILL = -10000.0


def _row_tile(sk: int, sq: int) -> int:
    return _dispatch.row_tile(sk, sq, cap=256)


def _fwd_kernel(x_ref, mask_ref, y_ref, *, scale, causal, sq, sk, tile):
    i = pl.program_id(2)
    x = x_ref[0, 0].astype(jnp.float32) * scale
    rows = lax.broadcasted_iota(jnp.int32, x.shape, 0) + i * tile
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    pad = cols >= sk
    if mask_ref is not None:
        x = jnp.where(mask_ref[0, 0] != 0, MASK_FILL, x)
    if causal:
        x = jnp.where(rows < cols, MASK_FILL, x)
    # padding columns must vanish entirely (not just MASK_FILL)
    x = jnp.where(pad, -jnp.inf, x)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    y = e / jnp.sum(e, axis=-1, keepdims=True)
    y_ref[0, 0] = y.astype(y_ref.dtype)


def _bwd_kernel(y_ref, dy_ref, dx_ref, *, scale):
    y = y_ref[0, 0].astype(jnp.float32)
    dy = dy_ref[0, 0].astype(jnp.float32)
    dot = jnp.sum(y * dy, axis=-1, keepdims=True)
    dx_ref[0, 0] = ((dy - dot) * y * scale).astype(dx_ref.dtype)


def _softmax_fwd(x, mask, scale, causal):
    b, np_, sq, sk = x.shape
    tile = _row_tile(sk, sq)
    sk_pad = _dispatch.round_up(sk, 128)
    xp = jnp.pad(x, ((0, 0), (0, 0), (0, _dispatch.round_up(sq, tile) - sq),
                     (0, sk_pad - sk)))
    nq = xp.shape[2] // tile

    in_specs = [pl.BlockSpec((1, 1, tile, sk_pad),
                             lambda b, h, i: (b, h, i, 0),
                             memory_space=pltpu.VMEM)]
    args = [xp]
    if mask is not None:
        mask = jnp.broadcast_to(mask, (mask.shape[0], 1, sq, sk)).astype(jnp.int8)
        mp = jnp.pad(mask, ((0, 0), (0, 0),
                            (0, xp.shape[2] - sq), (0, sk_pad - sk)))
        mb = mp.shape[0]
        in_specs.append(pl.BlockSpec(
            (1, 1, tile, sk_pad),
            lambda b, h, i, mb=mb: (b % mb, 0, i, 0),
            memory_space=pltpu.VMEM))
        args.append(mp)

    def fn(*refs):
        x_ref = refs[0]
        mask_ref = refs[1] if mask is not None else None
        y_ref = refs[-1]
        _fwd_kernel(x_ref, mask_ref, y_ref, scale=scale, causal=causal,
                    sq=sq, sk=sk, tile=tile)

    y = _dispatch.pallas_call(
        fn,
        grid=(b, np_, nq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, tile, sk_pad),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x.dtype),
        interpret=_INTERPRET(),
    )(*args)
    return y[:, :, :sq, :sk]


def _softmax_bwd_impl(y, dy, scale):
    b, np_, sq, sk = y.shape
    tile = _row_tile(sk, sq)
    sk_pad = _dispatch.round_up(sk, 128)
    pad = ((0, 0), (0, 0), (0, _dispatch.round_up(sq, tile) - sq),
           (0, sk_pad - sk))
    yp, dyp = jnp.pad(y, pad), jnp.pad(dy, pad)
    nq = yp.shape[2] // tile
    spec = pl.BlockSpec((1, 1, tile, sk_pad), lambda b, h, i: (b, h, i, 0),
                        memory_space=pltpu.VMEM)
    dx = _dispatch.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(b, np_, nq),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(yp.shape, dy.dtype),
        interpret=_INTERPRET(),
    )(yp, dyp)
    return dx[:, :, :sq, :sk]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _scaled_softmax(x, mask, scale, causal):
    return _softmax_fwd(x, mask, scale, causal)


def _scaled_softmax_vfwd(x, mask, scale, causal):
    y = _softmax_fwd(x, mask, scale, causal)
    return y, (y, mask)


def _scaled_softmax_vbwd(scale, causal, res, dy):
    y, mask = res
    dx = _softmax_bwd_impl(y, dy, scale)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dx, dmask


_scaled_softmax.defvjp(_scaled_softmax_vfwd, _scaled_softmax_vbwd)


def scaled_masked_softmax(x, mask: Optional[jax.Array], scale: float = 1.0):
    """softmax(scale*x masked-filled where ``mask`` is True), last dim.

    Reference: csrc/megatron/scaled_masked_softmax.h (fwd/bwd) via
    ``ScaledMaskedSoftmax`` autograd fn in
    apex/transformer/functional/fused_softmax.py.
    """
    return _scaled_softmax(x, mask, float(scale), False)


def scaled_upper_triang_masked_softmax(x, scale: float = 1.0):
    """Causal softmax for [b, sq, sk] score tensors (attn_batches layout).

    Reference: csrc/megatron/scaled_upper_triang_masked_softmax.h via
    ``ScaledUpperTriangMaskedSoftmax``.
    """
    y = _scaled_softmax(x[:, None], None, float(scale), True)
    return y[:, 0]


def scaled_softmax(x, scale: float = 1.0):
    """No-mask variant (reference ``ScaledSoftmax``)."""
    return _scaled_softmax(x, None, float(scale), False)
