"""The kernel layer — TPU-native equivalent of the reference's ``csrc/``.

Every op here is a pure function with a ``jax.custom_vjp`` backed by Pallas
TPU kernels (compiled via Mosaic on TPU; interpret mode off-TPU so the same
code paths are unit-testable on CPU). Reference mapping in SURVEY.md §2.2.
"""

from apex_tpu.ops.layer_norm import layer_norm, rms_norm  # noqa: F401
