"""The kernel layer — TPU-native equivalent of the reference's ``csrc/``.

Every op here is a pure function with a ``jax.custom_vjp`` backed by Pallas
TPU kernels (compiled via Mosaic on TPU; interpret mode off-TPU so the same
code paths are unit-testable on CPU). Reference mapping in SURVEY.md §2.2.
"""

from apex_tpu.ops.layer_norm import layer_norm, rms_norm  # noqa: F401
from apex_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_with_lse,
    mha_reference,
)
from apex_tpu.ops.paged_attention import (  # noqa: F401
    paged_attention,
    paged_attention_reference,
)
from apex_tpu.ops.ring_attention import (  # noqa: F401
    from_zigzag,
    ring_attention,
    ring_attention_zigzag,
    to_zigzag,
)
from apex_tpu.ops.scaled_softmax import (  # noqa: F401
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.ops.quant import int8_matmul, quantize_weight  # noqa: F401
from apex_tpu.ops.xentropy import softmax_cross_entropy  # noqa: F401
