"""NHWC GroupNorm (+ fused SiLU) with a Pallas forward kernel.

Reference: apex/contrib/csrc/group_norm/ (~2k LoC:
group_norm_nhwc_fwd/bwd*.cu, tuned for diffusion-model shapes) wrapped by
apex/contrib/group_norm/group_norm.py's ``GroupNorm`` (a torch GroupNorm
drop-in with ``act="silu"`` fusion).

TPU restatement: NHWC is already the natural TPU layout (channels on
lanes). The forward kernel processes one (sample, group) slab per grid step
— fp32 mean/var, normalize, affine, optional SiLU in a single VMEM pass —
and saves (mean, rstd) for the backward, which is the standard GroupNorm
two-reduction gradient expressed in jnp (XLA fuses it into two passes; the
reference's bwd kernels do the same reductions by hand). Shapes whose
per-group channel count isn't lane-aligned (cg % 128 != 0) or whose slab
exceeds VMEM fall back to the identical-math jnp path, mirroring the
reference's per-shape kernel dispatch.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import _dispatch

_INTERPRET = _dispatch.interpret


def _silu(x):
    return x * jax.nn.sigmoid(x)


def group_norm_reference(x, weight, bias, num_groups, eps,
                         act: Optional[str] = None):
    """Pure-jnp GroupNorm (fp32 accumulation) — fallback path and the
    ground truth for kernel parity tests."""
    n, h, w, c = x.shape
    g = num_groups
    x32 = x.astype(jnp.float32).reshape(n, h * w, g, c // g)
    mean = x32.mean(axis=(1, 3), keepdims=True)
    var = ((x32 - mean) ** 2).mean(axis=(1, 3), keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(n, h, w, c)
    if weight is not None:
        y = y * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    if act == "silu":
        y = _silu(y)
    return y.astype(x.dtype)


def _gn_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref,
                   *, eps, act, affine):
    x = x_ref[0].astype(jnp.float32)            # (hw, cg) one (n, g) slab
    mean = jnp.mean(x)
    var = jnp.mean(x * x) - mean * mean
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd
    if affine:
        y = y * w_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    if act == "silu":
        y = _silu(y)
    y_ref[0] = y.astype(y_ref.dtype)
    # stats ride in ONE whole-array SMEM block (Mosaic rejects (1, 1)
    # grid-blocked outputs: block dims must be (8, 128)-divisible or equal
    # the array's — TPU_TESTS_r03.log); each step writes its own row
    i = pl.program_id(0)
    mean_ref[i, 0] = mean
    rstd_ref[i, 0] = rstd


def _kernel_eligible(hw: int, cg: int) -> bool:
    return cg % 128 == 0 and hw % 8 == 0 and hw * cg * 4 <= 8 * 1024 * 1024


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def group_norm_nhwc(x, weight, bias, num_groups: int, eps: float = 1e-5,
                    act: Optional[str] = None):
    """GroupNorm over NHWC input; ``act='silu'`` fuses the activation.

    ``weight``/``bias`` may be None (no affine). Differentiable.
    """
    y, _ = _gn_fwd(x, weight, bias, num_groups, eps, act)
    return y


def _gn_fwd(x, weight, bias, num_groups, eps, act):
    if act not in (None, "", "silu"):
        raise ValueError(f"unsupported act {act!r} (reference: silu only)")
    n, h, w_, c = x.shape
    g = num_groups
    if c % g != 0:
        raise ValueError(f"channels {c} not divisible by groups {g}")
    cg = c // g
    hw = h * w_
    affine = weight is not None

    if not _kernel_eligible(hw, cg):
        y = group_norm_reference(x, weight, bias, g, eps, act)
        return y, None  # bwd recomputes stats (fallback shapes are small)

    x_slab = x.reshape(n, hw, g, cg).transpose(0, 2, 1, 3).reshape(
        n * g, hw, cg)
    if affine:
        w_slab = jnp.tile(weight.reshape(1, g, 1, cg), (n, 1, 1, 1)
                          ).reshape(n * g, 1, cg)
        b_slab = jnp.tile(bias.reshape(1, g, 1, cg), (n, 1, 1, 1)
                          ).reshape(n * g, 1, cg)
    else:
        w_slab = jnp.zeros((n * g, 1, cg), x.dtype)
        b_slab = jnp.zeros((n * g, 1, cg), x.dtype)

    y_slab, mean, rstd = _dispatch.pallas_call(
        functools.partial(_gn_fwd_kernel, eps=eps, act=act or None,
                          affine=affine),
        grid=(n * g,),
        in_specs=[
            pl.BlockSpec((1, hw, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n * g, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((n * g, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n * g, hw, cg), x.dtype),
            jax.ShapeDtypeStruct((n * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((n * g, 1), jnp.float32),
        ],
        interpret=_INTERPRET(),
    )(x_slab, w_slab, b_slab)
    y = y_slab.reshape(n, g, hw, cg).transpose(0, 2, 1, 3).reshape(n, h, w_, c)
    return y, (mean.reshape(n, g), rstd.reshape(n, g))


def _gn_fwd_vjp(x, weight, bias, num_groups, eps, act):
    y, saved = _gn_fwd(x, weight, bias, num_groups, eps, act)
    return y, (x, weight, bias, saved)


def _gn_bwd_kernel(x_ref, dy_ref, w_ref, b_ref, mean_ref, rstd_ref,
                   dx_ref, dwp_ref, dbp_ref, *, act, affine, m):
    """One (n, g) slab in a single VMEM pass: silu grad, dw/db partials,
    the two group reductions, and dx — the Pallas answer to the reference's
    group_norm_nhwc_bwd kernels (one-pass vs XLA's ~30 tensor sweeps for
    the jnp formulation, measured via cost_analysis; docs/normalization.md)."""
    x = x_ref[0].astype(jnp.float32)
    dy = dy_ref[0].astype(jnp.float32)
    i = pl.program_id(0)                        # stats: whole-array SMEM block
    mean = mean_ref[i, 0]
    rstd = rstd_ref[i, 0]
    xhat = (x - mean) * rstd
    if act == "silu":
        wv = w_ref[0].astype(jnp.float32) if affine else 1.0
        bv = b_ref[0].astype(jnp.float32) if affine else 0.0
        y_pre = xhat * wv + bv
        sig = jax.nn.sigmoid(y_pre)
        dy = dy * (sig * (1.0 + y_pre * (1.0 - sig)))
    dwp_ref[0] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    dbp_ref[0] = jnp.sum(dy, axis=0, keepdims=True)
    dyw = dy * w_ref[0].astype(jnp.float32) if affine else dy
    sum_dy = jnp.sum(dyw)
    sum_dy_xhat = jnp.sum(dyw * xhat)
    dx_ref[0] = (rstd * (dyw - sum_dy / m - xhat * sum_dy_xhat / m)
                 ).astype(dx_ref.dtype)


def _bwd_kernel_eligible(hw: int, cg: int) -> bool:
    # three live fp32 slabs (x, dy, dx) must fit VMEM alongside temps
    return cg % 128 == 0 and hw % 8 == 0 and hw * cg * 4 <= 2 * 1024 * 1024


def _gn_bwd(num_groups, eps, act, res, dy):
    x, weight, bias, saved = res
    n, h, w_, c = x.shape
    g = num_groups
    cg = c // g
    hw = h * w_
    affine = weight is not None

    if saved is None or not _bwd_kernel_eligible(hw, cg):
        return _gn_bwd_jnp(num_groups, eps, act, res, dy)

    mean, rstd = saved
    x_slab = x.reshape(n, hw, g, cg).transpose(0, 2, 1, 3).reshape(
        n * g, hw, cg)
    dy_slab = dy.reshape(n, hw, g, cg).transpose(0, 2, 1, 3).reshape(
        n * g, hw, cg)
    if affine:
        w_slab = jnp.tile(weight.reshape(1, g, 1, cg), (n, 1, 1, 1)
                          ).reshape(n * g, 1, cg)
        b_slab = jnp.tile(bias.reshape(1, g, 1, cg), (n, 1, 1, 1)
                          ).reshape(n * g, 1, cg)
    else:
        w_slab = jnp.zeros((n * g, 1, cg), x.dtype)
        b_slab = jnp.zeros((n * g, 1, cg), x.dtype)

    dx_slab, dwp, dbp = _dispatch.pallas_call(
        functools.partial(_gn_bwd_kernel, act=act or None, affine=affine,
                          m=float(hw * cg)),
        grid=(n * g,),
        in_specs=[
            pl.BlockSpec((1, hw, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, hw, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((n * g, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((n * g, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, cg), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n * g, hw, cg), x.dtype),
            jax.ShapeDtypeStruct((n * g, 1, cg), jnp.float32),
            jax.ShapeDtypeStruct((n * g, 1, cg), jnp.float32),
        ],
        interpret=_INTERPRET(),
    )(x_slab, dy_slab, w_slab, b_slab,
      mean.reshape(n * g, 1), rstd.reshape(n * g, 1))

    dx = dx_slab.reshape(n, g, hw, cg).transpose(0, 2, 1, 3).reshape(
        n, h, w_, c)
    if affine:
        # cross-sample accumulation of the per-slab partials ([n*g, cg])
        dw = dwp.reshape(n, g * cg).sum(axis=0).astype(weight.dtype)
        db = dbp.reshape(n, g * cg).sum(axis=0).astype(bias.dtype)
    else:
        dw = db = None
    return dx, dw, db


def _gn_bwd_jnp(num_groups, eps, act, res, dy):
    """Standard GroupNorm gradient (the reference's bwd kernels compute the
    same two per-group reductions); SiLU grad folded in first. Fallback for
    non-lane-aligned / oversized slabs and for the jnp-forward path."""
    x, weight, bias, saved = res
    n, h, w_, c = x.shape
    g = num_groups
    cg = c // g
    hw = h * w_
    affine = weight is not None

    x32 = x.astype(jnp.float32).reshape(n, hw, g, cg)
    if saved is not None:
        mean, rstd = saved
        mean = mean.reshape(n, 1, g, 1)
        rstd = rstd.reshape(n, 1, g, 1)
    else:
        mean = x32.mean(axis=(1, 3), keepdims=True)
        var = ((x32 - mean) ** 2).mean(axis=(1, 3), keepdims=True)
        rstd = jax.lax.rsqrt(var + eps)
    xhat = (x32 - mean) * rstd

    dy32 = dy.astype(jnp.float32).reshape(n, hw, g, cg)
    if act == "silu":
        # y_pre = affine(xhat); recompute to route grad through silu
        wv = (weight.astype(jnp.float32).reshape(1, 1, g, cg)
              if affine else 1.0)
        bv = (bias.astype(jnp.float32).reshape(1, 1, g, cg)
              if affine else 0.0)
        y_pre = xhat * wv + bv
        sig = jax.nn.sigmoid(y_pre)
        dy32 = dy32 * (sig * (1.0 + y_pre * (1.0 - sig)))

    if affine:
        dw = jnp.sum(dy32 * xhat, axis=(0, 1)).reshape(c)
        db = jnp.sum(dy32, axis=(0, 1)).reshape(c)
        dyw = dy32 * weight.astype(jnp.float32).reshape(1, 1, g, cg)
        dw = dw.astype(weight.dtype)
        db = db.astype(bias.dtype)
    else:
        dw = db = None
        dyw = dy32

    m = hw * cg
    sum_dy = dyw.sum(axis=(1, 3), keepdims=True)
    sum_dy_xhat = (dyw * xhat).sum(axis=(1, 3), keepdims=True)
    dx = rstd * (dyw - sum_dy / m - xhat * sum_dy_xhat / m)
    dx = dx.reshape(n, h, w_, c).astype(x.dtype)
    return dx, dw, db


group_norm_nhwc.defvjp(_gn_fwd_vjp, _gn_bwd)
