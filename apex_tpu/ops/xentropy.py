"""Pallas fused softmax-cross-entropy with label smoothing.

TPU rebuild of ``xentropy_cuda`` (apex/contrib/csrc/xentropy/interface.cpp +
xentropy_kernel.cu — fused log-softmax + NLL + label smoothing that saves only
(logsumexp) instead of the full softmax, recomputing probabilities in the
backward; the memory saving over log_softmax+nll_loss is the point).

Semantics (matching the reference kernel):
  loss_i = lse_i - (1-smoothing) * x_i[y_i] - smoothing * mean_v(x_i[v])
  dx_i   = dLoss_i * (softmax(x_i) - (1-smoothing) * onehot(y_i) - smoothing/V)
Rows whose label equals ``padding_idx`` (if given) produce zero loss and zero
gradient.

The full vocab row lives in VMEM (a (8..64, V) fp32 tile — fine up to V in the
hundreds of thousands); logsumexp accumulates in fp32 regardless of input
dtype.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import _dispatch

_INTERPRET = _dispatch.interpret


def _row_tile(vocab: int, rows: int) -> int:
    # budget sized so the ~5 fp32 intermediates the bwd kernel materializes
    # (x cast, p, onehot match, grad, dx) stay under the default 16MB scoped
    # VMEM limit at BERT/GPT vocab (~30-50k cols)
    return _dispatch.row_tile(vocab, rows, budget_bytes=1024 * 1024,
                              cap=128)


def _fwd_kernel(x_ref, lbl_ref, loss_ref, lse_ref, *, vocab, smoothing,
                padding_idx):
    x = x_ref[...].astype(jnp.float32)
    lbl = lbl_ref[...]  # (tile, 1) int32
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = cols < vocab
    xm = jnp.where(valid, x, -jnp.inf)
    m = jnp.max(xm, axis=-1, keepdims=True)
    sumexp = jnp.sum(jnp.where(valid, jnp.exp(x - m), 0.0), axis=-1,
                     keepdims=True)
    lse = m + jnp.log(sumexp)
    x_t = jnp.sum(jnp.where(cols == lbl, x, 0.0), axis=-1, keepdims=True)
    loss = lse - (1.0 - smoothing) * x_t
    if smoothing > 0.0:
        mean_x = jnp.sum(jnp.where(valid, x, 0.0), axis=-1, keepdims=True) / vocab
        loss = loss - smoothing * mean_x
    if padding_idx is not None:
        loss = jnp.where(lbl == padding_idx, 0.0, loss)
    loss_ref[...] = loss
    lse_ref[...] = lse


def _bwd_kernel(x_ref, lbl_ref, lse_ref, dy_ref, dx_ref, *, vocab, smoothing,
                padding_idx):
    x = x_ref[...].astype(jnp.float32)
    lbl = lbl_ref[...]
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = cols < vocab
    p = jnp.where(valid, jnp.exp(x - lse_ref[...]), 0.0)
    grad = p - (1.0 - smoothing) * (cols == lbl).astype(jnp.float32)
    if smoothing > 0.0:
        grad = grad - jnp.where(valid, smoothing / vocab, 0.0)
    grad = grad * dy_ref[...]
    if padding_idx is not None:
        grad = jnp.where(lbl == padding_idx, 0.0, grad)
    dx_ref[...] = grad.astype(dx_ref.dtype)


def _xent_fwd_call(logits2d, labels, smoothing, padding_idx):
    rows, vocab = logits2d.shape
    tile = _row_tile(vocab, rows)
    v_pad = _dispatch.round_up(vocab, 128)
    r_pad = _dispatch.round_up(rows, tile)
    xp = jnp.pad(logits2d, ((0, r_pad - rows), (0, v_pad - vocab)))
    # pad labels with -1: never matches a column, never equals padding_idx >= 0
    lp = jnp.pad(labels.astype(jnp.int32), (0, r_pad - rows),
                 constant_values=-1).reshape(-1, 1)
    grid = (r_pad // tile,)
    x_spec = pl.BlockSpec((tile, v_pad), lambda i: (i, 0),
                          memory_space=pltpu.VMEM)
    s_spec = pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)

    loss, lse = _dispatch.pallas_call(
        functools.partial(_fwd_kernel, vocab=vocab, smoothing=smoothing,
                          padding_idx=padding_idx),
        grid=grid,
        in_specs=[x_spec, s_spec],
        out_specs=[s_spec, s_spec],
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((r_pad, 1), jnp.float32),
        ],
        interpret=_INTERPRET(),
    )(xp, lp)
    return loss[:rows, 0], lse[:rows, 0]


def _xent_bwd_call(logits2d, labels, lse, dy, smoothing, padding_idx):
    rows, vocab = logits2d.shape
    tile = _row_tile(vocab, rows)
    v_pad = _dispatch.round_up(vocab, 128)
    r_pad = _dispatch.round_up(rows, tile)
    xp = jnp.pad(logits2d, ((0, r_pad - rows), (0, v_pad - vocab)))
    lp = jnp.pad(labels.astype(jnp.int32), (0, r_pad - rows),
                 constant_values=-1).reshape(-1, 1)
    # padded rows: lse=+inf → p=0; dy=0 anyway
    lsep = jnp.pad(lse, (0, r_pad - rows),
                   constant_values=jnp.inf).reshape(-1, 1)
    dyp = jnp.pad(dy.astype(jnp.float32), (0, r_pad - rows)).reshape(-1, 1)
    grid = (r_pad // tile,)
    x_spec = pl.BlockSpec((tile, v_pad), lambda i: (i, 0),
                          memory_space=pltpu.VMEM)
    s_spec = pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    dx = _dispatch.pallas_call(
        functools.partial(_bwd_kernel, vocab=vocab, smoothing=smoothing,
                          padding_idx=padding_idx),
        grid=grid,
        in_specs=[x_spec, s_spec, s_spec, s_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct(xp.shape, logits2d.dtype),
        interpret=_INTERPRET(),
    )(xp, lp, lsep, dyp)
    return dx[:rows, :vocab]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xent(logits2d, labels, smoothing, padding_idx):
    loss, _ = _xent_fwd_call(logits2d, labels, smoothing, padding_idx)
    return loss


def _xent_vfwd(logits2d, labels, smoothing, padding_idx):
    loss, lse = _xent_fwd_call(logits2d, labels, smoothing, padding_idx)
    return loss, (logits2d, labels, lse)


def _xent_vbwd(smoothing, padding_idx, res, dy):
    logits2d, labels, lse = res
    dx = _xent_bwd_call(logits2d, labels, lse, dy, smoothing, padding_idx)
    return dx, None


_xent.defvjp(_xent_vfwd, _xent_vbwd)


def softmax_cross_entropy(
    logits,
    labels,
    smoothing: float = 0.0,
    padding_idx: Optional[int] = None,
):
    """Fused label-smoothed softmax cross entropy, per-row losses (fp32).

    Args:
      logits: [..., vocab] any float dtype (fp32 accumulation inside).
      labels: [...] int class ids.
      smoothing: label-smoothing factor in [0, 1).
      padding_idx: rows with this label get zero loss/grad (reference:
        apex/contrib/xentropy/softmax_xentropy.py SoftmaxCrossEntropyLoss).
    """
    if not 0.0 <= smoothing < 1.0:
        raise ValueError(f"smoothing must be in [0, 1), got {smoothing}")
    vocab = logits.shape[-1]
    lead = logits.shape[:-1]
    loss = _xent(logits.reshape(-1, vocab), labels.reshape(-1),
                 float(smoothing),
                 None if padding_idx is None else int(padding_idx))
    return loss.reshape(lead)
