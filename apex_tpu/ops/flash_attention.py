"""Pallas flash attention (fwd + bwd) — the TPU answer to the reference's
attention kernel zoo.

One kernel family subsumes four reference CUDA extensions (SURVEY.md §2.2):
- ``fast_multihead_attn`` (apex/contrib/csrc/multihead_attn/*.cu — fused QKV
  GEMM + masked softmax + dropout + AV GEMM, self & enc-dec variants)
- ``fmhalib`` (apex/contrib/csrc/fmha/ — flash-style MHA, fp16, seqlen <= 512,
  varlen via cu_seqlens; here varlen = segment_ids and there is NO seqlen cap)
- ``scaled_masked_softmax_cuda`` / ``scaled_upper_triang_masked_softmax_cuda``
  (csrc/megatron/ — the softmax is folded into the attention kernel; a
  standalone fused softmax lives in apex_tpu/ops/scaled_softmax.py)
- attention dropout (``philox.h``) — threaded TPU PRNG seeded per block so the
  backward regenerates the identical keep-mask without storing it.

Algorithm: FlashAttention-2 style. Forward tiles (Bq x Bk) with online
softmax carrying (m, l, acc) in VMEM scratch across the sequential k-block
grid axis; saves only O and LSE. Backward recomputes P from (q, k, LSE) and
accumulates dq over k-blocks and (dk, dv) over q-blocks in two kernels.
All matmuls hit the MXU in the input dtype with fp32 accumulation; softmax
math is fp32 on the VPU.

Layout: q [B, H, Sq, D], k/v [B, Hkv, Sk, D] where Hkv divides H (GQA/MQA:
the kernels index the kv head as ``h // (H/Hkv)`` in their block index maps
— never materialize repeated K/V at a call site). Batch-first; module
facades adapt the reference's seq-first [S, B, H*D] layout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import _dispatch

_INTERPRET = _dispatch.interpret

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


_BLOCK_TABLE = None


def _block_table():
    """Autotuned (sq, sk, d, dtype) -> (block_q, block_k) winners, measured
    on-chip by tpu_autotune.py and committed as _flash_block_table.json
    next to this file. Missing file / missing key -> heuristic default."""
    global _BLOCK_TABLE
    if _BLOCK_TABLE is None:
        import json
        import os

        path = os.path.join(os.path.dirname(__file__),
                            "_flash_block_table.json")
        try:
            with open(path) as f:
                _BLOCK_TABLE = {k: tuple(v) for k, v in json.load(f).items()}
        except Exception:
            _BLOCK_TABLE = {}
    return _BLOCK_TABLE


def _block_sizes(sq: int, sk: int, block_q: Optional[int],
                 block_k: Optional[int], d: Optional[int] = None,
                 dtype=None):
    if block_q is None and block_k is None and d is not None:
        hit = _block_table().get(f"{sq},{sk},{d},{jnp.dtype(dtype).name}")
        if hit:
            return (min(hit[0], _dispatch.round_up(sq, 8)),
                    min(hit[1], _dispatch.round_up(sk, 128)))
    bq = block_q or min(128, _dispatch.round_up(sq, 8))
    bk = block_k or min(128, _dispatch.round_up(sk, 128))
    return bq, bk


#: the (b, h, s, d) shapes a tight-head-dim proof must have covered — the
#: autotune candidate set (tpu_autotune.SHAPES mirrors this) plus the
#: on-chip parity test's shape. The marker records the set it proved;
#: changing this list (new flagship shapes) deliberately invalidates old
#: markers.
TIGHT_PROOF_SHAPES = ((2, 8, 512, 64), (8, 16, 512, 64),
                      (4, 16, 1024, 64), (2, 16, 2048, 64))


def _git_rev():
    """HEAD revision of the checkout this module runs from, with a
    ``-dirty`` suffix when the tree has uncommitted changes (a proof run
    against edited-but-uncommitted kernel code must not validate for the
    clean tree at the same HEAD, or vice versa). None when git metadata
    is unavailable — pip installs, stripped archives."""
    import os
    import subprocess

    cwd = os.path.dirname(os.path.abspath(__file__))
    try:
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=cwd,
                             capture_output=True, text=True, timeout=10)
        rev = out.stdout.strip()
        if not rev:
            return None
        # tracked files only: the marker itself (and round artifacts like
        # TPU_TESTS_*.jsonl) are untracked, and counting them would flip
        # every post-proof read to -dirty, self-invalidating the marker
        status = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            cwd=cwd, capture_output=True, text=True, timeout=10)
        return rev + ("-dirty" if status.stdout.strip() else "")
    except Exception:
        return None


# Read ONCE at import: the value participates in traced shapes, and jit
# caches are not keyed on env vars — a mid-process flip would silently keep
# serving the previously-compiled layout. Set the env before importing
# apex_tpu (tests monkeypatch this constant + jax.clear_caches()).
#
# Default resolution (r5 pre-staged flip): env var wins when set; otherwise
# the layout turns ON once on-chip proof exists — ``_flash_tight_ok.json``,
# written by run_tpu_round.sh only after the on-chip parity test
# (test_flash_attention_tight_head_dim) passed AND the autotuner timed the
# tight layout faster than the 128-padded default on the real chip. The
# compile half of the gate is already discharged offline (AOT_r05.json:
# flash_tight_headdim_* compile to tpu_custom_call on the v5e topology).
#
# Staleness guard (ADVICE r5): the marker is keyed to the git revision and
# the shape set it proved — a marker written at another revision (stale
# proof surviving a flash-kernel change, or a fresh clone carrying someone
# else's artifact) or for a different TIGHT_PROOF_SHAPES is IGNORED.
def _tight_default() -> bool:
    import json
    import os

    env = os.environ.get("APEX_TPU_FLASH_TIGHT_HEADDIM")
    if env is not None:
        return env == "1"
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "_flash_tight_ok.json")) as f:
            marker = json.load(f)
        if not marker.get("ok"):
            return False
        rev = _git_rev()
        if rev is None or marker.get("rev") != rev:
            return False
        # a proof from a dirty tree names no reproducible code state —
        # dirtiness is binary, so "same dirty rev" doesn't mean same
        # kernel; only clean-tree proofs count
        if rev.endswith("-dirty"):
            return False
        if marker.get("shapes") != [list(s) for s in TIGHT_PROOF_SHAPES]:
            return False
        return True
    except Exception:
        return False


_TIGHT_HEADDIM = _tight_default()


def _head_pad(d: int) -> int:
    """Padded head-dim for the kernel blocks.

    Default: round up to a 128-lane multiple — always legal. With
    ``APEX_TPU_FLASH_TIGHT_HEADDIM=1`` (read at import, see
    ``_TIGHT_HEADDIM``) a sublane-aligned d (64 for BERT/GPT-2 heads) is
    kept as-is: the block's minor dim then equals the full array dim,
    which Mosaic's (8, 128)-or-full-dim rule permits, and the QK^T/PV
    contractions stop wasting half their MXU work on zero padding. Gated
    off by default until the on-chip suite
    (tests/test_real_tpu_kernels.py::test_flash_attention_tight_head_dim)
    has proven the layout compiles on the target chip generation.
    """
    if d % 128 == 0:
        return d
    if _TIGHT_HEADDIM and d % 8 == 0:
        return d
    return _dispatch.round_up(d, 128)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = _dispatch.round_up(size, mult) - size
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _mask_block(s, *, b_q, b_k, bq, bk, q_len, kv_len, causal, causal_offset,
                q_seg, kv_seg, window=None):
    """Padding / causal / segment masking for one (bq, bk) score tile.

    Returns (s_filled, live): masked entries get the finite
    DEFAULT_MASK_VALUE (NaN-free max), and callers must ALSO zero their
    exp() by ``live`` — otherwise a fully-masked row degenerates to a
    uniform distribution over every key including the block padding
    (fully-masked rows here output exactly 0, like the padded rows of the
    reference's varlen fmha).
    """
    rows = lax.broadcasted_iota(jnp.int32, s.shape, 0) + b_q * bq
    cols = lax.broadcasted_iota(jnp.int32, s.shape, 1) + b_k * bk
    mask = cols < kv_len
    if causal:
        mask &= (rows + causal_offset) >= cols
    if window is not None:
        # sliding window (Mistral-style): query r sees keys in
        # [r + offset - (window-1), r + offset]
        mask &= cols >= (rows + causal_offset - (window - 1))
    if q_seg is not None:
        mask &= q_seg.reshape(-1, 1) == kv_seg.reshape(1, -1)
    del q_len  # padded q rows produce garbage that the caller slices away
    return jnp.where(mask, s, DEFAULT_MASK_VALUE), mask


def _dropout_keep(shape, rate, seed, bh, row0, col0):
    """Deterministic keep mask / (1-rate) scale for one score tile.

    Counter-based (Philox-spirit, reference: multihead_attn philox.h): each
    global (batch*head, row, col) position hashes to a uniform u32 via murmur3
    finalizer mixing, so forward and both backward kernels regenerate the
    identical mask from the seed alone — nothing is stored, and the mask is
    independent of block shape / grid order. Runs on any backend (the VPU cost
    is a handful of integer ops per element).
    """
    rows = lax.broadcasted_iota(jnp.uint32, shape, 0) + jnp.uint32(row0)
    cols = lax.broadcasted_iota(jnp.uint32, shape, 1) + jnp.uint32(col0)
    x = (rows * jnp.uint32(0x9E3779B1)
         + cols * jnp.uint32(0x85EBCA77)
         + jnp.uint32(bh) * jnp.uint32(0xC2B2AE3D)
         + jnp.uint32(seed))
    x ^= x >> 16
    x *= jnp.uint32(0x85EBCA6B)
    x ^= x >> 13
    x *= jnp.uint32(0xC2B2AE35)
    x ^= x >> 16
    threshold = jnp.uint32(min(int(rate * (2.0 ** 32)), 2 ** 32 - 1))
    return (x >= threshold).astype(jnp.float32) / (1.0 - rate)


# =============================================================================
# forward
# =============================================================================

def _band_width_blocks(span: int, other_block: int, n_total: int) -> int:
    """Blocks needed to cover a sliding band of ``span`` positions when the
    band start is not block-aligned: ceil(span/blk) + 1, capped at n_total."""
    return min(n_total, (span + other_block - 1) // other_block + 1)


def _global_block_ids(i_grid, j_grid, *, bq, bk, causal_offset,
                      window, band_over):
    """Map grid ids to GLOBAL (q-block, k-block) ids.

    With ``window`` set, the dead-block grid dimension is shrunk to the
    band (``band_over`` = "k" for fwd/dq, "q" for dkdv) and the band-local
    id offsets by the band start — so skipped blocks cost neither FLOPs
    nor DMA (grid cells outside the band simply don't exist). Callers
    clamp the returned ids in their BlockSpec index maps; the kernels use
    the UNclamped ids to compute liveness."""
    if window is None or band_over is None:
        return i_grid, j_grid
    if band_over == "k":
        lo = jnp.maximum(
            0, (i_grid * bq + causal_offset - (window - 1)) // bk)
        return i_grid, lo + j_grid
    lo = jnp.maximum(0, (j_grid * bk - causal_offset) // bq)
    return lo + i_grid, j_grid


def _band_index_map(*, bq, bk, n_limit, causal_offset, window, band_over):
    """Clamped grid->global block map for BlockSpec index maps: identity
    when no window, else the band-offset id clamped into [0, n_limit-1]
    (dead cells may DMA a duplicate edge block; the kernels' UNclamped ids
    mark them dead so they never contribute)."""
    if window is None:
        return lambda i_grid, j_grid: (j_grid if band_over == "k"
                                       else i_grid)

    def f(i_grid, j_grid):
        i_g, j_g = _global_block_ids(
            i_grid, j_grid, bq=bq, bk=bk, causal_offset=causal_offset,
            window=window, band_over=band_over)
        return jnp.minimum(j_g if band_over == "k" else i_g, n_limit - 1)

    return f


def _block_live(i_g, j_g, *, bq, bk, nq, nk, causal, causal_offset, window):
    """Liveness of global block (i_g, j_g): inside array bounds, on/below
    the causal diagonal, and inside the sliding-window band."""
    live = True
    if causal:
        live = (i_g * bq + bq - 1 + causal_offset) >= j_g * bk
    if window is not None:
        live &= (j_g * bk + bk - 1
                 >= i_g * bq + causal_offset - (window - 1))
        live &= (i_g < nq) & (j_g < nk)   # band ids can run past the edge
    return live


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, qseg_ref, kseg_ref, seed_ref,
                off_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                scale, causal, causal_offset, q_len, kv_len, bq, bk, nk,
                nq, dropout_rate, window=None, banded=True):
    b, h, i, j = (pl.program_id(d) for d in range(4))
    # a DYNAMIC offset (ring steps whose upstream distance depends on the
    # device index — zigzag CP) arrives as an SMEM scalar; the band-grid
    # restriction needs a static offset, so dynamic callers run unbanded
    # (``banded=False``) and dead blocks are skipped by ``block_live``
    off = off_ref[0, 0] if off_ref is not None else causal_offset
    # under a (static-offset) window the j grid spans only the band;
    # recover global ids
    i_g, j_g = _global_block_ids(i, j, bq=bq, bk=bk,
                                 causal_offset=causal_offset,
                                 window=window if banded else None,
                                 band_over="k")

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    block_live = _block_live(i_g, j_g, bq=bq, bk=bk, nq=nq, nk=nk,
                             causal=causal, causal_offset=off,
                             window=window)

    @pl.when(block_live)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if bias_ref is not None:
            s += bias_ref[0, 0].astype(jnp.float32)
        s, live = _mask_block(
            s, b_q=i_g, b_k=j_g, bq=bq, bk=bk, q_len=q_len, kv_len=kv_len,
            causal=causal, causal_offset=off,
            q_seg=qseg_ref[0] if qseg_ref is not None else None,
            kv_seg=kseg_ref[0] if kseg_ref is not None else None,
            window=window,
        )
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(live, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = m_new
        if dropout_rate > 0.0:
            bh = b * pl.num_programs(1) + h
            p = p * _dropout_keep(p.shape, dropout_rate, seed_ref[0, 0],
                                  bh, i_g * bq + seed_ref[0, 1],
                                  j_g * bk + seed_ref[0, 2])
        v = v_ref[0, 0]
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        l = l_ref[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → output 0
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[...] + jnp.log(l_safe)


def _gqa_rep(heads: int, kv_heads: int) -> int:
    """Query-heads-per-kv-head ratio (1 = standard MHA). The kernels index
    the kv head as ``h // rep`` in their BlockSpec index maps, so GQA/MQA
    never materialize repeated K/V in HBM (the win over jnp.repeat)."""
    if heads % kv_heads != 0:
        raise ValueError(
            f"q heads ({heads}) must be a multiple of kv heads ({kv_heads})")
    return heads // kv_heads


def _fa_fwd(q, k, v, bias, q_seg, kv_seg, seed, scale, causal, dropout_rate,
            block_q, block_k, window=None, causal_offset=None,
            dyn_offset=None):
    batch, heads, q_len, d = q.shape
    kv_len = k.shape[2]
    rep = _gqa_rep(heads, k.shape[1])
    bq, bk = _block_sizes(q_len, kv_len, block_q, block_k, d, q.dtype)
    d_pad = _head_pad(d)

    qp = _pad_to(_pad_to(q, 2, bq), 3, d_pad)
    kp = _pad_to(_pad_to(k, 2, bk), 3, d_pad)
    vp = _pad_to(_pad_to(v, 2, bk), 3, d_pad)
    sq_p, sk_p = qp.shape[2], kp.shape[2]
    nq, nk = sq_p // bq, sk_p // bk
    banded = window is not None and dyn_offset is None
    if dyn_offset is None and causal_offset is None:
        causal_offset = kv_len - q_len   # cross-attention diagonal default

    # band-restricted k grid under a window: dead blocks don't exist, so
    # windowed attention is O(S*window) in DMA as well as FLOPs. A DYNAMIC
    # offset cannot position the band statically: full grid, with dead
    # blocks skipped (FLOPs saved, DMA not) by the kernel's block_live.
    nk_grid = (_band_width_blocks(bq + window - 1, bk, nk) if banded
               else nk)
    jmap = _band_index_map(bq=bq, bk=bk, n_limit=nk,
                           causal_offset=causal_offset,
                           window=window if banded else None,
                           band_over="k")

    in_specs = [
        pl.BlockSpec((1, 1, bq, d_pad), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bk, d_pad),
                     lambda b, h, i, j: (b, h // rep, jmap(i, j), 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, bk, d_pad),
                     lambda b, h, i, j: (b, h // rep, jmap(i, j), 0),
                     memory_space=pltpu.VMEM),
    ]
    args = [qp, kp, vp]
    if bias is not None:
        bias = jnp.broadcast_to(
            bias, (bias.shape[0], bias.shape[1], q_len, kv_len))
        bias = _pad_to(_pad_to(bias, 2, bq), 3, bk)
        bb, bh = bias.shape[0], bias.shape[1]
        in_specs.append(pl.BlockSpec(
            (1, 1, bq, bk),
            lambda b, h, i, j, bb=bb, bh=bh: (b % bb, h % bh, i, jmap(i, j)),
            memory_space=pltpu.VMEM))
        args.append(bias)
    if q_seg is not None:
        qsp = _pad_to(q_seg.astype(jnp.int32), 1, bq)
        ksp = _pad_to(kv_seg.astype(jnp.int32), 1, bk)
        # pad kv segments with -1 so padded keys never match a real segment
        if ksp.shape[1] != kv_seg.shape[1]:
            ksp = ksp.at[:, kv_seg.shape[1]:].set(-1)
        # rank-3 with singleton middle dim so block last-two-dims = (1, bq)
        # satisfies Mosaic's (8, 128)-or-full-dim rule
        in_specs.append(pl.BlockSpec((1, 1, bq), lambda b, h, i, j: (b, 0, i),
                                     memory_space=pltpu.VMEM))
        in_specs.append(pl.BlockSpec(
            (1, 1, bk), lambda b, h, i, j: (b, 0, jmap(i, j)),
            memory_space=pltpu.VMEM))
        args.extend([qsp[:, None], ksp[:, None]])
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec((1, 3), lambda b, h, i, j: (0, 0),
                                     memory_space=pltpu.SMEM))
        args.append(seed)
    if dyn_offset is not None:
        in_specs.append(pl.BlockSpec((1, 1), lambda b, h, i, j: (0, 0),
                                     memory_space=pltpu.SMEM))
        args.append(dyn_offset.astype(jnp.int32).reshape(1, 1))

    def fn(*refs):
        it = iter(refs)
        q_ref, k_ref, v_ref = next(it), next(it), next(it)
        bias_ref = next(it) if bias is not None else None
        qseg_ref = next(it) if q_seg is not None else None
        kseg_ref = next(it) if q_seg is not None else None
        seed_ref = next(it) if dropout_rate > 0.0 else None
        off_ref = next(it) if dyn_offset is not None else None
        o_ref, lse_ref = next(it), next(it)
        acc_ref, m_ref, l_ref = next(it), next(it), next(it)
        _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, qseg_ref, kseg_ref, seed_ref,
                    off_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                    scale=scale, causal=causal, causal_offset=causal_offset,
                    q_len=q_len, kv_len=kv_len, bq=bq, bk=bk, nk=nk, nq=nq,
                    dropout_rate=dropout_rate, window=window, banded=banded)

    o, lse = _dispatch.pallas_call(
        fn,
        grid=(batch, heads, nq, nk_grid),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d_pad), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, sq_p, d_pad), q.dtype),
            jax.ShapeDtypeStruct((batch, heads, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d_pad), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET(),
    )(*args)
    return o[:, :, :q_len, :d], lse[:, :, :q_len, 0]


# =============================================================================
# backward
# =============================================================================

def _recompute_p(q_ref, k_ref, lse_ref, bias_ref, qseg_ref, kseg_ref, *,
                 scale, causal, causal_offset, kv_len, bq, bk, b_q, b_k,
                 window=None):
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if bias_ref is not None:
        s += bias_ref[0, 0].astype(jnp.float32)
    s, live = _mask_block(
        s, b_q=b_q, b_k=b_k, bq=bq, bk=bk, q_len=None, kv_len=kv_len,
        causal=causal, causal_offset=causal_offset,
        q_seg=qseg_ref[0] if qseg_ref is not None else None,
        kv_seg=kseg_ref[0] if kseg_ref is not None else None,
        window=window,
    )
    return jnp.where(live, jnp.exp(s - lse_ref[0, 0].reshape(-1, 1)), 0.0)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               bias_ref, qseg_ref, kseg_ref, seed_ref, off_ref,
               dq_ref, dq_acc, *,
               scale, causal, causal_offset, kv_len, bq, bk, nk, nq,
               dropout_rate, window=None, banded=True):
    b, h, i, j = (pl.program_id(d) for d in range(4))
    off = off_ref[0, 0] if off_ref is not None else causal_offset
    i_g, j_g = _global_block_ids(i, j, bq=bq, bk=bk,
                                 causal_offset=causal_offset,
                                 window=window if banded else None,
                                 band_over="k")

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    block_live = _block_live(i_g, j_g, bq=bq, bk=bk, nq=nq, nk=nk,
                             causal=causal, causal_offset=off,
                             window=window)

    @pl.when(block_live)
    def _body():
        p = _recompute_p(q_ref, k_ref, lse_ref, bias_ref, qseg_ref, kseg_ref,
                         scale=scale, causal=causal,
                         causal_offset=off, kv_len=kv_len,
                         bq=bq, bk=bk, b_q=i_g, b_k=j_g, window=window)
        do = do_ref[0, 0]
        v = v_ref[0, 0]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            bh = b * pl.num_programs(1) + h
            dp = dp * _dropout_keep(dp.shape, dropout_rate, seed_ref[0, 0],
                                    bh, i_g * bq + seed_ref[0, 1],
                                    j_g * bk + seed_ref[0, 2])
        ds = p * (dp - delta_ref[0, 0].reshape(-1, 1)) * scale
        k = k_ref[0, 0]
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 bias_ref, qseg_ref, kseg_ref, seed_ref, off_ref,
                 dk_ref, dv_ref, dk_acc, dv_acc, *,
                 scale, causal, causal_offset, kv_len, bq, bk, nq, nk,
                 dropout_rate, window=None, banded=True):
    # NOTE grid order: (b, h, j over k-blocks, i over q-blocks)
    b, h, j, i = (pl.program_id(d) for d in range(4))
    off = off_ref[0, 0] if off_ref is not None else causal_offset
    i_g, j_g = _global_block_ids(i, j, bq=bq, bk=bk,
                                 causal_offset=causal_offset,
                                 window=window if banded else None,
                                 band_over="q")

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    block_live = _block_live(i_g, j_g, bq=bq, bk=bk, nq=nq, nk=nk,
                             causal=causal, causal_offset=off,
                             window=window)

    @pl.when(block_live)
    def _body():
        p = _recompute_p(q_ref, k_ref, lse_ref, bias_ref, qseg_ref, kseg_ref,
                         scale=scale, causal=causal,
                         causal_offset=off, kv_len=kv_len,
                         bq=bq, bk=bk, b_q=i_g, b_k=j_g, window=window)
        do = do_ref[0, 0]
        v = v_ref[0, 0]
        if dropout_rate > 0.0:
            bh = b * pl.num_programs(1) + h
            keep = _dropout_keep(p.shape, dropout_rate, seed_ref[0, 0],
                                 bh, i_g * bq + seed_ref[0, 1],
                                 j_g * bk + seed_ref[0, 2])
            p_dropped = p * keep
        else:
            keep = None
            p_dropped = p
        dv_acc[...] += jax.lax.dot_general(
            p_dropped.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if keep is not None:
            dp = dp * keep
        ds = p * (dp - delta_ref[0, 0].reshape(-1, 1)) * scale
        q = q_ref[0, 0]
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == pl.num_programs(3) - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _fa_bwd_impl(q, k, v, bias, q_seg, kv_seg, seed, scale, causal,
                 dropout_rate, block_q, block_k, o, lse, do,
                 delta_adjust=None, window=None, causal_offset=None,
                 dyn_offset=None):
    batch, heads, q_len, d = q.shape
    kv_len = k.shape[2]
    kv_heads = k.shape[1]
    rep = _gqa_rep(heads, kv_heads)
    bq, bk = _block_sizes(q_len, kv_len, block_q, block_k, d, q.dtype)
    d_pad = _head_pad(d)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if delta_adjust is not None:
        # an lse cotangent folds into the row correction:
        # ds = p*(dp - delta + dlse) = p*(dp - (delta - dlse))
        delta = delta + delta_adjust

    qp = _pad_to(_pad_to(q, 2, bq), 3, d_pad)
    kp = _pad_to(_pad_to(k, 2, bk), 3, d_pad)
    vp = _pad_to(_pad_to(v, 2, bk), 3, d_pad)
    dop = _pad_to(_pad_to(do, 2, bq), 3, d_pad)
    # pad lse with +inf → p = exp(s - inf) = 0 for padded q rows
    sq_p, sk_p = qp.shape[2], kp.shape[2]
    lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, sq_p - q_len)),
                   constant_values=jnp.inf)[..., None]
    deltap = _pad_to(delta, 2, bq)[..., None]
    nq, nk = sq_p // bq, sk_p // bk
    banded = window is not None and dyn_offset is None
    if dyn_offset is None and causal_offset is None:
        causal_offset = kv_len - q_len

    if banded:
        nkg_dq = _band_width_blocks(bq + window - 1, bk, nk)
        nig_dkdv = _band_width_blocks(bk + window - 1, bq, nq)
    else:
        nkg_dq, nig_dkdv = nk, nq
    jmap_dq = _band_index_map(bq=bq, bk=bk, n_limit=nk,
                              causal_offset=causal_offset,
                              window=window if banded else None,
                              band_over="k")
    _imap = _band_index_map(bq=bq, bk=bk, n_limit=nq,
                            causal_offset=causal_offset,
                            window=window if banded else None,
                            band_over="q")

    def imap_dkdv(j, i):
        return _imap(i, j)

    base_args = [qp, kp, vp, dop, lsep, deltap]
    if bias is not None:
        bias_b = jnp.broadcast_to(
            bias, (bias.shape[0], bias.shape[1], q_len, kv_len))
        bias_p = _pad_to(_pad_to(bias_b, 2, bq), 3, bk)
        bb, bh = bias_p.shape[0], bias_p.shape[1]
        base_args.append(bias_p)
    if q_seg is not None:
        qsp = _pad_to(q_seg.astype(jnp.int32), 1, bq)
        ksp = _pad_to(kv_seg.astype(jnp.int32), 1, bk)
        if ksp.shape[1] != kv_seg.shape[1]:
            ksp = ksp.at[:, kv_seg.shape[1]:].set(-1)
        base_args.extend([qsp[:, None], ksp[:, None]])
    if dropout_rate > 0.0:
        base_args.append(seed)
    if dyn_offset is not None:
        base_args.append(dyn_offset.astype(jnp.int32).reshape(1, 1))

    def make_specs(idx_q, idx_k):
        """Index maps for one kernel given q-block/k-block extractors."""
        def qspec():
            return pl.BlockSpec((1, 1, bq, d_pad),
                                lambda *g: (g[0], g[1], idx_q(g), 0),
                                memory_space=pltpu.VMEM)

        def kspec():
            # kv head = q head // rep (GQA; rep=1 is standard MHA)
            return pl.BlockSpec((1, 1, bk, d_pad),
                                lambda *g: (g[0], g[1] // rep, idx_k(g), 0),
                                memory_space=pltpu.VMEM)

        def rspec():
            return pl.BlockSpec((1, 1, bq, 1),
                                lambda *g: (g[0], g[1], idx_q(g), 0),
                                memory_space=pltpu.VMEM)

        specs = [qspec(), kspec(), kspec(), qspec(), rspec(), rspec()]
        if bias is not None:
            specs.append(pl.BlockSpec(
                (1, 1, bq, bk),
                lambda *g: (g[0] % bb, g[1] % bh, idx_q(g), idx_k(g)),
                memory_space=pltpu.VMEM))
        if q_seg is not None:
            specs.append(pl.BlockSpec((1, 1, bq), lambda *g: (g[0], 0, idx_q(g)),
                                      memory_space=pltpu.VMEM))
            specs.append(pl.BlockSpec((1, 1, bk), lambda *g: (g[0], 0, idx_k(g)),
                                      memory_space=pltpu.VMEM))
        if dropout_rate > 0.0:
            specs.append(pl.BlockSpec((1, 3), lambda *g: (0, 0),
                                      memory_space=pltpu.SMEM))
        if dyn_offset is not None:
            specs.append(pl.BlockSpec((1, 1), lambda *g: (0, 0),
                                      memory_space=pltpu.SMEM))
        return specs

    def split_refs(refs, n_out):
        it = iter(refs)
        ins = [next(it) for _ in range(6)]
        bias_ref = next(it) if bias is not None else None
        qseg_ref = next(it) if q_seg is not None else None
        kseg_ref = next(it) if q_seg is not None else None
        seed_ref = next(it) if dropout_rate > 0.0 else None
        off_ref = next(it) if dyn_offset is not None else None
        outs = [next(it) for _ in range(n_out)]
        scratch = list(it)
        return ins, bias_ref, qseg_ref, kseg_ref, seed_ref, off_ref, \
            outs, scratch

    # ---- dq ----
    def dq_fn(*refs):
        ins, bias_ref, qseg_ref, kseg_ref, seed_ref, off_ref, outs, \
            scratch = split_refs(refs, 1)
        _dq_kernel(*ins, bias_ref, qseg_ref, kseg_ref, seed_ref, off_ref,
                   outs[0], scratch[0],
                   scale=scale, causal=causal, causal_offset=causal_offset,
                   kv_len=kv_len, bq=bq, bk=bk, nk=nk, nq=nq,
                   dropout_rate=dropout_rate, window=window, banded=banded)

    dq = _dispatch.pallas_call(
        dq_fn,
        grid=(batch, heads, nq, nkg_dq),
        in_specs=make_specs(lambda g: g[2], lambda g: jmap_dq(g[2], g[3])),
        out_specs=[pl.BlockSpec((1, 1, bq, d_pad),
                                lambda b, h, i, j: (b, h, i, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((batch, heads, sq_p, d_pad), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, d_pad), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET(),
    )(*base_args)[0]

    # ---- dk, dv ----
    def dkdv_fn(*refs):
        ins, bias_ref, qseg_ref, kseg_ref, seed_ref, off_ref, outs, \
            scratch = split_refs(refs, 2)
        _dkdv_kernel(*ins, bias_ref, qseg_ref, kseg_ref, seed_ref, off_ref,
                     outs[0], outs[1], scratch[0], scratch[1],
                     scale=scale, causal=causal, causal_offset=causal_offset,
                     kv_len=kv_len, bq=bq, bk=bk, nq=nq, nk=nk,
                     dropout_rate=dropout_rate, window=window, banded=banded)

    dk, dv = _dispatch.pallas_call(
        dkdv_fn,
        grid=(batch, heads, nk, nig_dkdv),
        in_specs=make_specs(lambda g: imap_dkdv(g[2], g[3]),
                            lambda g: g[2]),
        out_specs=[
            pl.BlockSpec((1, 1, bk, d_pad), lambda b, h, j, i: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_pad), lambda b, h, j, i: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, sk_p, d_pad), k.dtype),
            jax.ShapeDtypeStruct((batch, heads, sk_p, d_pad), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d_pad), jnp.float32),
            pltpu.VMEM((bk, d_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET(),
    )(*base_args)

    if rep > 1:
        # per-q-head dk/dv partials -> their kv head (fp32 accumulation);
        # identical math to jnp.repeat's VJP but without the forward ever
        # materializing repeated K/V
        dk = dk.astype(jnp.float32).reshape(
            batch, kv_heads, rep, *dk.shape[2:]).sum(axis=2).astype(k.dtype)
        dv = dv.astype(jnp.float32).reshape(
            batch, kv_heads, rep, *dv.shape[2:]).sum(axis=2).astype(v.dtype)
    return (dq[:, :, :q_len, :d], dk[:, :, :kv_len, :d], dv[:, :, :kv_len, :d])


# =============================================================================
# custom-vjp entry
# =============================================================================

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12))
def _flash(q, k, v, bias, q_seg, kv_seg, seed, scale, causal, dropout_rate,
           block_q, block_k, window):
    o, _ = _fa_fwd(q, k, v, bias, q_seg, kv_seg, seed, scale, causal,
                   dropout_rate, block_q, block_k, window)
    return o


def _flash_fwd(q, k, v, bias, q_seg, kv_seg, seed, scale, causal,
               dropout_rate, block_q, block_k, window):
    o, lse = _fa_fwd(q, k, v, bias, q_seg, kv_seg, seed, scale, causal,
                     dropout_rate, block_q, block_k, window)
    return o, (q, k, v, bias, q_seg, kv_seg, seed, o, lse)


def _flash_bwd(scale, causal, dropout_rate, block_q, block_k, window,
               res, do):
    q, k, v, bias, q_seg, kv_seg, seed, o, lse = res
    dq, dk, dv = _fa_bwd_impl(q, k, v, bias, q_seg, kv_seg, seed, scale,
                              causal, dropout_rate, block_q, block_k,
                              o, lse, do, window=window)
    dbias = None if bias is None else jnp.zeros_like(bias)
    dseg = None if q_seg is None else jnp.zeros_like(q_seg)
    dkseg = None if kv_seg is None else jnp.zeros_like(kv_seg)
    return dq, dk, dv, dbias, dseg, dkseg, None


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_with_lse(q, k, v, dyn_off, drop_meta, scale, causal, block_q,
                    block_k, window, causal_offset, dropout_rate):
    """(o, lse) variant for blockwise/ring composition: callers that merge
    partial attention results (ring attention over a context-sharded
    sequence) need the per-row logsumexp, and its cotangent folds into the
    backward's delta correction (see _fa_bwd_impl.delta_adjust).
    ``causal_offset``/``dyn_off`` override the cross-attention diagonal — a
    ring step attending an upstream chunk passes the global row offset so
    causal / window masking applies at GLOBAL positions; ``dyn_off`` is the
    TRACED (1, 1) i32 variant for offsets that depend on the device index
    (zigzag CP). ``drop_meta`` is a (1, 3) i32 [seed, global_row0,
    global_col0] so a CP-sharded sequence regenerates the exact
    single-device keep mask."""
    return _fa_fwd(q, k, v, None, None, None, drop_meta, scale, causal,
                   dropout_rate, block_q, block_k, window, causal_offset,
                   dyn_off)


def _flash_with_lse_fwd(q, k, v, dyn_off, drop_meta, scale, causal, block_q,
                        block_k, window, causal_offset, dropout_rate):
    o, lse = _fa_fwd(q, k, v, None, None, None, drop_meta, scale, causal,
                     dropout_rate, block_q, block_k, window, causal_offset,
                     dyn_off)
    return (o, lse), (q, k, v, dyn_off, drop_meta, o, lse)


def _flash_with_lse_bwd(scale, causal, block_q, block_k, window,
                        causal_offset, dropout_rate, res, cts):
    q, k, v, dyn_off, drop_meta, o, lse = res
    do, dlse = cts
    dq, dk, dv = _fa_bwd_impl(q, k, v, None, None, None, drop_meta, scale,
                              causal, dropout_rate, block_q, block_k,
                              o, lse, do,
                              delta_adjust=-dlse.astype(jnp.float32),
                              window=window, causal_offset=causal_offset,
                              dyn_offset=dyn_off)
    return dq, dk, dv, None, None


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


def flash_attention_with_lse(q, k, v, *, scale: Optional[float] = None,
                             causal: bool = False,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             window: Optional[int] = None,
                             causal_offset=None,
                             dropout_rate: float = 0.0,
                             dropout_seed=0,
                             dropout_row0=0,
                             dropout_col0=0):
    """Flash attention returning ``(o, lse)`` — the building block for
    ring/blockwise attention (apex_tpu/ops/ring_attention.py). Fully
    differentiable including through the lse.

    ``window``/``causal_offset`` let a ring step apply GLOBAL-position
    causal+window masking to an upstream chunk (window requires causal).
    ``causal_offset`` may be a traced value (device-index-dependent
    offsets, zigzag CP): the kernel then masks via an SMEM scalar and the
    static band-grid restriction is disabled (dead blocks still skip their
    FLOPs via the liveness predicate).

    ``dropout_rate``/``dropout_seed`` with ``dropout_row0``/``dropout_col0``
    (global positions of this chunk's first q row / k col, traced OK) make
    the counter-based keep mask a function of GLOBAL coordinates — a ring
    of chunked calls reproduces exactly the mask one unsharded call draws,
    so CP attention dropout matches single-device (reference:
    multihead_attn's fused softmax-dropout under sequence sharding)."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    d = q.shape[-1]
    scale = (1.0 / (d ** 0.5)) if scale is None else scale
    if causal_offset is None or isinstance(causal_offset, (int, np.integer)):
        dyn = None
        static_off = None if causal_offset is None else int(causal_offset)
    else:
        dyn = jnp.asarray(causal_offset, jnp.int32).reshape(1, 1)
        static_off = None
    meta = None
    if dropout_rate > 0.0:
        meta = jnp.stack([
            jnp.asarray(dropout_seed, jnp.int32).reshape(()),
            jnp.asarray(dropout_row0, jnp.int32).reshape(()),
            jnp.asarray(dropout_col0, jnp.int32).reshape(()),
        ]).reshape(1, 3)
    # under an lse cotangent the staged bwd re-runs the fwd kernel for
    # residuals and drops one twin; tpu_custom_call is side-effect-free
    # so XLA DCEs it — training-only path, not worth a custom_vjp split
    # tpu-lint: disable=ir-dead-output -- dead twin is DCE'd by XLA
    return _flash_with_lse(
        q, k, v, dyn, meta, float(scale), causal, block_q, block_k,
        None if window is None else int(window), static_off,
        float(dropout_rate))


def flash_attention(
    q,
    k,
    v,
    bias: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_seed: int = 0,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    window: Optional[int] = None,
):
    """Flash attention: softmax(scale * q @ k^T + bias [masked]) @ v.

    Args:
      q: [batch, heads, q_len, head_dim].
      k, v: [batch, kv_heads, kv_len, head_dim] — ``kv_heads`` may DIVIDE
        ``heads`` (grouped-query / multi-query attention, beyond the
        reference's equal-heads kernels): the kernels index the kv head as
        ``h // (heads/kv_heads)`` in their block index maps, so GQA never
        materializes repeated K/V in HBM.
      bias: optional additive bias/mask broadcastable to
        [batch, heads, q_len, kv_len] (the reference's arbitrary attention
        mask, generic_scaled_masked_softmax); NOT differentiated (masks are
        constants in the reference API).
      segment_ids / kv_segment_ids: optional int32 [batch, len] varlen packing
        (reference fmha cu_seqlens, apex/contrib/csrc/fmha/fmha_api.cpp);
        tokens attend only within equal segment ids. kv_segment_ids defaults
        to segment_ids (self attention).
      causal: upper-triangular masking (scaled_upper_triang_masked_softmax).
      scale: softmax scale; default 1/sqrt(head_dim).
      dropout_rate/dropout_seed: attention-prob dropout (multihead_attn's
        fused softmax-dropout); the keep mask is regenerated in backward from
        the seed, never materialized.
      window: sliding-window width (Mistral-style, requires causal=True):
        query r attends keys [r-window+1, r]. The kernels' k/q grid
        dimension is RESTRICTED to the live band (``_global_block_ids``),
        so out-of-band blocks don't exist at all — neither their FLOPs nor
        their HBM->VMEM copies happen, and end-to-end cost scales
        O(S*window) instead of O(S^2/2). Beyond the reference's kernels
        (its fmha has no windowing at all).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if segment_ids is not None and kv_segment_ids is None:
        kv_segment_ids = segment_ids
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (Mistral-style "
                             "sliding window over a causal sequence)")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    # seed is a *traced* (1,3) SMEM scalar row [seed, row0, col0] so jitted
    # training steps can vary it per step without recompiling (unlike a
    # static-arg seed); row0/col0 are the global-position bases (0 here —
    # ring callers offset them per chunk via flash_attention_with_lse)
    seed = (jnp.stack([jnp.asarray(dropout_seed, jnp.int32).reshape(()),
                       jnp.zeros((), jnp.int32),
                       jnp.zeros((), jnp.int32)]).reshape(1, 3)
            if dropout_rate > 0.0 else None)
    return _flash(q, k, v, bias, segment_ids, kv_segment_ids, seed,
                  float(scale), bool(causal), float(dropout_rate),
                  block_q, block_k,
                  None if window is None else int(window))


def mha_reference(q, k, v, bias=None, segment_ids=None, kv_segment_ids=None,
                  *, causal=False, scale=None, dropout_rate=0.0,
                  dropout_seed=0, window=None):
    """Pure-jnp unfused reference (the 'impl=default' ground-truth path that
    the reference's tests compare the fast kernels against)."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True (same contract as "
                         "flash_attention)")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if segment_ids is not None and kv_segment_ids is None:
        kv_segment_ids = segment_ids
    if k.shape[1] != q.shape[1]:  # GQA ground truth: repeat kv heads
        rep = _gqa_rep(q.shape[1], k.shape[1])
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s += bias.astype(jnp.float32)
    q_len, kv_len = q.shape[2], k.shape[2]
    mask = jnp.ones((q_len, kv_len), bool)
    if causal:
        rows = jnp.arange(q_len)[:, None] + (kv_len - q_len)
        mask &= rows >= jnp.arange(kv_len)[None, :]
        if window is not None:
            mask &= jnp.arange(kv_len)[None, :] >= rows - (window - 1)
    mask = mask[None, None]
    if segment_ids is not None:
        mask = mask & (segment_ids[:, None, :, None]
                       == kv_segment_ids[:, None, None, :])
    # same semantics as the kernel: masked entries contribute exactly zero
    # and fully-masked rows output exactly zero
    s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.where(mask, jnp.exp(s - m), 0.0)
    denom = jnp.sum(e, axis=-1, keepdims=True)
    p = e / jnp.where(denom == 0.0, 1.0, denom)
    if dropout_rate > 0.0:
        raise NotImplementedError(
            "reference path has no in-kernel PRNG; compare dropout runs "
            "statistically against the kernel instead")
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
