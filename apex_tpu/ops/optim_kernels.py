"""Fused optimizer-update Pallas kernels over flat parameter buffers.

TPU-native rebuild of apex's ``amp_C`` multi-tensor kernel family
(csrc/multi_tensor_adam.cu, multi_tensor_lamb.cu + _stage_1/_stage_2,
multi_tensor_novograd.cu, multi_tensor_sgd_kernel.cu,
multi_tensor_l2norm_kernel.cu, multi_tensor_scale_kernel.cu): one launch
updates every parameter of a network. Here the parameters live in one
lane-aligned ``(rows, 1024)`` fp32 buffer (see flat_buffer.py); kernels tile
rows into VMEM, read hyperparameters from SMEM, and compute per-tensor
reductions (LAMB trust ratios, NovoGrad per-layer moments, l2norms) with a
row->segment one-hot matmul on the MXU — replacing the CUDA per-chunk
shared-memory reductions. Inf/NaN detection (the ``noop_flag`` of the
reference) is fused into the stats kernel; update kernels take a ``noop``
scalar that turns the step into an identity (dynamic-loss-scaling skip).

All kernels donate p/m/v via input_output_aliases (no extra HBM copies).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import _dispatch
from apex_tpu.ops.flat_buffer import LANE

_INTERPRET = _dispatch.interpret

STAT_SUMSQ_A = 0   # per-segment sum of squares of buffer A
STAT_SUMSQ_B = 1   # per-segment sum of squares of buffer B
STAT_NONFINITE = 2  # per-segment count of non-finite entries of buffer A
_STAT_ROWS = 8     # fp32 sublane minimum


def _seg_pad(num_segments: int) -> int:
    return max(128, _dispatch.round_up(num_segments, 128))


def _row_block(total_rows: int, n_bufs: int = 5) -> int:
    """Rows per grid step, sized to Mosaic's 16 MB scoped-VMEM stack.

    ``n_bufs`` counts the big (blk, LANE) fp32 blocks live per step (inputs
    + outputs). The Adam kernel (7 buffers + ~10 body temporaries) measured
    17.91 MB of scoped stack at blk=256 — over the limit (caught offline by
    tpu_aot.py at the BERT-Large buffer shape); halving the block halves the
    stack. Kernels with <=6 buffers fit at 256.
    """
    cap = 256 if n_bufs <= 6 else 128
    return min(cap, _dispatch.round_up(total_rows, 8))


def _grid(total_rows: int, blk: int):
    return (_dispatch.cdiv(total_rows, blk),)


def _smem_spec(n):
    return pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.SMEM)


def _buf_spec(blk):
    return pl.BlockSpec((blk, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _seg_spec(blk):
    return pl.BlockSpec((blk, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)


# =============================================================================
# segment stats: per-tensor sumsq (+ nonfinite count) in one pass
#   (reference: csrc/multi_tensor_l2norm_kernel.cu per_tensor=True, and the
#    noop_flag inf/nan detection of multi_tensor_scale_kernel.cu)
# =============================================================================

def _stats_kernel(a_ref, b_ref, seg_ref, out_ref, *, s_pad, total_rows, blk, with_b):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    row_ids = lax.broadcasted_iota(jnp.int32, (blk, 1), 0) + i * blk
    valid = row_ids < total_rows  # (blk, 1) bool
    # Out-of-bounds rows of a partial final block read unspecified memory;
    # they must be where-selected to zero (a multiplicative mask would turn
    # NaN garbage into NaN: 0 * NaN = NaN).
    a = jnp.where(valid, a_ref[...].astype(jnp.float32), 0.0)

    seg = seg_ref[...]  # (blk, 1) int32
    one_hot = (seg == lax.broadcasted_iota(jnp.int32, (blk, s_pad), 1)).astype(jnp.float32)
    one_hot = one_hot * valid.astype(jnp.float32)

    sumsq_a = jnp.sum(a * a, axis=1)[None, :]      # (1, blk)
    nonfin = jnp.sum(1.0 - jnp.isfinite(a).astype(jnp.float32), axis=1)[None, :]
    rows = [sumsq_a]
    if with_b:
        b = jnp.where(valid, b_ref[...].astype(jnp.float32), 0.0)
        rows.append(jnp.sum(b * b, axis=1)[None, :])
    else:
        rows.append(jnp.zeros_like(sumsq_a))
    rows.append(nonfin)
    stat_rows = jnp.concatenate(rows + [jnp.zeros((_STAT_ROWS - 3, blk), jnp.float32)], axis=0)
    # (_STAT_ROWS, blk) @ (blk, s_pad) -> per-segment partials on the MXU
    out_ref[...] += jnp.dot(stat_rows, one_hot, preferred_element_type=jnp.float32)


def segment_stats(a, seg_rows, num_segments: int, b: Optional[jax.Array] = None):
    """Per-segment [sumsq(a), sumsq(b), nonfinite(a)] — one pass over HBM.

    Returns (``_STAT_ROWS``, s_pad) fp32; rows indexed by ``STAT_*``.
    """
    total_rows = a.shape[0]
    blk = _row_block(total_rows)
    s_pad = _seg_pad(num_segments)
    with_b = b is not None

    in_specs = [_buf_spec(blk)]
    args = [a]
    if with_b:
        in_specs.append(_buf_spec(blk))
        args.append(b)
    in_specs.append(_seg_spec(blk))
    args.append(seg_rows.reshape(-1, 1))

    def fn(*refs):
        if with_b:
            a_ref, b_ref, seg_ref, out_ref = refs
        else:
            a_ref, seg_ref, out_ref = refs
            b_ref = None
        _stats_kernel(a_ref, b_ref, seg_ref, out_ref,
                      s_pad=s_pad, total_rows=total_rows, blk=blk, with_b=with_b)

    return _dispatch.pallas_call(
        fn,
        grid=_grid(total_rows, blk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((_STAT_ROWS, s_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((_STAT_ROWS, s_pad), jnp.float32),
        interpret=_INTERPRET(),
    )(*args)


def global_grad_norm_and_finite(g_flat, seg_rows, num_segments):
    """Global L2 norm of the flat grad buffer + all-finite flag (fused pass)."""
    stats = segment_stats(g_flat, seg_rows, num_segments)
    gnorm_sq = jnp.sum(stats[STAT_SUMSQ_A])
    finite = jnp.sum(stats[STAT_NONFINITE]) == 0.0
    return jnp.sqrt(gnorm_sq), finite, stats


# =============================================================================
# Adam / AdamW  (reference: csrc/multi_tensor_adam.cu, apex FusedAdam)
# =============================================================================

_ADAM_HP = 9  # b1, b2, eps, wd, lr, rbc1, rbc2, grad_scale, noop


def _adam_kernel(hp_ref, g_ref, p_ref, m_ref, v_ref, seg_ref, wd_ref,
                 p_out, m_out, v_out, *, adam_w, per_tensor_wd, s_pad):
    b1 = hp_ref[0, 0]
    b2 = hp_ref[0, 1]
    eps = hp_ref[0, 2]
    if per_tensor_wd:
        blk = g_ref.shape[0]
        one_hot = (seg_ref[...] == lax.broadcasted_iota(jnp.int32, (blk, s_pad), 1)).astype(jnp.float32)
        wd = jnp.sum(one_hot * wd_ref[0:1, :], axis=1, keepdims=True)  # (blk, 1)
    else:
        wd = hp_ref[0, 3]
    lr = hp_ref[0, 4]
    rbc1 = hp_ref[0, 5]   # 1/(1-b1^t)
    rbc2 = hp_ref[0, 6]   # 1/(1-b2^t)
    gscale = hp_ref[0, 7]  # unscale * clip factor
    noop = hp_ref[0, 8]

    g = g_ref[...].astype(jnp.float32) * gscale
    p = p_ref[...]
    if not adam_w:
        g = g + wd * p  # L2 mode (reference ADAM_MODE_1)
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    mhat = m * rbc1
    vhat = v * rbc2
    update = mhat / (jnp.sqrt(vhat) + eps)
    if adam_w:
        update = update + wd * p  # decoupled (reference ADAM_MODE_0 / adam_w_mode)
    # where-select (not arithmetic blend): with non-finite grads a 0*inf
    # blend would write NaNs; noop must leave state bit-identical.
    skip = noop > 0.0
    p_out[...] = jnp.where(skip, p, p - lr * update)
    m_out[...] = jnp.where(skip, m_ref[...], m)
    v_out[...] = jnp.where(skip, v_ref[...], v)


def adam_update(g, p, m, v, *, beta1, beta2, eps, weight_decay, lr, step,
                grad_scale=None, noop=None, adam_w_mode=True, bias_correction=True,
                seg_rows=None, num_segments=None):
    """One fused Adam(W) step over flat buffers. Scalars may be traced.

    ``weight_decay`` may be a scalar, or a (num_segments,) per-tensor vector
    when ``seg_rows``/``num_segments`` are given (apex param-group parity).

    Returns (p, m, v) — inputs are donated/aliased.
    """
    total_rows = p.shape[0]
    blk = _row_block(total_rows, n_bufs=7)  # g,p,m,v in + p,m,v out
    one = jnp.float32(1.0)
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        rbc1 = one / (one - jnp.asarray(beta1, jnp.float32) ** step)
        rbc2 = one / (one - jnp.asarray(beta2, jnp.float32) ** step)
    else:
        rbc1 = rbc2 = one

    wd = jnp.asarray(weight_decay, jnp.float32)
    per_tensor_wd = wd.ndim > 0
    if per_tensor_wd and (seg_rows is None or num_segments is None):
        raise ValueError("per-tensor weight_decay requires seg_rows and num_segments")
    s_pad = _seg_pad(num_segments) if per_tensor_wd else 128

    hp = jnp.stack([
        jnp.asarray(beta1, jnp.float32), jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.zeros((), jnp.float32) if per_tensor_wd else wd,
        jnp.asarray(lr, jnp.float32), rbc1, rbc2,
        one if grad_scale is None else jnp.asarray(grad_scale, jnp.float32),
        jnp.zeros((), jnp.float32) if noop is None else jnp.asarray(noop, jnp.float32),
    ]).reshape(1, _ADAM_HP)

    in_specs = [_smem_spec(_ADAM_HP)] + [_buf_spec(blk)] * 4
    args = [hp, g, p, m, v]
    aliases = {2: 0, 3: 1, 4: 2}
    if per_tensor_wd:
        wd_mat = jnp.zeros((_STAT_ROWS, s_pad), jnp.float32).at[0, :num_segments].set(wd)
        in_specs += [_seg_spec(blk),
                     pl.BlockSpec((_STAT_ROWS, s_pad), lambda i: (0, 0), memory_space=pltpu.VMEM)]
        args += [seg_rows.reshape(-1, 1), wd_mat]

    def fn(*refs):
        if per_tensor_wd:
            hp_ref, g_ref, p_ref, m_ref, v_ref, seg_ref, wd_ref, po, mo, vo = refs
        else:
            hp_ref, g_ref, p_ref, m_ref, v_ref, po, mo, vo = refs
            seg_ref = wd_ref = None
        _adam_kernel(hp_ref, g_ref, p_ref, m_ref, v_ref, seg_ref, wd_ref,
                     po, mo, vo, adam_w=adam_w_mode,
                     per_tensor_wd=per_tensor_wd, s_pad=s_pad)

    return _dispatch.pallas_call(
        fn,
        grid=_grid(total_rows, blk),
        in_specs=in_specs,
        out_specs=[_buf_spec(blk)] * 3,
        out_shape=[jax.ShapeDtypeStruct(p.shape, jnp.float32)] * 3,  # tpu-lint: disable=pallas-dtype-drift -- fp32 master params/state by contract
        input_output_aliases=aliases,
        interpret=_INTERPRET(),
    )(*args)


# =============================================================================
# SGD (+momentum/nesterov)  (reference: csrc/multi_tensor_sgd_kernel.cu)
# =============================================================================

_SGD_HP = 6  # lr, momentum, dampening, wd, nesterov, noop(+first_run via mu scale)


def _sgd_kernel(hp_ref, g_ref, p_ref, m_ref, p_out, m_out, *, use_momentum):
    lr = hp_ref[0, 0]
    mu = hp_ref[0, 1]
    damp = hp_ref[0, 2]
    wd = hp_ref[0, 3]
    nesterov = hp_ref[0, 4]
    noop = hp_ref[0, 5]

    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...]
    g = g + wd * p
    if use_momentum:
        m = mu * m_ref[...] + (1.0 - damp) * g
        d = nesterov * (g + mu * m) + (1.0 - nesterov) * m
    else:
        m = m_ref[...]
        d = g
    skip = noop > 0.0
    p_out[...] = jnp.where(skip, p, p - lr * d)
    m_out[...] = jnp.where(skip, m_ref[...], m)


def sgd_update(g, p, m, *, lr, momentum=0.0, dampening=0.0, weight_decay=0.0,
               nesterov=False, noop=None, step=None):
    """``step`` (1-based) reproduces the torch/apex first-use rule: the
    momentum buffer is initialized with the raw gradient (no dampening) on
    the first step."""
    total_rows = p.shape[0]
    blk = _row_block(total_rows)
    damp = jnp.asarray(dampening, jnp.float32)
    if step is not None:
        damp = jnp.where(jnp.asarray(step, jnp.float32) <= 1.0, 0.0, damp)
    hp = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(momentum, jnp.float32),
        damp, jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(1.0 if nesterov else 0.0, jnp.float32),
        jnp.zeros((), jnp.float32) if noop is None else jnp.asarray(noop, jnp.float32),
    ]).reshape(1, _SGD_HP)
    use_momentum = not (isinstance(momentum, (int, float)) and momentum == 0.0)

    return _dispatch.pallas_call(
        functools.partial(_sgd_kernel, use_momentum=use_momentum),
        grid=_grid(total_rows, blk),
        in_specs=[_smem_spec(_SGD_HP)] + [_buf_spec(blk)] * 3,
        out_specs=[_buf_spec(blk)] * 2,
        out_shape=[jax.ShapeDtypeStruct(p.shape, jnp.float32)] * 2,  # tpu-lint: disable=pallas-dtype-drift -- fp32 master params/momentum by contract
        input_output_aliases={2: 0, 3: 1},
        interpret=_INTERPRET(),
    )(hp, g, p, m)


# =============================================================================
# LAMB  (reference: csrc/multi_tensor_lamb.cu — phase 1 computes the adam-style
#        direction + per-tensor ||p|| and ||u||; phase 2 applies trust ratio)
# =============================================================================

_LAMB_HP = 9  # b1, b2, eps, beta3, rbc1, rbc2, grad_scale, noop, (unused)


def _lamb_phase1_kernel(hp_ref, g_ref, p_ref, m_ref, v_ref, seg_ref, wd_ref,
                        u_out, m_out, v_out, stats_out, *, s_pad, total_rows, blk):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        stats_out[...] = jnp.zeros_like(stats_out)

    b1 = hp_ref[0, 0]
    b2 = hp_ref[0, 1]
    eps = hp_ref[0, 2]
    beta3 = hp_ref[0, 3]  # grad_averaging ? (1-b1) : 1  (reference semantics)
    rbc1 = hp_ref[0, 4]
    rbc2 = hp_ref[0, 5]
    gscale = hp_ref[0, 6]
    noop = hp_ref[0, 7]

    g = g_ref[...].astype(jnp.float32) * gscale
    p = p_ref[...]
    seg_one_hot = (seg_ref[...] == lax.broadcasted_iota(jnp.int32, (blk, s_pad), 1)).astype(jnp.float32)
    # per-tensor weight decay (apex expresses this via param groups; here it is
    # a per-segment vector gathered through the same one-hot)
    wd = jnp.sum(seg_one_hot * wd_ref[0:1, :], axis=1, keepdims=True)  # (blk, 1)
    m = b1 * m_ref[...] + beta3 * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    mhat = m * rbc1
    vhat = v * rbc2
    u = mhat / (jnp.sqrt(vhat) + eps) + wd * p

    skip = noop > 0.0
    u_out[...] = jnp.where(skip, 0.0, u)
    m_out[...] = jnp.where(skip, m_ref[...], m)
    v_out[...] = jnp.where(skip, v_ref[...], v)

    row_ids = lax.broadcasted_iota(jnp.int32, (blk, 1), 0) + i * blk
    valid = row_ids < total_rows
    one_hot = seg_one_hot * valid.astype(jnp.float32)
    # where-select (not multiply): OOB rows may hold NaN garbage
    p_safe = jnp.where(valid, p, 0.0)
    u_safe = jnp.where(valid & jnp.logical_not(skip), u, 0.0)
    sumsq_p = jnp.sum(p_safe * p_safe, axis=1)[None, :]
    sumsq_u = jnp.sum(u_safe * u_safe, axis=1)[None, :]
    stat_rows = jnp.concatenate(
        [sumsq_p, sumsq_u, jnp.zeros((_STAT_ROWS - 2, blk), jnp.float32)], axis=0
    )
    stats_out[...] += jnp.dot(stat_rows, one_hot, preferred_element_type=jnp.float32)


def _lamb_phase2_kernel(hp_ref, u_ref, p_ref, ratio_ref, seg_ref, p_out, *, s_pad, blk):
    lr = hp_ref[0, 0]
    noop = hp_ref[0, 1]
    one_hot = (seg_ref[...] == lax.broadcasted_iota(jnp.int32, (blk, s_pad), 1)).astype(jnp.float32)
    # gather per-row trust ratio: (blk, s_pad) * (1, s_pad) summed over segs
    ratio = jnp.sum(one_hot * ratio_ref[0:1, :], axis=1, keepdims=True)  # (blk, 1)
    p = p_ref[...]
    p_out[...] = jnp.where(noop > 0.0, p, p - lr * ratio * u_ref[...])


def lamb_update(g, p, m, v, seg_rows, num_segments, *, beta1, beta2, eps,
                weight_decay, lr, step, grad_scale=None, noop=None,
                bias_correction=True, grad_averaging=True, use_nvlamb=False,
                stats_psum_axis=None):
    """Fused LAMB step: phase-1 kernel (direction + per-tensor norms on the
    MXU) then phase-2 kernel (trust-ratio apply). Mirrors the two-stage
    structure of csrc/multi_tensor_lamb.cu.

    ``weight_decay`` may be a scalar or a (num_segments,) per-tensor vector
    (apex expresses the latter via param groups).

    Trust ratio: ||p|| / ||u|| where defined; 1.0 otherwise (and for tensors
    excluded unless use_nvlamb — reference semantics).

    ``stats_psum_axis``: when the flat buffers are ROW-SHARDS of a larger
    buffer (ZeRO: DistributedFusedLAMB), per-tensor ||p||/||u|| partials must
    be summed across shard ranks between the phases — the analog of the
    reference's allreduce between multi_tensor_lamb_stage_1 and _stage_2
    (apex/contrib/optimizers/distributed_fused_lamb.py).
    """
    total_rows = p.shape[0]
    # phase 1 holds SEVEN big (blk, LANE) fp32 buffers (g,p,m,v in +
    # u,m,v out) — the same count that pushed Adam to 17.91 MB of scoped
    # VMEM at blk=256; cap the block at 128 (ADVICE r5)
    blk = _row_block(total_rows, n_bufs=7)
    s_pad = _seg_pad(num_segments)
    one = jnp.float32(1.0)
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        rbc1 = one / (one - jnp.asarray(beta1, jnp.float32) ** step)
        rbc2 = one / (one - jnp.asarray(beta2, jnp.float32) ** step)
    else:
        rbc1 = rbc2 = one
    beta3 = (one - jnp.asarray(beta1, jnp.float32)) if grad_averaging else one
    noop_s = jnp.zeros((), jnp.float32) if noop is None else jnp.asarray(noop, jnp.float32)
    hp1 = jnp.stack([
        jnp.asarray(beta1, jnp.float32), jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32), beta3,
        rbc1, rbc2,
        one if grad_scale is None else jnp.asarray(grad_scale, jnp.float32),
        noop_s, jnp.zeros((), jnp.float32),
    ]).reshape(1, _LAMB_HP)

    wd = jnp.asarray(weight_decay, jnp.float32)
    if wd.ndim == 0:
        wd_vec = jnp.full((num_segments,), wd, jnp.float32)
    else:
        wd_vec = wd
    wd_mat = jnp.zeros((_STAT_ROWS, s_pad), jnp.float32).at[0, :num_segments].set(wd_vec)

    seg2d = seg_rows.reshape(-1, 1)
    u, m, v, stats = _dispatch.pallas_call(
        functools.partial(_lamb_phase1_kernel, s_pad=s_pad, total_rows=total_rows, blk=blk),
        grid=_grid(total_rows, blk),
        in_specs=[_smem_spec(_LAMB_HP)] + [_buf_spec(blk)] * 4 + [_seg_spec(blk)]
        + [pl.BlockSpec((_STAT_ROWS, s_pad), lambda i: (0, 0), memory_space=pltpu.VMEM)],
        out_specs=[_buf_spec(blk)] * 3
        + [pl.BlockSpec((_STAT_ROWS, s_pad), lambda i: (0, 0), memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct(p.shape, jnp.float32)] * 3  # tpu-lint: disable=pallas-dtype-drift -- fp32 master params/state by contract
        + [jax.ShapeDtypeStruct((_STAT_ROWS, s_pad), jnp.float32)],
        input_output_aliases={3: 1, 4: 2},
        interpret=_INTERPRET(),
    )(hp1, g, p, m, v, seg2d, wd_mat)

    if stats_psum_axis is not None:
        stats = lax.psum(stats, stats_psum_axis)
    p_norm = jnp.sqrt(stats[0])  # (s_pad,)
    u_norm = jnp.sqrt(stats[1])
    # reference trust-ratio rule (multi_tensor_lamb.cu): ratio = ||p||/||u||
    # when both norms > 0, else 1 — and with use_nvlamb=False (default) the
    # ratio is only applied to weight-decayed tensors; decay-excluded tensors
    # (wd == 0) get ratio 1.
    ratio = jnp.where((p_norm > 0.0) & (u_norm > 0.0), p_norm / jnp.maximum(u_norm, 1e-30), 1.0)
    if not use_nvlamb:
        wd_full = jnp.zeros((s_pad,), jnp.float32).at[:num_segments].set(wd_vec)
        ratio = jnp.where(wd_full > 0.0, ratio, 1.0)
    ratio_mat = jnp.zeros((_STAT_ROWS, s_pad), jnp.float32).at[0].set(ratio)

    hp2 = jnp.stack([jnp.asarray(lr, jnp.float32), noop_s]).reshape(1, 2)
    p_new = _dispatch.pallas_call(
        functools.partial(_lamb_phase2_kernel, s_pad=s_pad, blk=blk),
        grid=_grid(total_rows, blk),
        in_specs=[_smem_spec(2), _buf_spec(blk), _buf_spec(blk),
                  pl.BlockSpec((_STAT_ROWS, s_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
                  _seg_spec(blk)],
        out_specs=_buf_spec(blk),
        out_shape=jax.ShapeDtypeStruct(p.shape, jnp.float32),  # tpu-lint: disable=pallas-dtype-drift -- fp32 master params by contract
        input_output_aliases={2: 0},
        interpret=_INTERPRET(),
    )(hp2, u, p, ratio_mat, seg2d)
    return p_new, m, v


# =============================================================================
# NovoGrad  (reference: csrc/multi_tensor_novograd.cu — per-tensor 2nd moment)
# =============================================================================

_NVG_HP = 7  # b1, beta3, eps(unused: folded into vden), wd, lr, grad_scale, noop


def _novograd_kernel(hp_ref, g_ref, p_ref, m_ref, vden_ref, seg_ref,
                     p_out, m_out, *, s_pad, blk):
    b1 = hp_ref[0, 0]
    beta3 = hp_ref[0, 1]  # grad_averaging ? (1-b1) : 1
    wd = hp_ref[0, 3]
    lr = hp_ref[0, 4]
    gscale = hp_ref[0, 5]
    noop = hp_ref[0, 6]

    g = g_ref[...].astype(jnp.float32) * gscale
    p = p_ref[...]
    one_hot = (seg_ref[...] == lax.broadcasted_iota(jnp.int32, (blk, s_pad), 1)).astype(jnp.float32)
    vden = jnp.sum(one_hot * vden_ref[0:1, :], axis=1, keepdims=True)  # sqrt(v_t)+eps per row
    gn = g / vden + wd * p
    m = b1 * m_ref[...] + beta3 * gn
    skip = noop > 0.0
    p_out[...] = jnp.where(skip, p, p - lr * m)
    m_out[...] = jnp.where(skip, m_ref[...], m)


def novograd_update(g, p, m, v_per_tensor, seg_rows, num_segments, *, beta1, beta2,
                    eps, weight_decay, lr, step, grad_scale=None, noop=None,
                    grad_averaging=True, init_zero=False):
    """Fused NovoGrad step. ``v_per_tensor`` is the (num_segments,) per-tensor
    second moment ||g||^2 EMA (reference keeps one float per tensor).

    Returns (p, m, v_per_tensor).
    """
    total_rows = p.shape[0]
    blk = _row_block(total_rows)
    s_pad = _seg_pad(num_segments)

    gnorm, finite, stats = global_grad_norm_and_finite(g, seg_rows, num_segments)
    gs = jnp.float32(1.0) if grad_scale is None else jnp.asarray(grad_scale, jnp.float32)
    g_sumsq = stats[STAT_SUMSQ_A][:num_segments] * gs * gs
    step = jnp.asarray(step, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    # reference first-step rule: v_1 = ||g||^2 unless init_zero (then the EMA
    # runs from zero: v_1 = (1-b2)||g||^2) — apex fused_novograd.py init_zero
    first = (1.0 - b2) * g_sumsq if init_zero else g_sumsq
    v_new = jnp.where(step <= 1.0, first, b2 * v_per_tensor + (1.0 - b2) * g_sumsq)
    vden = jnp.sqrt(v_new) + jnp.asarray(eps, jnp.float32)
    vden_mat = jnp.zeros((_STAT_ROWS, s_pad), jnp.float32).at[0, :num_segments].set(vden)

    noop_s = jnp.zeros((), jnp.float32) if noop is None else jnp.asarray(noop, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    beta3 = (1.0 - b1) if grad_averaging else jnp.float32(1.0)
    hp = jnp.stack([
        b1, beta3, jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32), jnp.asarray(lr, jnp.float32),
        gs, noop_s,
    ]).reshape(1, _NVG_HP)

    p_new, m_new = _dispatch.pallas_call(
        functools.partial(_novograd_kernel, s_pad=s_pad, blk=blk),
        grid=_grid(total_rows, blk),
        in_specs=[_smem_spec(_NVG_HP), _buf_spec(blk), _buf_spec(blk), _buf_spec(blk),
                  pl.BlockSpec((_STAT_ROWS, s_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
                  _seg_spec(blk)],
        out_specs=[_buf_spec(blk)] * 2,
        out_shape=[jax.ShapeDtypeStruct(p.shape, jnp.float32)] * 2,  # tpu-lint: disable=pallas-dtype-drift -- fp32 master params/momentum by contract
        input_output_aliases={2: 0, 3: 1},
        interpret=_INTERPRET(),
    )(hp, g, p, m, vden_mat, seg_rows.reshape(-1, 1))
    v_out = jnp.where(noop_s > 0.0, v_per_tensor, v_new)
    return p_new, m_new, v_out


# =============================================================================
# scale (amp unscale with found-inf)  (reference: multi_tensor_scale_kernel.cu)
# =============================================================================

def _scale_kernel(hp_ref, x_ref, y_out):
    y_out[...] = x_ref[...].astype(jnp.float32) * hp_ref[0, 0]


def multi_tensor_scale(x, scale):
    """out = x * scale over a flat buffer (one launch)."""
    total_rows = x.shape[0]
    blk = _row_block(total_rows)
    hp = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    return _dispatch.pallas_call(
        _scale_kernel,
        grid=_grid(total_rows, blk),
        in_specs=[_smem_spec(1), _buf_spec(blk)],
        out_specs=_buf_spec(blk),
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),  # tpu-lint: disable=pallas-dtype-drift -- amp unscale emits fp32 master grads
        interpret=_INTERPRET(),
    )(hp, x)
