"""Fused LayerNorm / RMSNorm Pallas kernels with custom VJP.

TPU-native rebuild of the reference's ``fused_layer_norm_cuda`` extension
(csrc/layer_norm_cuda.cpp:~300, csrc/layer_norm_cuda_kernel.cu:~900 — per-row
Welford mean/invvar with fp32 accumulation, affine/non-affine/RMS variants,
two-stage dgamma/dbeta reduction) and of ``fast_layer_norm``
(apex/contrib/csrc/layer_norm/ — the same math hand-tuned per hidden size).
One kernel family replaces both: rows are tiled into VMEM and the hidden dim
is reduced in fp32 on the VPU; the backward fuses dx with the dgamma/dbeta
row-reduction by accumulating partials across sequential grid steps (the
Pallas analog of the CUDA two-stage shared-memory reduction).

API semantics match apex/normalization/fused_layer_norm.py:
- fp32 accumulation regardless of input dtype; output in input dtype
- ``memory_efficient=True`` saves the *output* instead of the input and
  recomputes x-hat in backward (FusedLayerNormAffineFunction's
  memory_efficient flag). Caveat (inherent to the trick, same as the
  reference's kernel): x-hat is recovered as (y - beta) / gamma, so with
  16-bit activations and entries of gamma near zero the recovered x-hat —
  and hence d-gamma — loses precision (measured: exact in fp32; ~0.7% max
  rel err in bf16 with |gamma| >= 0.5; unusable when |gamma| ~ 1e-3). Keep
  gamma well-conditioned or use the default path in low precision.
- weight/bias may be fp32 while x is bf16 (the "Mixed" variants)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import _dispatch

_INTERPRET = _dispatch.interpret


def _row_tile(n_cols: int, n_rows: int, bytes_per_el: int = 4) -> int:
    """Pick a row-tile so x-tile + scratch stay under the 16MB scoped-VMEM
    limit: the bwd kernel holds ~8 fp32 tile-sized arrays (x, dy, xhat, dx,
    partial dgamma/dbeta, temporaries), so cap tiles at 1MB each."""
    return _dispatch.row_tile(n_cols, n_rows, cap=256,
                              budget_bytes=1024 * 1024,
                              bytes_per_el=bytes_per_el)


# =============================================================================
# forward
# =============================================================================

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps, affine, rms):
    x = x_ref[...].astype(jnp.float32)
    if rms:
        mean = jnp.zeros((x.shape[0], 1), jnp.float32)
        var = jnp.mean(x * x, axis=-1, keepdims=True)
    else:
        mean = jnp.mean(x, axis=-1, keepdims=True)
        xc = x - mean
        var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    if affine:
        w = w_ref[...].astype(jnp.float32)  # (1, cols)
        y = xhat * w
        if b_ref is not None:
            y = y + b_ref[...].astype(jnp.float32)
    else:
        y = xhat
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _ln_fwd(x2d, weight, bias, eps, rms):
    rows, cols = x2d.shape
    affine = weight is not None
    tile = _row_tile(cols, rows)
    grid = (_dispatch.cdiv(rows, tile),)

    kernel = functools.partial(_ln_fwd_kernel, eps=eps, affine=affine, rms=rms)
    if not affine:
        def kernel_noaff(x_ref, y_ref, mean_ref, rstd_ref):
            _ln_fwd_kernel(x_ref, None, None, y_ref, mean_ref, rstd_ref,
                           eps=eps, affine=False, rms=rms)
        fn = kernel_noaff
        in_specs = [pl.BlockSpec((tile, cols), lambda i: (i, 0), memory_space=pltpu.VMEM)]
        args = (x2d,)
    elif bias is None:
        def kernel_nobias(x_ref, w_ref, y_ref, mean_ref, rstd_ref):
            _ln_fwd_kernel(x_ref, w_ref, None, y_ref, mean_ref, rstd_ref,
                           eps=eps, affine=True, rms=rms)
        fn = kernel_nobias
        in_specs = [
            pl.BlockSpec((tile, cols), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cols), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ]
        args = (x2d, weight.reshape(1, cols))
    else:
        fn = kernel
        in_specs = [
            pl.BlockSpec((tile, cols), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cols), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cols), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ]
        args = (x2d, weight.reshape(1, cols), bias.reshape(1, cols))

    y, mean, rstd = _dispatch.pallas_call(
        fn,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((tile, cols), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cols), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=_INTERPRET(),
    )(*args)
    return y, mean, rstd


# =============================================================================
# backward
# =============================================================================

def _ln_bwd_kernel(dy_ref, xhat_src_ref, mean_ref, rstd_ref, w_ref, b_ref,
                   dx_ref, dw_ref, db_ref, *, affine, rms, from_y, n_rows, tile):
    """dx for this row tile; dgamma/dbeta partials accumulated across the
    (sequential) grid — Pallas analog of csrc/layer_norm_cuda_kernel.cu's
    two-stage shared-memory reduction."""
    i = pl.program_id(0)
    dy = dy_ref[...].astype(jnp.float32)
    rstd = rstd_ref[...]  # (tile, 1) fp32
    cols = dy.shape[1]

    if affine:
        w = w_ref[...].astype(jnp.float32)  # (1, cols)
    else:
        w = jnp.ones((1, cols), jnp.float32)

    src = xhat_src_ref[...].astype(jnp.float32)
    if from_y:
        # memory_efficient: recompute xhat from the saved output
        if affine:
            b = b_ref[...].astype(jnp.float32) if b_ref is not None else 0.0
            xhat = (src - b) / w
        else:
            xhat = src
    else:
        mean = mean_ref[...] if not rms else 0.0
        xhat = (src - mean) * rstd

    # mask padded rows so dw/db partials are exact on ragged final tiles;
    # where-select, not multiply: OOB rows hold unspecified memory and
    # 0 * NaN = NaN would poison the cross-row dgamma/dbeta reduction
    row_ids = lax.broadcasted_iota(jnp.int32, dy.shape, 0) + i * tile
    valid = row_ids < n_rows
    dy = jnp.where(valid, dy, 0.0)
    xhat = jnp.where(valid, xhat, 0.0)

    wdy = dy * w
    c1 = jnp.mean(xhat * wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy, axis=-1, keepdims=True)
    if rms:
        dx = (wdy - xhat * c1) * rstd
    else:
        dx = (wdy - xhat * c1 - c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)

    if affine:
        @pl.when(i == 0)
        def _init():
            dw_ref[...] = jnp.zeros_like(dw_ref)
            if db_ref is not None:
                db_ref[...] = jnp.zeros_like(db_ref)

        dw_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
        if db_ref is not None:
            db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def _ln_bwd(dy2d, saved, weight, bias, eps, rms, memory_efficient):
    xhat_src, mean, rstd = saved
    rows, cols = dy2d.shape
    affine = weight is not None
    has_bias = bias is not None
    tile = _row_tile(cols, rows)
    grid = (_dispatch.cdiv(rows, tile),)

    x_spec = pl.BlockSpec((tile, cols), lambda i: (i, 0), memory_space=pltpu.VMEM)
    s_spec = pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM)
    v_spec = pl.BlockSpec((1, cols), lambda i: (0, 0), memory_space=pltpu.VMEM)

    out_specs = [x_spec]
    out_shape = [jax.ShapeDtypeStruct((rows, cols), dy2d.dtype)]
    if affine:
        out_specs.append(v_spec)
        out_shape.append(jax.ShapeDtypeStruct((1, cols), jnp.float32))
        if has_bias:
            out_specs.append(v_spec)
            out_shape.append(jax.ShapeDtypeStruct((1, cols), jnp.float32))

    needs_mean = mean is not None
    in_specs = [x_spec, x_spec]
    args = [dy2d, xhat_src]
    if needs_mean:
        in_specs.append(s_spec)
        args.append(mean)
    in_specs.append(s_spec)
    args.append(rstd)
    if affine:
        in_specs.append(v_spec)
        args.append(weight.reshape(1, cols))
        if has_bias and memory_efficient:
            in_specs.append(v_spec)
            args.append(bias.reshape(1, cols))

    def fn(*refs):
        it = iter(refs)
        dy_ref, src_ref = next(it), next(it)
        mean_ref = next(it) if needs_mean else None
        rstd_ref = next(it)
        w_ref = next(it) if affine else None
        b_ref = next(it) if (affine and has_bias and memory_efficient) else None
        dx_ref = next(it)
        dw_ref = next(it) if affine else None
        db_ref = next(it) if (affine and has_bias) else None
        _ln_bwd_kernel(dy_ref, src_ref, mean_ref, rstd_ref, w_ref, b_ref,
                       dx_ref, dw_ref, db_ref,
                       affine=affine, rms=rms, from_y=memory_efficient,
                       n_rows=rows, tile=tile)

    outs = _dispatch.pallas_call(
        fn,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_INTERPRET(),
    )(*args)
    dx = outs[0]
    dw = outs[1].reshape(-1).astype(weight.dtype) if affine else None
    db = outs[2].reshape(-1).astype(bias.dtype) if (affine and has_bias) else None
    return dx, dw, db


# =============================================================================
# public custom-vjp ops
# =============================================================================

def _norm_impl(x, weight, bias, eps, rms, memory_efficient):
    shape = x.shape
    cols = shape[-1]
    x2d = x.reshape(-1, cols)
    y, mean, rstd = _ln_fwd(x2d, weight, bias, eps, rms)
    # mean is only consumed by the default (save-x) LayerNorm backward; drop
    # it otherwise so memory_efficient actually shrinks the residual set
    # (apex's memory_efficient discards mean the same way).
    keep_mean = mean if (not rms and not memory_efficient) else None
    return y.reshape(shape), (y if memory_efficient else x2d, keep_mean, rstd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_norm(x, weight, bias, eps, rms, memory_efficient):
    return _norm_impl(x, weight, bias, eps, rms, memory_efficient)[0]


def _fused_norm_fwd(x, weight, bias, eps, rms, memory_efficient):
    y, (src, mean, rstd) = _norm_impl(x, weight, bias, eps, rms, memory_efficient)
    src2d = src.reshape(-1, src.shape[-1])
    return y, (src2d, mean, rstd, weight, bias, x.shape)


def _fused_norm_bwd(eps, rms, memory_efficient, res, dy):
    src2d, mean, rstd, weight, bias, shape = res
    dy2d = dy.reshape(-1, shape[-1])
    dx, dw, db = _ln_bwd(dy2d, (src2d, mean, rstd), weight, bias, eps, rms, memory_efficient)
    return (dx.reshape(shape), dw, db)


_fused_norm.defvjp(_fused_norm_fwd, _fused_norm_bwd)


def layer_norm(
    x,
    weight: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    eps: float = 1e-5,
    memory_efficient: bool = False,
):
    """Fused LayerNorm over the last dimension.

    Reference API: apex/normalization/fused_layer_norm.py
    (FusedLayerNormAffineFunction / FusedLayerNormFunction).
    """
    if weight is None and bias is not None:
        raise ValueError("layer_norm: bias requires weight (the reference API has no bias-only variant)")
    return _fused_norm(x, weight, bias, float(eps), False, bool(memory_efficient))


def rms_norm(
    x,
    weight: Optional[jax.Array] = None,
    eps: float = 1e-5,
    memory_efficient: bool = False,
):
    """Fused RMSNorm over the last dimension.

    Reference API: apex/normalization/fused_layer_norm.py
    (FusedRMSNormAffineFunction / FusedRMSNormFunction).
    """
    return _fused_norm(x, weight, None, float(eps), True, bool(memory_efficient))
