"""Backend dispatch for Pallas kernels.

Kernels compile via Mosaic on TPU. Off-TPU (CPU tests, debugging) the same
kernels run through the Pallas interpreter so numerics tests cover the real
kernel code, not a separate fallback — replacing the reference's
"skip-if-extension-not-built" gating (apex/contrib/test SkipTestCase) with
run-everywhere kernels.
"""

from __future__ import annotations

import functools
import os

import jax


@functools.cache
def _backend() -> str:
    return jax.default_backend()


def interpret() -> bool:
    """True when pallas_call must run in interpreter mode (non-TPU backend).

    ``APEX_TPU_FORCE_MOSAIC=1`` forces the Mosaic path even when the default
    backend is CPU — used by the offline AOT evidence tier (``tpu_aot.py``),
    which lowers kernels against a device-less TPU *topology*
    (``jax.experimental.topologies``) where ``jax.default_backend()`` still
    reports the host platform.
    """
    if os.environ.get("APEX_TPU_FORCE_INTERPRET") == "1":
        return True
    if os.environ.get("APEX_TPU_FORCE_MOSAIC") == "1":
        return False
    return _backend() != "tpu"


def pallas_call(kernel, *, out_shape, **kw):
    """``pl.pallas_call`` that propagates varying-manual-axes (vma).

    Inside ``shard_map(check_vma=True)`` a pallas_call must declare how its
    outputs vary over mesh axes; the correct answer for our elementwise/
    row-tiled kernels is "varies over the union of the inputs' axes". This
    wrapper stamps that union onto every ShapeDtypeStruct in ``out_shape`` at
    call time, so all ops work under both jit and manual shard_map without
    per-site bookkeeping.
    """
    from jax.experimental import pallas as pl

    from jax import lax

    def call(*args):
        vma = frozenset()
        for a in jax.tree.leaves(args):
            vma = vma | getattr(jax.typeof(a), "vma", frozenset())

        def lift(a):
            # align every input to the union vma (a replicated operand next
            # to a varying one trips "varying manual axes must match" inside
            # the kernel body)
            missing = vma - getattr(jax.typeof(a), "vma", frozenset())
            return lax.pcast(a, tuple(missing), to="varying") if missing else a

        def stamp(s):
            # empty vma: pass s through untouched (also keeps older jax,
            # whose ShapeDtypeStruct has no vma kwarg, working — there the
            # union is always empty)
            if isinstance(s, jax.ShapeDtypeStruct) and vma:
                return jax.ShapeDtypeStruct(s.shape, s.dtype, vma=vma)
            return s

        args = jax.tree.map(lift, args)
        os_ = jax.tree.map(stamp, out_shape)
        return pl.pallas_call(kernel, out_shape=os_, **kw)(*args)

    return call


def round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def row_tile(n_cols: int, n_rows: int, *, budget_bytes: int = 2 * 1024 * 1024,
             cap: int = 256, bytes_per_el: int = 4) -> int:
    """Row-tile size so one (tile, n_cols) fp32 block stays within a VMEM
    budget; multiple of 8 (sublane), bounded by ``cap`` and the row count."""
    tile = max(8, budget_bytes // max(1, n_cols * bytes_per_el))
    tile = min(tile, cap)
    tile = max(8, (tile // 8) * 8)
    return min(tile, round_up(n_rows, 8))
