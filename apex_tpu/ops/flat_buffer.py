"""Flattened parameter buffers — the TPU analog of apex's multi-tensor apply.

Reference: csrc/multi_tensor_apply.cuh (~130 lines) dispatches one CUDA kernel
over a chunked list-of-tensor-pointers so a whole optimizer step is a handful
of launches (capped by depth_to_max_tensors ~30-110 per launch). On TPU the
same amortization is achieved differently: every tensor in a pytree is padded
to a lane-aligned length and concatenated once into a single fp32 buffer
viewed as ``(rows, LANE)``; optimizer kernels then run ONE Pallas launch over
row tiles. Per-tensor reductions (LAMB trust ratios, NovoGrad per-layer norms)
use a row->segment map: each 1024-element row belongs to exactly one tensor,
so per-segment partial sums become a small one-hot matmul on the MXU.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

LANE = 1024  # elements per row: 8 sublanes x 128 lanes (fp32 min tile)


@dataclasses.dataclass(frozen=True)
class FlatSpec:
    """Static layout of a flattened pytree."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]          # unpadded element counts
    row_offsets: Tuple[int, ...]    # starting row of each tensor
    row_counts: Tuple[int, ...]     # rows occupied by each tensor
    total_rows: int

    @property
    def num_tensors(self) -> int:
        return len(self.shapes)

    @property
    def total_elements(self) -> int:
        return self.total_rows * LANE

    def segment_rows(self) -> np.ndarray:
        """int32 (total_rows,) mapping each row to its tensor index."""
        seg = np.zeros(self.total_rows, np.int32)
        for i, (off, cnt) in enumerate(zip(self.row_offsets, self.row_counts)):
            seg[off : off + cnt] = i
        return seg


def build_spec(tree) -> FlatSpec:
    leaves, treedef = jax.tree.flatten(tree)
    shapes, dtypes, sizes, row_offsets, row_counts = [], [], [], [], []
    row = 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.ndim else 1
        rows = max(1, -(-n // LANE))
        shapes.append(tuple(leaf.shape))
        dtypes.append(leaf.dtype)
        sizes.append(n)
        row_offsets.append(row)
        row_counts.append(rows)
        row += rows
    return FlatSpec(
        treedef=treedef,
        shapes=tuple(shapes),
        dtypes=tuple(dtypes),
        sizes=tuple(sizes),
        row_offsets=tuple(row_offsets),
        row_counts=tuple(row_counts),
        total_rows=row,
    )


def flatten(tree, spec: FlatSpec, dtype=jnp.float32) -> jax.Array:
    """Concatenate a pytree into one padded ``(total_rows, LANE)`` buffer.

    Built as a concat of per-leaf ``(rows_i, LANE)`` blocks along axis 0 —
    never as one giant 1D array. A full-buffer 1D<->2D reshape is NOT a
    bitcast under TPU tiled layouts, and with an odd ``total_rows`` the
    backend lowers it through a relayout whose intermediate allocates
    ~64x the buffer (observed on-chip: an f32[N/2, 2] relayout tile-padded
    2->128 lanes = 86 GB for BERT-Large; TPU_TESTS_r03.log). Row-space
    concat keeps every reshape leaf-local.
    """
    leaves = jax.tree.leaves(tree)
    parts: List[jax.Array] = []
    for leaf, n, rows in zip(leaves, spec.sizes, spec.row_counts):
        v = leaf.reshape(-1).astype(dtype)
        pad = rows * LANE - n
        if pad:
            v = jnp.concatenate([v, jnp.zeros((pad,), dtype)])
        parts.append(v.reshape(rows, LANE))
    return jnp.concatenate(parts, axis=0)


def unflatten(flat: jax.Array, spec: FlatSpec, dtypes: Sequence[Any] | None = None):
    """Slice a ``(total_rows, LANE)`` buffer back into the original pytree.

    Row-sliced per leaf (2D static slices) so the only 1D reshapes are
    leaf-sized — see ``flatten`` for why a full-buffer 1D view is
    catastrophic under TPU tiled layouts.
    """
    leaves = []
    for shape, dt, n, off, cnt in zip(
        spec.shapes,
        dtypes if dtypes is not None else spec.dtypes,
        spec.sizes,
        spec.row_offsets,
        spec.row_counts,
    ):
        chunk = flat[off:off + cnt].reshape(cnt * LANE)
        leaves.append(chunk[:n].reshape(shape).astype(dt))
    return jax.tree.unflatten(spec.treedef, leaves)
