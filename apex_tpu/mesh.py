"""Global device-mesh management.

The reference (apex/transformer/parallel_state.py:~100-600) tracks NCCL process
groups for tensor/pipeline/data parallelism, enumerated over
``world_size = dp * pp * tp`` with tp varying fastest. On TPU the same role is
played by ONE ``jax.sharding.Mesh`` with named axes — collectives are emitted by
XLA against axis names rather than process-group handles.

Axis convention (used across the whole package):

    ``data``   — data parallel (reference: _DATA_PARALLEL_GROUP)
    ``stage``  — pipeline parallel (reference: _PIPELINE_MODEL_PARALLEL_GROUP)
    ``model``  — tensor parallel (reference: _TENSOR_MODEL_PARALLEL_GROUP)
    ``context``— sequence/context parallel for ring attention (beyond reference;
                 the reference has no context parallelism — SURVEY.md §2.4)

Device order is TPU-first, not a copy of the reference's rank enumeration
(which is tp fastest, dp middle, pp slowest): here ``model`` varies fastest so
TP peers sit on adjacent devices (latency-critical per-layer collectives ride
shortest ICI hops), ``stage`` next so pipeline neighbors are also close
(ppermute activations), and ``data`` slowest — DP gradient all-reduce is
bandwidth-heavy but latency-tolerant, so it can take the long hops/DCN.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
STAGE_AXIS = "stage"
MODEL_AXIS = "model"
CONTEXT_AXIS = "context"

_GLOBAL_MESH: Optional[Mesh] = None


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes of each parallelism axis. -1 for ``data`` means "fill"."""

    data: int = -1
    stage: int = 1
    model: int = 1
    context: int = 1


def _slice_key(d) -> int:
    """Connectivity-domain id of a device: its TPU slice when exposed
    (multi-slice pods — ICI only *within* a slice), else the host process
    (multi-host CPU/DCN simulation). ``slice_index`` is only trusted on
    TPU devices — distributed CPU backends expose it as 0 on every device,
    which would collapse all processes into one 'slice'."""
    if getattr(d, "platform", "") == "tpu":
        s = getattr(d, "slice_index", None)
        if s is not None:
            return int(s)
    return int(getattr(d, "process_index", 0))


def build_mesh(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    context_parallel_size: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_data_parallel_size: int = 1,
) -> Mesh:
    """Build the global 4-axis mesh (data, stage, context, model).

    Mirrors ``initialize_model_parallel(tp, pp)`` from the reference
    (apex/transformer/parallel_state.py) but returns a Mesh instead of
    mutating process-group globals.

    ``dcn_data_parallel_size`` > 1 requests topology-aware multi-slice
    placement (the ``mesh_utils.create_hybrid_device_mesh`` analog, SURVEY
    §2.4 closing: "ICI for intra-slice and DCN for multi-slice axes"):
    devices are grouped by slice (``Device.slice_index``, falling back to
    ``process_index`` off-TPU), each slice must hold a full tp*pp*cp block,
    and the ``data`` axis is ordered slice-OUTER — consecutive data ranks
    stay inside one slice (gradient reduce-scatter phases ride ICI) and only
    the outermost data strides cross the DCN. ``model``/``stage``/
    ``context`` never cross a slice boundary.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    tp = tensor_model_parallel_size
    pp = pipeline_model_parallel_size
    cp = context_parallel_size
    denom = tp * pp * cp
    if n % denom != 0:
        raise RuntimeError(
            f"device count {n} is not divisible by tp({tp}) * pp({pp}) * cp({cp})"
        )
    dp = n // denom
    dcn = dcn_data_parallel_size
    if dcn > 1:
        groups: dict = {}
        for d in devices:
            groups.setdefault(_slice_key(d), []).append(d)
        if len(groups) != dcn:
            raise RuntimeError(
                f"dcn_data_parallel_size={dcn} but the device list spans "
                f"{len(groups)} slices/processes ({sorted(groups)})")
        sizes = {k: len(v) for k, v in groups.items()}
        if len(set(sizes.values())) != 1:
            raise RuntimeError(f"uneven devices per slice: {sizes}")
        per_slice = n // dcn
        if per_slice % denom != 0:
            raise RuntimeError(
                f"per-slice device count {per_slice} is not divisible by "
                f"tp({tp}) * pp({pp}) * cp({cp}) — model/stage/context axes "
                "must not cross a slice (ICI) boundary")
        # slice-major order: reshaping to (dcn, ici_dp, pp, cp, tp) keeps
        # every non-data axis (and the inner data blocks) within one slice
        ordered = [d for k in sorted(groups) for d in groups[k]]
        dev_array = np.asarray(ordered).reshape(
            dcn, per_slice // denom, pp, cp, tp).reshape(dp, pp, cp, tp)
    else:
        dev_array = np.asarray(devices).reshape(dp, pp, cp, tp)
    return Mesh(dev_array, axis_names=(DATA_AXIS, STAGE_AXIS, CONTEXT_AXIS, MODEL_AXIS))


def set_global_mesh(mesh: Optional[Mesh]) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Mesh:
    if _GLOBAL_MESH is None:
        raise RuntimeError(
            "global mesh is not initialized; call "
            "apex_tpu.transformer.parallel_state.initialize_model_parallel() "
            "or apex_tpu.mesh.set_global_mesh() first"
        )
    return _GLOBAL_MESH


def maybe_global_mesh() -> Optional[Mesh]:
    return _GLOBAL_MESH


@contextlib.contextmanager
def global_mesh(mesh: Mesh):
    """Context manager installing ``mesh`` as the global mesh."""
    prev = _GLOBAL_MESH
    set_global_mesh(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        set_global_mesh(prev)


def sharding(*spec, mesh: Optional[Mesh] = None) -> NamedSharding:
    """NamedSharding on the global (or given) mesh for a PartitionSpec."""
    m = mesh if mesh is not None else get_global_mesh()
    return NamedSharding(m, PartitionSpec(*spec))
