"""apex.mlp equivalent — fused multi-layer perceptron.

Reference: apex/mlp/mlp.py:~15 (``MLP`` module) + csrc/mlp.cpp /
csrc/mlp_cuda.cu (~800 LoC of chained cublas GEMMs with fused
bias+ReLU/sigmoid epilogues and workspace management). On TPU the entire
chain — GEMM + bias + activation per layer — is fused by XLA into MXU ops
with epilogue fusion, so the module is a plain jnp chain: the CUDA file's
whole purpose (avoiding per-op kernel launches and intermediate HBM trips)
is what the XLA compiler does by default here. API parity is the deliverable.

Weights are torch-layout (out_features, in_features) like the reference.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.amp.policy import resolve_compute_dtype


class MLP(nn.Module):
    """Drop-in for apex.mlp.MLP.

    Args (reference ctor): ``mlp_sizes`` — list of layer widths including the
    input width; ``bias``; ``relu``/``activation`` — 'none' | 'relu' |
    'sigmoid' (applied to every layer except the last... the reference applies
    activation to ALL layers including the last — matched here).
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.activation not in ("none", "relu", "sigmoid"):
            raise TypeError(f"activation '{self.activation}' not supported")
        sizes = list(self.mlp_sizes)
        assert x.shape[-1] == sizes[0], (
            f"input width {x.shape[-1]} != mlp_sizes[0] {sizes[0]}")
        dt = resolve_compute_dtype(x.dtype)  # amp O1 seam: GEMMs in half
        for i in range(len(sizes) - 1):
            w = self.param(f"weight_{i}",
                           nn.initializers.variance_scaling(
                               1.0 / 3.0, "fan_in", "uniform"),
                           (sizes[i + 1], sizes[i]), self.param_dtype)
            x = x.astype(dt) @ w.astype(dt).T
            if self.bias:
                b = self.param(f"bias_{i}", nn.initializers.zeros,
                               (sizes[i + 1],), self.param_dtype)
                x = x + b.astype(dt)
            if self.activation == "relu":
                x = nn.relu(x)
            elif self.activation == "sigmoid":
                x = nn.sigmoid(x)
        return x

    forward = __call__
