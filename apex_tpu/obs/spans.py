"""Per-request lifecycle spans: the "why was request 17 slow?" layer.

A :class:`SpanTracer` records named intervals (spans) and instants
(events — zero-duration spans) per request id, entirely host-side: no
device syncs, a few dict operations per request phase. The serving
scheduler drives the canonical lifecycle

    enqueue -> admit -> prefill -> first_token -> decode -> retire

from which :meth:`SpanTracer.lifecycle` derives the operator metrics:

- ``queue_wait_ms`` — enqueue to admit (slot + page availability),
- ``ttft_ms``       — enqueue to first token (queue wait + prefill),
- ``tpot_ms``       — decode span / (new_tokens - 1): steady-state
  time-per-output-token,
- ``prefill`` attrs — ``cached_tokens`` vs ``computed_tokens`` (the
  prefix-cache split).

Intervals additionally enter/exit ``jax.profiler.TraceAnnotation`` so an
xprof capture of a serving run shows the same request phases as labeled
host spans next to the device timeline — one trace model for both the
postmortem dump and the profiler UI.

Timestamps come from an injectable monotonic ``clock`` (tests pass a
fake); they are durations-on-one-host, not wall time — the
:class:`~apex_tpu.obs.events.EventLog` records wall-clock for
correlation with external logs.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax

__all__ = ["PHASES", "Span", "SpanTracer"]

#: canonical request lifecycle, in order. The bracketed middle may repeat:
#: the serving front-end preempts a running request (``preempt`` instant,
#: then a ``preempted`` interval open until its ``resume`` instant, whose
#: re-admission opens a fresh ``prefill``/``decode`` pair), so a request
#: can carry several decode segments — :meth:`SpanTracer.lifecycle` sums
#: them and reports the total time-in-preempted as ``preempted_ms``.
PHASES = ("enqueue", "admit", "prefill", "first_token", "decode",
          "preempt", "preempted", "resume", "retire")


@dataclasses.dataclass
class Span:
    """One named interval (or instant, when ``t_end == t_start``) in a
    request's lifecycle. ``attrs`` carries phase payloads (token counts,
    slot ids); :meth:`duration_ms` is None while the span is open."""

    request_id: object
    name: str
    t_start: float
    t_end: Optional[float] = None
    attrs: Dict[str, object] = dataclasses.field(default_factory=dict)

    @property
    def duration_ms(self) -> Optional[float]:
        if self.t_end is None:
            return None
        return (self.t_end - self.t_start) * 1e3

    def to_dict(self) -> dict:
        return {"request_id": self.request_id, "name": self.name,
                "t_start": self.t_start, "t_end": self.t_end,
                "duration_ms": self.duration_ms, "attrs": dict(self.attrs)}


class SpanTracer:
    """Collects spans per request id and assembles lifecycle summaries.

    Thread-safe; begin/end of one span must pair on one thread (the
    profiler annotation is thread-scoped). The scheduler creates a fresh
    tracer per ``run()`` so lifecycles describe exactly one run.
    """

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.RLock()
        self._spans: Dict[object, List[Span]] = {}
        self._open: Dict[Tuple[object, str], Tuple[Span, object]] = {}

    # -- recording ------------------------------------------------------

    def event(self, request_id, name: str, **attrs) -> Span:
        """Record an instant (zero-duration span)."""
        t = self._clock()
        span = Span(request_id, name, t, t, attrs)
        with self._lock:
            self._spans.setdefault(request_id, []).append(span)
        return span

    def begin(self, request_id, name: str, annotate: bool = False,
              **attrs) -> Span:
        """Open a span. ``annotate=True`` additionally enters a
        ``jax.profiler.TraceAnnotation`` — only safe when the matching
        ``end`` nests LIFO on this thread (use :meth:`span` for that);
        free-form overlapping spans (concurrent requests' decode
        intervals) must leave it False: TraceMe demands properly nested
        begin/end pairs per thread."""
        span = Span(request_id, name, self._clock(), None, attrs)
        with self._lock:
            key = (request_id, name)
            if key in self._open:
                # check BEFORE entering the annotation: raising with an
                # entered TraceMe would leave it open on this thread and
                # mis-nest every later annotation
                raise RuntimeError(f"span {name!r} already open for "
                                   f"request {request_id!r}")
            ann = None
            if annotate:
                ann = jax.profiler.TraceAnnotation(
                    f"req{request_id}:{name}")
                ann.__enter__()
            self._open[key] = (span, ann)
            self._spans.setdefault(request_id, []).append(span)
        return span

    def end(self, request_id, name: str, **attrs) -> Span:
        with self._lock:
            try:
                span, ann = self._open.pop((request_id, name))
            except KeyError:
                raise RuntimeError(f"end({name!r}) for request "
                                   f"{request_id!r} without begin()")
            # mutate under the lock: a concurrent reader (lifecycles /
            # to_dicts from an export thread) must never see t_end set
            # while the closing attrs are still missing
            span.t_end = self._clock()
            span.attrs.update(attrs)
        if ann is not None:
            ann.__exit__(None, None, None)
        return span

    @contextlib.contextmanager
    def span(self, request_id, name: str, **attrs):
        """Properly-nested interval: rides a profiler annotation, so it
        shows up as a labeled host span in xprof captures."""
        s = self.begin(request_id, name, annotate=True, **attrs)
        try:
            yield s
        finally:
            self.end(request_id, name)

    # -- reading --------------------------------------------------------

    def requests(self) -> List[object]:
        with self._lock:
            return list(self._spans)

    def spans(self, request_id) -> List[Span]:
        with self._lock:
            return list(self._spans.get(request_id, ()))

    def lifecycle(self, request_id) -> Dict[str, object]:
        """Derived per-request metrics from the canonical phases. Keys
        appear only when their source spans exist (a partial lifecycle —
        a still-running request — yields what is known so far).

        Preemption-aware: a preempted-and-resumed request carries one
        ``prefill``/``decode`` span pair per segment, so segment spans
        are SUMMED (``decode_ms``, ``prefill_ms``, ``new_tokens``,
        ``cached_tokens``/``computed_tokens`` are totals across
        segments), the boundary instants anchor on the FIRST occurrence
        (``queue_wait_ms``/``ttft_ms`` measure the original arrival, not
        a resume), and closed ``preempted`` intervals report their total
        as ``preempted_ms`` with the count as ``preemptions``. ``tpot``
        is decode time per generated token — preempted/queued time
        excluded by construction."""
        by_name: Dict[str, List[Span]] = {}
        for s in self.spans(request_id):
            by_name.setdefault(s.name, []).append(s)

        def first(name):
            spans = by_name.get(name)
            return spans[0] if spans else None

        out: Dict[str, object] = {"request_id": request_id}
        enq = first("enqueue")
        admit = first("admit")
        ftok = first("first_token")
        if enq is not None and admit is not None:
            out["queue_wait_ms"] = (admit.t_start - enq.t_start) * 1e3
        if enq is not None and ftok is not None:
            out["ttft_ms"] = (ftok.t_start - enq.t_start) * 1e3
        prefills = [s for s in by_name.get("prefill", ())
                    if s.duration_ms is not None]
        if prefills:
            out["prefill_ms"] = sum(s.duration_ms for s in prefills)
            for k in ("cached_tokens", "computed_tokens"):
                vals = [s.attrs[k] for s in prefills if k in s.attrs]
                if vals:
                    out[k] = sum(vals)
        decodes = [s for s in by_name.get("decode", ())
                   if s.duration_ms is not None]
        if decodes:
            out["decode_ms"] = sum(s.duration_ms for s in decodes)
            n_new = [s.attrs["new_tokens"] for s in decodes
                     if "new_tokens" in s.attrs]
            if n_new:
                total_new = int(sum(n_new))
                out["new_tokens"] = total_new
                # token 0 samples at admit; decode produces the rest
                out["tpot_ms"] = out["decode_ms"] / max(total_new - 1, 1)
        preempted = [s for s in by_name.get("preempted", ())
                     if s.duration_ms is not None]
        if by_name.get("preempted"):
            out["preemptions"] = len(by_name["preempted"])
            out["preempted_ms"] = sum(s.duration_ms for s in preempted)
        retires = by_name.get("retire")
        if enq is not None and retires:
            out["total_ms"] = (retires[-1].t_start - enq.t_start) * 1e3
        return out

    def lifecycles(self) -> Dict[object, Dict[str, object]]:
        return {rid: self.lifecycle(rid) for rid in self.requests()}

    def to_dicts(self) -> List[dict]:
        """Every span, flattened — the postmortem-dump payload."""
        with self._lock:
            return [s.to_dict() for spans in self._spans.values()
                    for s in spans]
