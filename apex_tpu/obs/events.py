"""Bounded ring-buffer event log with a JSONL postmortem dump.

Structured counterpart to a log file: the serving engine (and anything
else) ``emit()``\\ s small dict events — admissions, retirements,
evictions, defrags, deferrals — into a fixed-capacity ring. Memory is
bounded no matter how long the process serves; when something goes wrong
the operator calls :meth:`EventLog.dump` and reads the last N events as
JSON lines, newest state included, oldest silently dropped (the
``dropped`` counter says how many).

Events carry a monotonically increasing ``seq`` (gap-free — a reader can
detect drops between two dumps) and a wall-clock ``t`` (``time.time``)
for correlation with external logs; the injectable ``clock`` makes tests
deterministic.

Incremental reads (the fleet-federation scrape, ``/events?since_seq=``
on the serving HTTP port): :meth:`EventLog.since` returns only the
events past a caller-held cursor plus the count the ring dropped past
it — a scraper re-ships nothing and still *knows* when it lost events
to a lap. :meth:`EventLog.dump` takes the same ``since_seq`` cursor.
"""

from __future__ import annotations

import collections
import io
import json
import threading
import time
from typing import List, Optional, Tuple

__all__ = ["EventLog"]


class EventLog:
    """Thread-safe fixed-capacity event ring."""

    def __init__(self, capacity: int = 1024, clock=time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def emit(self, kind: str, **fields) -> dict:
        """Append one event; returns a COPY of the stored record (same
        contract as :meth:`tail` — mutating it cannot corrupt the
        ring)."""
        with self._lock:
            rec = {"seq": self._seq, "t": self._clock(), "kind": kind,
                   **fields}
            self._seq += 1
            self._buf.append(rec)
        return dict(rec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def total(self) -> int:
        """Events ever emitted (retained + dropped)."""
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        """Events pushed out of the ring by newer ones."""
        with self._lock:
            return self._seq - len(self._buf)

    def tail(self, n: Optional[int] = None) -> List[dict]:
        """The last ``n`` retained events (all of them when ``n`` is
        None), oldest first. Returns copies — mutating them does not
        corrupt the ring."""
        with self._lock:
            events = list(self._buf)
        if n is not None:
            events = events[-n:]
        return [dict(e) for e in events]

    def since(self, seq: int) -> Tuple[List[dict], int]:
        """Incremental read past a cursor: ``(events, dropped)`` where
        ``events`` are the retained events with ``seq > seq`` (oldest
        first, copies) and ``dropped`` counts the events emitted after
        the cursor that the ring already pushed out — a non-zero value
        means the scraper's view has a gap it cannot recover. A cursor
        of ``-1`` reads from the beginning."""
        with self._lock:
            events = [dict(e) for e in self._buf if e["seq"] > seq]
            emitted_after = max(self._seq - (seq + 1), 0)
            dropped = emitted_after - len(events)
        return events, dropped

    def dump(self, path: Optional[str] = None, *,
             since_seq: Optional[int] = None) -> str:
        """Serialize the retained events as JSONL (one event per line,
        oldest first), preceded by a header line with total/dropped
        counts. Writes to ``path`` when given; always returns the text —
        the postmortem artifact docs/observability.md walks through.

        With ``since_seq``, only events past that cursor are emitted and
        the header grows ``since_seq`` plus a cursor-relative ``dropped``
        (events the ring lapped past the cursor — the gap-detection
        contract federation scrapes rely on). The default header shape
        (no cursor) is pinned byte-for-byte by the wraparound test."""
        if since_seq is not None:
            events, dropped = self.since(since_seq)
            with self._lock:
                header = {"kind": "event_log_header",
                          "capacity": self.capacity, "total": self._seq,
                          "dropped": dropped, "since_seq": since_seq}
        else:
            with self._lock:
                events = [dict(e) for e in self._buf]
                header = {"kind": "event_log_header",
                          "capacity": self.capacity, "total": self._seq,
                          "dropped": self._seq - len(events)}
        out = io.StringIO()
        out.write(json.dumps(header) + "\n")
        for e in events:
            out.write(json.dumps(e) + "\n")
        text = out.getvalue()
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text
