"""Bounded ring-buffer event log with a JSONL postmortem dump.

Structured counterpart to a log file: the serving engine (and anything
else) ``emit()``\\ s small dict events — admissions, retirements,
evictions, defrags, deferrals — into a fixed-capacity ring. Memory is
bounded no matter how long the process serves; when something goes wrong
the operator calls :meth:`EventLog.dump` and reads the last N events as
JSON lines, newest state included, oldest silently dropped (the
``dropped`` counter says how many).

Events carry a monotonically increasing ``seq`` (gap-free — a reader can
detect drops between two dumps) and a wall-clock ``t`` (``time.time``)
for correlation with external logs; the injectable ``clock`` makes tests
deterministic.
"""

from __future__ import annotations

import collections
import io
import json
import threading
import time
from typing import List, Optional

__all__ = ["EventLog"]


class EventLog:
    """Thread-safe fixed-capacity event ring."""

    def __init__(self, capacity: int = 1024, clock=time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def emit(self, kind: str, **fields) -> dict:
        """Append one event; returns a COPY of the stored record (same
        contract as :meth:`tail` — mutating it cannot corrupt the
        ring)."""
        with self._lock:
            rec = {"seq": self._seq, "t": self._clock(), "kind": kind,
                   **fields}
            self._seq += 1
            self._buf.append(rec)
        return dict(rec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def total(self) -> int:
        """Events ever emitted (retained + dropped)."""
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        """Events pushed out of the ring by newer ones."""
        with self._lock:
            return self._seq - len(self._buf)

    def tail(self, n: Optional[int] = None) -> List[dict]:
        """The last ``n`` retained events (all of them when ``n`` is
        None), oldest first. Returns copies — mutating them does not
        corrupt the ring."""
        with self._lock:
            events = list(self._buf)
        if n is not None:
            events = events[-n:]
        return [dict(e) for e in events]

    def dump(self, path: Optional[str] = None) -> str:
        """Serialize the retained events as JSONL (one event per line,
        oldest first), preceded by a header line with total/dropped
        counts. Writes to ``path`` when given; always returns the text —
        the postmortem artifact docs/observability.md walks through."""
        with self._lock:
            events = list(self._buf)
            header = {"kind": "event_log_header", "capacity": self.capacity,
                      "total": self._seq,
                      "dropped": self._seq - len(events)}
        out = io.StringIO()
        out.write(json.dumps(header) + "\n")
        for e in events:
            out.write(json.dumps(e) + "\n")
        text = out.getvalue()
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text
