"""apex_tpu.obs — serving/training observability (docs/observability.md).

Three host-side layers over the ``apex_tpu.utils.metrics`` instrument
registry, built for operating the continuous-batching serving engine the
way production paged-KV systems are operated (Orca, Yu et al. 2022;
vLLM, Kwon et al. 2023) — per-request lifecycle traces in the spirit of
Dapper (Sigelman et al. 2010):

- ``spans``  — :class:`SpanTracer`: per-request lifecycle spans
  (enqueue → admit → prefill → first_token → decode → retire) with
  derived queue-wait / TTFT / TPOT, nested under ``jax.profiler`` trace
  annotations so they also land in xprof captures.
- ``events`` — :class:`EventLog`: bounded ring-buffer event log with a
  JSONL postmortem ``dump()``.
- ``export`` — Prometheus text exposition + JSON snapshots of the
  metric registry, file-based or via a stdlib HTTP endpoint.
"""

from apex_tpu.obs.events import EventLog
from apex_tpu.obs.export import (json_snapshot, prometheus_text, serve,
                                 write_snapshot)
from apex_tpu.obs.spans import PHASES, Span, SpanTracer

__all__ = ["EventLog", "PHASES", "Span", "SpanTracer", "json_snapshot",
           "prometheus_text", "serve", "write_snapshot"]
