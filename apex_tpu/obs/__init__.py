"""apex_tpu.obs — serving/training observability (docs/observability.md).

Three host-side layers over the ``apex_tpu.utils.metrics`` instrument
registry, built for operating the continuous-batching serving engine the
way production paged-KV systems are operated (Orca, Yu et al. 2022;
vLLM, Kwon et al. 2023) — per-request lifecycle traces in the spirit of
Dapper (Sigelman et al. 2010):

- ``spans``  — :class:`SpanTracer`: per-request lifecycle spans
  (enqueue → admit → prefill → first_token → decode → retire) with
  derived queue-wait / TTFT / TPOT, nested under ``jax.profiler`` trace
  annotations so they also land in xprof captures.
- ``events`` — :class:`EventLog`: bounded ring-buffer event log with a
  JSONL postmortem ``dump()``.
- ``export`` — Prometheus text exposition + JSON snapshots of the
  metric registry, file-based or via a stdlib HTTP endpoint
  (``/metrics``, ``/healthz``, ``/costs``).

Performance attribution (PR 8) adds three more, CLI-first:

- ``costs``  — deterministic jaxpr roofline cost model over the lint
  harness's programs (``python -m apex_tpu.obs.costs``).
- ``compile_watch`` — :class:`CompileWatcher`: jit recompile /
  trace-cache-miss counters keyed by function name, with the serving
  frontend's recompile-storm warning built on top.
- ``ledger`` — the persistent perf ledger + regression gate
  (``python -m apex_tpu.obs.ledger --check``, ``PERF_LEDGER.jsonl``).

The fleet plane (``fleet``, docs/observability.md "Fleet plane") spans
processes: process-independent trace ids stitched across replica
failovers, router-side metrics federation (:class:`FleetCollector`),
multi-window SLO burn-rate alerting (:class:`BurnRateAlerter`), and
the schema-pinned postmortem flight recorder
(:func:`build_flight` / :func:`validate_flight`).
"""

from apex_tpu.obs.compile_watch import CompileWatcher, watcher
from apex_tpu.obs.events import EventLog
from apex_tpu.obs.export import (describe, health_doc, json_snapshot,
                                 latest_costs, prometheus_text,
                                 publish_costs, serve, write_snapshot)
from apex_tpu.obs.fleet import (FLIGHT_SCHEMA, BurnRateAlerter,
                                FleetCollector, build_flight,
                                mint_trace_id, parse_traceparent,
                                row_from_snapshot, stitch_traces,
                                traceparent, validate_flight)
from apex_tpu.obs.spans import PHASES, Span, SpanTracer

__all__ = ["BurnRateAlerter", "CompileWatcher", "EventLog",
           "FLIGHT_SCHEMA", "FleetCollector", "PHASES", "Span",
           "SpanTracer", "build_flight", "describe", "health_doc",
           "json_snapshot", "latest_costs", "mint_trace_id",
           "parse_traceparent", "prometheus_text", "publish_costs",
           "row_from_snapshot", "serve", "stitch_traces",
           "traceparent", "validate_flight", "watcher",
           "write_snapshot"]
