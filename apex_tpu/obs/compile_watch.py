"""Recompile / trace-cache watcher over ``jax.monitoring``.

A recompile inside the serving pump is a latency cliff: one shape drift
in the decode chunk and every request on the box stalls behind an XLA
compile. The IR lint tier bounds compile-key cardinality *statically*
(``ir-compile-key-cardinality``); this module watches the *dynamic*
counterpart — what actually compiled at runtime — and feeds it into the
PR 4 instrument registry:

- ``jit.compiles`` (Counter, ``fn`` label) + ``jit.compile_ms``
  (Histogram, ``fn`` label) — one increment/observation per XLA backend
  compile, keyed by the jitted function's name.
- ``jit.trace_cache_misses`` (Counter, ``fn`` label) — one increment per
  jaxpr re-trace (every trace-cache miss re-stages the program; most
  then also compile).

Mechanism: ``jax.monitoring.register_event_duration_secs_listener``
subscribes to jax's own ``/jax/core/compile/...`` duration events. Those
events carry no function name, so the watcher also wraps
``jax._src.dispatch.log_elapsed_time`` (the context manager every
compile/trace timer runs under) purely to capture ``fun_name`` into a
thread-local — the listener reads it at record time. When this jax
version has no ``jax.monitoring`` (or the internal timer moved), the
wrapper alone times the lowering and records directly — same
instruments, degraded to wrapper-measured durations; if neither hook
exists the watcher is inert (counts stay 0) rather than broken.

One process-wide watcher (:func:`watcher`) is installed lazily on first
use — the serving frontend snapshots its counters per run and raises a
``compile_storm`` warning event when one function name recompiles more
than ``DEFAULT_STORM_THRESHOLD`` times within a single frontend's
lifetime (docs/observability.md).

Attribution caveat: compiles are PROCESS-wide facts (jax has one trace
cache), so a frontend's ``stats()`` deltas and storm window see every
compile in the process during its lifetime — including another
concurrently live engine's. With the usual one-serving-engine-per-
process deployment the attribution is exact; with several, treat
``jit.compiles`` as a process number and ``compile_storm`` as a
process-level warning that happened to be noticed by this frontend.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional, Tuple

from apex_tpu.utils import metrics

__all__ = ["CompileWatcher", "watcher", "DEFAULT_STORM_THRESHOLD"]

#: compiles of ONE function name within one frontend run that count as a
#: recompile storm (bucketed admission legitimately compiles once per
#: prompt bucket — the threshold sits above any sane bucket count)
DEFAULT_STORM_THRESHOLD = 8

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_UNKNOWN = "<unknown>"


class CompileWatcher:
    """Subscribes to jax compile/trace events; see the module docstring.

    Thread-safe: compiles happen on whichever thread first calls a
    jitted function (the pump, a submitter, an exporter warming up), so
    every mutation of the per-name tables takes ``self._lock``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._compiles: Dict[str, int] = {}
        self._trace_misses: Dict[str, int] = {}
        self._installed = False
        self._listener_active = False
        self._orig_log_elapsed = None
        self._names = threading.local()

    # -- name capture (thread-local stack) -----------------------------------

    def _current_name(self) -> str:
        stack = getattr(self._names, "stack", None)
        return stack[-1] if stack else _UNKNOWN

    @contextlib.contextmanager
    def _wrapped_log_elapsed(self, fmt, fun_name, event=None, **kw):
        stack = getattr(self._names, "stack", None)
        if stack is None:
            stack = self._names.stack = []
        stack.append(str(fun_name))
        t0 = time.perf_counter()
        try:
            with self._orig_log_elapsed(fmt, fun_name, event=event, **kw):
                yield
        finally:
            # fallback mode: no monitoring listener delivers durations,
            # so the wrapper itself times the lowering window
            if not self._listener_active and event is not None:
                self._record(event, time.perf_counter() - t0)
            stack.pop()

    # -- recording -----------------------------------------------------------

    # the listener runs synchronously inside jax's compile path on
    # arbitrary threads; it only updates host-side counters
    # tpu-lint: host-boundary -- monitoring callback, never traced
    def _on_duration(self, event, duration, **kwargs) -> None:
        self._record(event, duration)

    def _record(self, event: str, duration_s: float) -> None:
        name = self._current_name()
        if event == _COMPILE_EVENT:
            with self._lock:
                self._compiles[name] = self._compiles.get(name, 0) + 1
            metrics.counter("jit.compiles", labels={"fn": name}).inc()
            metrics.histogram("jit.compile_ms", labels={"fn": name}) \
                .observe(duration_s * 1e3)
        elif event == _TRACE_EVENT:
            with self._lock:
                self._trace_misses[name] = \
                    self._trace_misses.get(name, 0) + 1
            metrics.counter("jit.trace_cache_misses",
                            labels={"fn": name}).inc()

    # -- install / uninstall -------------------------------------------------

    def install(self) -> "CompileWatcher":
        """Idempotently hook jax. Safe to call from any thread."""
        with self._lock:
            if self._installed:
                return self
            self._installed = True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                self._on_duration)
            self._listener_active = True
        except Exception:       # noqa: BLE001 — no monitoring: fallback
            self._listener_active = False
        try:
            from jax._src import dispatch as _dispatch
            self._orig_log_elapsed = _dispatch.log_elapsed_time
            _dispatch.log_elapsed_time = self._wrapped_log_elapsed
        except Exception:       # noqa: BLE001 — names degrade to unknown
            self._orig_log_elapsed = None
        return self

    def uninstall(self) -> None:
        """Remove the hooks (tests); counts/instruments are kept."""
        with self._lock:
            if not self._installed:
                return
            self._installed = False
        if self._listener_active:
            try:
                from jax._src import monitoring as _monitoring
                _monitoring._unregister_event_duration_listener_by_callback(
                    self._on_duration)
            except Exception:   # noqa: BLE001 — listener list unchanged
                pass
            self._listener_active = False
        if self._orig_log_elapsed is not None:
            from jax._src import dispatch as _dispatch
            _dispatch.log_elapsed_time = self._orig_log_elapsed
            self._orig_log_elapsed = None

    # -- reads ---------------------------------------------------------------

    def counts(self) -> Dict[str, int]:
        """Per-function-name backend-compile counts (a copy)."""
        with self._lock:
            return dict(self._compiles)

    def trace_misses(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._trace_misses)

    def totals(self) -> Tuple[int, int]:
        """(total compiles, total trace-cache misses)."""
        with self._lock:
            return (sum(self._compiles.values()),
                    sum(self._trace_misses.values()))

    def storms(self, since: Optional[Dict[str, int]] = None,
               threshold: int = DEFAULT_STORM_THRESHOLD
               ) -> Dict[str, int]:
        """Function names whose compile count grew by >= ``threshold``
        since the ``since`` snapshot (``counts()`` at window start;
        None = process start). Returns {name: compiles_in_window}."""
        base = since or {}
        out = {}
        with self._lock:
            for name, n in self._compiles.items():
                delta = n - base.get(name, 0)
                if delta >= threshold:
                    out[name] = delta
        return out


_PROCESS_WATCHER: Optional[CompileWatcher] = None
_PROCESS_LOCK = threading.Lock()


def watcher() -> CompileWatcher:
    """The process-wide watcher, installed on first call (the serving
    frontend's constructor uses this — one set of hooks per process no
    matter how many engines run)."""
    global _PROCESS_WATCHER
    with _PROCESS_LOCK:
        if _PROCESS_WATCHER is None:
            _PROCESS_WATCHER = CompileWatcher().install()
        return _PROCESS_WATCHER
