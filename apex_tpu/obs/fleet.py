"""Fleet observability plane: cross-replica traces, metrics federation,
SLO burn-rate alerts, and the postmortem flight recorder.

Everything PR 4/8 built — typed instruments, :class:`SpanTracer`
lifecycles, event rings, ``/metrics`` — is process-local. The replica
router (serving/router.py) supervises N replicas that may live in OTHER
processes behind HTTP (serving/http.py), and a request that fails over
between replicas leaves two disconnected span fragments. This module is
the layer that re-joins the fleet into one observable system:

- **Trace propagation** — every submit path mints a process-independent
  ``trace_id`` (:func:`mint_trace_id`, 32 lowercase hex — the W3C
  ``traceparent`` trace-id field), carried on ``Request.trace_id``, as a
  ``traceparent`` header on ``POST /v1/generate``, and as a ``trace_id``
  attr on each replica tracer's ``enqueue`` event.
  :func:`stitch_traces` merges per-replica span dumps into ONE lifecycle
  per trace: segment metrics are summed exactly like
  :meth:`~apex_tpu.obs.spans.SpanTracer.lifecycle` (TTFT anchors at the
  FIRST replica's first token), and the gap between one replica's last
  span and the next replica's first is synthesized as a ``failover``
  preempt/resume segment naming both replicas, counted into
  ``preempted_ms``.
- **Metrics federation** — :class:`FleetCollector` scrapes every replica
  on the router's supervision tick: local replicas read the process
  registry directly (filtered to the replica engine's labels); remote
  replicas go through the client's ``fleet_scrape()`` (one
  ``/metrics.json`` + one incremental ``/events?since_seq=`` GET). Rows
  are re-derived from the snapshot with the SAME bucket interpolation
  the in-process :meth:`~apex_tpu.utils.metrics.Histogram.quantile`
  uses (:func:`row_from_snapshot` — scrape fidelity is by
  construction), published as ``fleet.*{replica=}`` gauges with a
  per-replica ``fleet.scrape_age_s`` staleness gauge, and aggregated
  into the pinned ``fleet`` block (``report.FLEET_FIELDS``).
- **SLO burn-rate alerting** — :class:`BurnRateAlerter` evaluates the
  federated ``slo_burn`` series over a fast and a slow window
  (multi-window burn-rate alerting, Google SRE workbook ch. 5): it
  fires only when BOTH window means sit at/above the threshold (a
  transient spike cannot page) and resolves only once the fast window
  falls under ``threshold * hysteresis`` (no flapping at the
  boundary), emitting ``fleet.alert`` events — the signal ROADMAP
  item 2's autoscaler consumes.
- **Flight recorder** — :func:`build_flight` assembles the correlated
  postmortem bundle (every replica's event-ring tail, spans stitched by
  trace_id, instrument snapshot, pool gauges, the router's routing
  table and counters) under the pinned :data:`FLIGHT_SCHEMA`;
  :func:`validate_flight` rejects a malformed bundle. The router dumps
  one on any replica death or supervisor failure and on explicit
  ``flight_snapshot()``; the chaos CI round banks it as
  ``FLIGHT_<tag>.json``.

Concurrency (the conc-lint tier pins this): the collector's scrape I/O
runs with NO lock held — replica targets are snapshotted under the
router's lock (``router.fleet_targets()``), the scrape happens between
locks, and only the result merge takes the collector's own ``_lock``.
"""

from __future__ import annotations

import hashlib
import itertools
import math
import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from apex_tpu.obs.events import EventLog
from apex_tpu.utils import metrics

__all__ = ["BurnRateAlerter", "FLIGHT_SCHEMA", "FleetCollector",
           "build_flight", "mint_trace_id", "parse_traceparent",
           "row_from_snapshot", "stitch_traces", "traceparent",
           "validate_flight"]


# ---------------------------------------------------------------------------
# trace ids
# ---------------------------------------------------------------------------

#: per-process mint sequence (uniqueness within one process even when
#: the clock stalls)
_TRACE_SEQ = itertools.count()
#: per-process salt: two processes minting at the same nanosecond with
#: the same pid-recycled id still diverge
_TRACE_SALT = os.urandom(16)

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{32}$")


def mint_trace_id() -> str:
    """A process-independent trace id: 32 lowercase hex chars (the W3C
    ``traceparent`` trace-id field width). Collision-resistant across
    processes and restarts — pid, wall-clock nanoseconds, a per-process
    salt, and a mint sequence all feed the hash."""
    h = hashlib.sha256()
    h.update(_TRACE_SALT)
    h.update(os.getpid().to_bytes(8, "big"))
    h.update(time.time_ns().to_bytes(16, "big", signed=True))
    h.update(next(_TRACE_SEQ).to_bytes(8, "big"))
    return h.hexdigest()[:32]


def traceparent(trace_id: str, span_id: str = "0" * 16) -> str:
    """The ``traceparent`` header value carrying ``trace_id``
    (version 00, sampled)."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value) -> Optional[str]:
    """The trace id inside a ``traceparent`` header value (or a bare
    32-hex trace id); None when absent/malformed — a bad header must
    degrade to a fresh mint, never to a 400."""
    if not isinstance(value, str):
        return None
    value = value.strip().lower()
    if _TRACE_ID_RE.match(value):
        return value
    parts = value.split("-")
    if len(parts) >= 2 and _TRACE_ID_RE.match(parts[1]):
        return parts[1]
    return None


# ---------------------------------------------------------------------------
# trace stitching
# ---------------------------------------------------------------------------

def _closed_ms(span: dict) -> Optional[float]:
    return span.get("duration_ms")


def _stitch_one(trace_id: str, items: List[Tuple[str, dict]]) -> dict:
    """One stitched lifecycle from ``(replica, span_dict)`` pairs —
    the cross-replica mirror of :meth:`SpanTracer.lifecycle`: boundary
    instants anchor on the FIRST occurrence across the merged timeline,
    segment spans are summed, and inter-replica gaps become synthesized
    ``failover`` preempt/resume segments."""
    items = sorted(items, key=lambda rs: rs[1]["t_start"])
    by_name: Dict[str, List[dict]] = {}
    replicas: List[str] = []
    request_ids: List[object] = []
    for replica, s in items:
        by_name.setdefault(s["name"], []).append(s)
        if replica not in replicas:
            replicas.append(replica)
        if s["request_id"] not in request_ids:
            request_ids.append(s["request_id"])

    def first(name: str) -> Optional[dict]:
        spans = by_name.get(name)
        return spans[0] if spans else None

    out: Dict[str, object] = {"trace_id": trace_id, "replicas": replicas,
                              "request_ids": request_ids,
                              "spans": len(items)}
    enq, admit, ftok = first("enqueue"), first("admit"), first("first_token")
    if enq is not None and admit is not None:
        out["queue_wait_ms"] = (admit["t_start"] - enq["t_start"]) * 1e3
    if enq is not None and ftok is not None:
        out["ttft_ms"] = (ftok["t_start"] - enq["t_start"]) * 1e3
    prefills = [s for s in by_name.get("prefill", ())
                if _closed_ms(s) is not None]
    if prefills:
        out["prefill_ms"] = sum(_closed_ms(s) for s in prefills)
        for k in ("cached_tokens", "computed_tokens"):
            vals = [s["attrs"][k] for s in prefills if k in s["attrs"]]
            if vals:
                out[k] = sum(vals)
    decodes = [s for s in by_name.get("decode", ())
               if _closed_ms(s) is not None]
    if decodes:
        out["decode_ms"] = sum(_closed_ms(s) for s in decodes)
        n_new = [s["attrs"]["new_tokens"] for s in decodes
                 if "new_tokens" in s["attrs"]]
        if n_new:
            total_new = int(sum(n_new))
            out["new_tokens"] = total_new
            out["tpot_ms"] = out["decode_ms"] / max(total_new - 1, 1)
    preempted = [s for s in by_name.get("preempted", ())
                 if _closed_ms(s) is not None]
    preemptions = len(by_name.get("preempted", ()))
    preempted_ms = sum(_closed_ms(s) for s in preempted)
    retires = by_name.get("retire")
    if enq is not None and retires:
        out["total_ms"] = (retires[-1]["t_start"] - enq["t_start"]) * 1e3

    # per-replica segments (span-extent envelopes), ordered by start,
    # with the inter-replica handoff gaps synthesized as failover
    # preempt/resume segments naming BOTH replicas — a failed-over
    # request's time in limbo is preempted time, exactly like an
    # in-replica preemption
    segments = []
    for replica in replicas:
        mine = [s for r, s in items if r == replica]
        start = min(s["t_start"] for s in mine)
        end = max(s["t_end"] if s["t_end"] is not None else s["t_start"]
                  for s in mine)
        segments.append({"replica": replica, "t_start": start,
                         "t_end": end, "spans": len(mine)})
    segments.sort(key=lambda seg: seg["t_start"])
    failovers = []
    for prev, nxt in zip(segments, segments[1:]):
        gap_ms = max((nxt["t_start"] - prev["t_end"]) * 1e3, 0.0)
        failovers.append({"name": "failover",
                          "from_replica": prev["replica"],
                          "to_replica": nxt["replica"],
                          "preempt_t": prev["t_end"],
                          "resume_t": nxt["t_start"],
                          "gap_ms": gap_ms})
    preemptions += len(failovers)
    preempted_ms += sum(f["gap_ms"] for f in failovers)
    if preemptions:
        out["preemptions"] = preemptions
        out["preempted_ms"] = preempted_ms
    out["segments"] = segments
    out["failovers"] = failovers
    return out


def stitch_traces(dumps: Dict[str, List[dict]]) -> dict:
    """Merge per-replica span dumps into one lifecycle per trace.

    ``dumps`` maps a replica name to that replica tracer's
    ``to_dicts()`` output. Within each replica, any span carrying a
    ``trace_id`` attr (the ``enqueue`` event, by the propagation
    contract) binds its ``request_id`` to that trace; every span of a
    bound request joins the trace. Returns ``{"traces": {trace_id:
    stitched_lifecycle}, "orphans": [span, ...]}`` — orphans are spans
    whose request never carried a trace id (zero, when propagation
    works)."""
    trace_of: Dict[Tuple[str, object], str] = {}
    for replica, spans in dumps.items():
        for s in spans:
            tid = (s.get("attrs") or {}).get("trace_id")
            if tid:
                trace_of[(replica, s["request_id"])] = str(tid)
    grouped: Dict[str, List[Tuple[str, dict]]] = {}
    orphans: List[dict] = []
    for replica, spans in dumps.items():
        for s in spans:
            tid = trace_of.get((replica, s["request_id"]))
            if tid is None:
                orphan = dict(s)
                orphan["replica"] = replica
                orphans.append(orphan)
            else:
                grouped.setdefault(tid, []).append((replica, s))
    return {"traces": {tid: _stitch_one(tid, items)
                       for tid, items in grouped.items()},
            "orphans": orphans}


# ---------------------------------------------------------------------------
# snapshot -> fleet row (the scrape-fidelity core)
# ---------------------------------------------------------------------------

def _labels_match(entry_labels: Dict[str, str],
                  want: Dict[str, str]) -> bool:
    return all(entry_labels.get(k) == v for k, v in want.items())


def _entries(snap: dict, kind: str, name: str,
             want: Dict[str, str]) -> List[dict]:
    return [e for e in snap.get(kind, ())
            if e["name"] == name
            and _labels_match(e.get("labels", {}), want)]


def _merged_quantile(entries: List[dict], q: float) -> float:
    """Quantile over one or more snapshot histogram entries of one
    family, mirroring :meth:`Histogram.quantile` exactly (same linear
    interpolation inside the target bucket, clamped to the observed
    min/max) — the remote side of the scrape-fidelity contract: a p95
    recomputed from ``/metrics.json`` buckets equals the replica's
    in-process ``quantile(0.95)``."""
    entries = [e for e in entries if e.get("count")]
    if not entries:
        return 0.0
    total = sum(e["count"] for e in entries)
    vmin = min(e["min"] for e in entries)
    vmax = max(e["max"] for e in entries)
    les = [le for le, _ in entries[0]["buckets"]]
    counts = [0] * len(les)
    for e in entries:
        prev = 0
        for i, (_, cum) in enumerate(e["buckets"]):
            counts[i] += cum - prev
            prev = cum
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= target:
            lo = 0.0 if i == 0 else les[i - 1]
            hi = les[i]
            if hi is None or math.isinf(hi):
                hi = vmax
            frac = (target - cum) / c
            v = lo + frac * (hi - lo)
            return min(max(v, vmin), vmax)
        cum += c
    return vmax


def row_from_snapshot(snap: dict,
                      labels: Optional[Dict[str, str]] = None) -> dict:
    """One replica's fleet row from a registry snapshot (the in-process
    ``metrics.snapshot()`` or a scraped ``/metrics.json`` document).

    ``labels`` filters to one engine's label set (the LOCAL path — the
    process registry holds every in-process replica). A remote scrape
    passes no filter: the replica's process registry is merged across
    label sets, which is exact for the one-engine-per-serving-process
    deployment shape (docs/http.md Limits)."""
    want = {k: str(v) for k, v in (labels or {}).items()}
    row = {
        "ttft_ms_p95": _merged_quantile(
            _entries(snap, "histograms", "serving.ttft_ms", want), 0.95),
        "tpot_ms_p95": _merged_quantile(
            _entries(snap, "histograms", "serving.tpot_ms", want), 0.95),
        "queue_depth": sum(
            e["value"] for e in _entries(snap, "gauges",
                                         "serving.queue_depth", want)),
        "slo_burn": max(
            [e["value"] for e in _entries(snap, "gauges",
                                          "serving.slo_burn", want)]
            or [0.0]),
    }
    return row


def _scrape(fe, cursor: int) -> dict:
    """Scrape ONE replica (no locks held — pure I/O / registry reads).

    A frontend-shaped object exposing ``fleet_scrape`` (the HTTP
    replica client) is scraped over the wire; anything else is a local
    replica whose registry slice and engine event ring are read
    directly. Returns ``{"row", "events", "dropped", "cursor"}``."""
    remote = getattr(fe, "fleet_scrape", None)
    if remote is not None:
        doc = remote(cursor)
        snap = doc.get("metrics", {})
        edoc = doc.get("events", {})
        events = list(edoc.get("events", ()))
        dropped = int(edoc.get("dropped", 0))
        row = row_from_snapshot(snap)
    else:
        row = row_from_snapshot(metrics.snapshot(),
                                labels=fe.engine.obs_labels)
        row["queue_depth"] = fe.queue_depth
        events, dropped = fe.engine.events.since(cursor)
    new_cursor = cursor
    for e in events:
        new_cursor = max(new_cursor, int(e.get("seq", cursor)))
    if dropped:
        # a lapped cursor: everything up to the ring's oldest retained
        # event is gone — advance past the gap so it is counted once
        new_cursor = max(new_cursor, cursor + dropped)
    return {"row": row, "events": events, "dropped": dropped,
            "cursor": new_cursor}


# ---------------------------------------------------------------------------
# burn-rate alerting
# ---------------------------------------------------------------------------

class BurnRateAlerter:
    """Multi-window SLO burn-rate alerting over an injectable clock.

    ``observe(burn)`` appends one sample of the federated ``slo_burn``
    series (the SLO miss rate the serving frontend maintains —
    TTFT-deadline and TPOT misses per retirement). The alert FIRES when
    the mean burn over BOTH the fast and the slow window reaches
    ``threshold`` — the fast window gives detection latency, the slow
    window confirms it is not a transient (the multi-window burn-rate
    pattern, Google SRE workbook ch. 5). It RESOLVES only once the
    fast-window mean drops below ``threshold * hysteresis`` — the
    asymmetric band pins flap-free behavior at the boundary. Each
    transition emits one ``fleet.alert`` event (``state`` firing /
    resolved) into ``events``.

    Thread-safe; sample state is guarded by the alerter's own lock and
    the event emission happens outside it."""

    def __init__(self, *, threshold: float = 0.1,
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 600.0,
                 hysteresis: float = 0.5,
                 events: Optional[EventLog] = None,
                 clock=time.monotonic):
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        if not 0.0 <= hysteresis <= 1.0:
            raise ValueError(f"hysteresis must be in [0, 1], got "
                             f"{hysteresis}")
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        self.threshold = float(threshold)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.hysteresis = float(hysteresis)
        self.events = events if events is not None else EventLog(256)
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: deque = deque()   # (t, burn), slow-window bound
        self._firing = False
        self._fired = 0

    @property
    def firing(self) -> bool:
        with self._lock:
            return self._firing

    @property
    def fired(self) -> int:
        """Fire transitions so far (the ``fleet.alerts_fired`` field)."""
        with self._lock:
            return self._fired

    def windows(self) -> Tuple[float, float]:
        """Current ``(fast, slow)`` window means (0.0 when empty)."""
        with self._lock:
            return self._means_locked(self._clock())

    def _means_locked(self, now: float) -> Tuple[float, float]:
        while self._samples and \
                now - self._samples[0][0] > self.slow_window_s:
            self._samples.popleft()
        slow_vals = [b for _, b in self._samples]
        fast_vals = [b for t, b in self._samples
                     if now - t <= self.fast_window_s]
        fast = sum(fast_vals) / len(fast_vals) if fast_vals else 0.0
        slow = sum(slow_vals) / len(slow_vals) if slow_vals else 0.0
        return fast, slow

    def observe(self, burn: float) -> bool:
        """Feed one federated burn sample; returns the (possibly
        updated) firing state."""
        now = self._clock()
        with self._lock:
            self._samples.append((now, float(burn)))
            fast, slow = self._means_locked(now)
            was = self._firing
            if not was and fast >= self.threshold \
                    and slow >= self.threshold:
                self._firing = True
                self._fired += 1
            elif was and fast < self.threshold * self.hysteresis:
                self._firing = False
            firing = self._firing
            fired = self._fired
        if firing != was:
            self.events.emit("fleet.alert",
                             state="firing" if firing else "resolved",
                             fast_burn=round(fast, 6),
                             slow_burn=round(slow, 6),
                             threshold=self.threshold,
                             alerts_fired=fired)
        return firing


# ---------------------------------------------------------------------------
# the federation collector
# ---------------------------------------------------------------------------

class FleetCollector:
    """Router-side metrics/event federation over N replicas.

    ``tick()`` — called from the router's supervision tick — snapshots
    the replica set via ``router.fleet_targets()`` (the router takes
    its own lock for exactly that read), scrapes each live replica with
    NO lock held (:func:`_scrape` — registry reads locally, two HTTP
    GETs remotely), then merges the results under the collector's own
    ``_lock``: per-replica rows, incremental event tails (cursor-based,
    gap-counting), ``fleet.*{replica=}`` gauges with scrape-staleness,
    and one :class:`BurnRateAlerter` sample of the worst live replica's
    ``slo_burn``. Scrapes are throttled to ``interval_s`` of the
    injected clock (``force=True`` bypasses — the flight recorder's
    final scrape)."""

    def __init__(self, router, *, interval_s: float = 0.05,
                 event_tail: int = 512,
                 alerter: Optional[BurnRateAlerter] = None,
                 clock=time.monotonic):
        self._router = router
        self.interval_s = float(interval_s)
        self.event_tail = int(event_tail)
        self.alerter = alerter
        self._clock = clock
        self._lock = threading.Lock()
        self._order: List[str] = []
        self._rows: Dict[str, dict] = {}
        self._tails: Dict[str, deque] = {}
        self._cursors: Dict[str, int] = {}
        self._scraped_at: Dict[str, float] = {}
        self._storms: Dict[str, int] = {}
        self._dropped: Dict[str, int] = {}
        self._alive: Dict[str, bool] = {}

    # -- the federation tick -------------------------------------------------

    def tick(self, force: bool = False) -> bool:
        """One federation pass; returns False when throttled."""
        now = self._clock()
        with self._lock:
            last = max(self._scraped_at.values(), default=None)
            if not force and last is not None \
                    and now - last < self.interval_s:
                return False
            cursors = dict(self._cursors)
        targets = self._router.fleet_targets()
        results: Dict[str, Optional[dict]] = {}
        for name, alive, fe in targets:
            got = None
            if alive:
                try:
                    got = _scrape(fe, cursors.get(name, -1))
                except Exception:        # noqa: BLE001 — a scrape
                    got = None           # failure is staleness, never
                #                          a supervisor crash
            results[name] = got
        now = self._clock()
        burn = None
        with self._lock:
            self._order = [name for name, _, _ in targets]
            for name, alive, _ in targets:
                self._alive[name] = alive
                got = results[name]
                if got is None:
                    continue             # row + age keep their last
                #                          scrape (staleness grows)
                self._rows[name] = got["row"]
                self._cursors[name] = got["cursor"]
                self._scraped_at[name] = now
                self._dropped[name] = (self._dropped.get(name, 0)
                                       + got["dropped"])
                tail = self._tails.setdefault(
                    name, deque(maxlen=self.event_tail))
                for e in got["events"]:
                    tail.append(e)
                    if e.get("kind") == "compile_storm":
                        self._storms[name] = \
                            self._storms.get(name, 0) + 1
            for name in self._order:
                row = self._rows.get(name, {})
                lbl = {"replica": name}
                for field in ("ttft_ms_p95", "tpot_ms_p95",
                              "queue_depth", "slo_burn"):
                    metrics.gauge(f"fleet.{field}", labels=lbl).set(
                        row.get(field, 0.0))
                age = now - self._scraped_at[name] \
                    if name in self._scraped_at else 0.0
                metrics.gauge("fleet.scrape_age_s", labels=lbl).set(age)
            live_burns = [self._rows[n].get("slo_burn", 0.0)
                          for n in self._order
                          if self._alive.get(n) and n in self._rows]
            if live_burns:
                burn = max(live_burns)
        if burn is not None and self.alerter is not None:
            self.alerter.observe(burn)
        return True

    # -- read side -----------------------------------------------------------

    def scrape_ages(self) -> Dict[str, Optional[float]]:
        """Per-replica seconds since the last successful scrape (None
        before the first) — the ``/healthz`` staleness fields."""
        with self._lock:
            now = self._clock()
            return {name: (round(now - self._scraped_at[name], 6)
                           if name in self._scraped_at else None)
                    for name in self._order}

    def events_tail(self, name: Optional[str] = None):
        """The federated event tail for one replica (or all, keyed by
        replica name) — the flight recorder's per-replica ring copy,
        which survives the replica's death."""
        with self._lock:
            if name is not None:
                return [dict(e) for e in self._tails.get(name, ())]
            return {n: [dict(e) for e in t]
                    for n, t in self._tails.items()}

    def block(self) -> dict:
        """The pinned ``fleet`` block (``report.FLEET_FIELDS``):
        per-replica rows plus fleet aggregates — worst-replica p95s and
        burn (an SLO is only as good as the slowest replica), summed
        depth/storms, max scrape age, and the alerter's state."""
        with self._lock:
            now = self._clock()
            per = []
            for name in self._order:
                row = dict(self._rows.get(
                    name, {"ttft_ms_p95": 0.0, "tpot_ms_p95": 0.0,
                           "queue_depth": 0, "slo_burn": 0.0}))
                row["replica"] = name
                row["alive"] = bool(self._alive.get(name, False))
                row["scrape_age_s"] = \
                    round(now - self._scraped_at[name], 6) \
                    if name in self._scraped_at else 0.0
                row["compile_storms"] = self._storms.get(name, 0)
                row["events_dropped"] = self._dropped.get(name, 0)
                per.append(row)
        alerter = self.alerter
        out = {
            "replicas": len(per),
            "ttft_ms_p95": max((r["ttft_ms_p95"] for r in per),
                               default=0.0),
            "tpot_ms_p95": max((r["tpot_ms_p95"] for r in per),
                               default=0.0),
            "queue_depth": sum(r["queue_depth"] for r in per),
            "slo_burn": max((r["slo_burn"] for r in per), default=0.0),
            "compile_storms": sum(r["compile_storms"] for r in per),
            "scrape_age_s_max": max((r["scrape_age_s"] for r in per),
                                    default=0.0),
            "alerts_fired": alerter.fired if alerter is not None else 0,
            "alert_firing": (alerter.firing
                             if alerter is not None else False),
            "per_replica": per,
        }
        return out


# ---------------------------------------------------------------------------
# the flight recorder
# ---------------------------------------------------------------------------

#: pinned bundle schema — validate_flight() and the banked
#: FLIGHT_<tag>.json artifacts both key on it
FLIGHT_SCHEMA = "apex-tpu/flight/v1"

#: required top-level keys of a flight bundle
FLIGHT_KEYS = ("schema", "reason", "tag", "time_unix", "replicas",
               "router", "traces", "orphan_spans", "fleet",
               "pool_gauges", "metrics")

#: required keys of each per-replica entry
FLIGHT_REPLICA_KEYS = ("alive", "dead_reason", "events",
                       "events_dropped", "queue_depth", "routed",
                       "scrape_age_s")


def build_flight(*, reason: str, routing: List[dict],
                 counters: Dict[str, int], router_events: List[dict],
                 dumps: Dict[str, List[dict]],
                 collector: Optional[FleetCollector] = None,
                 replica_events: Optional[Dict[str, List[dict]]] = None,
                 tag: Optional[str] = None,
                 event_tail: int = 256) -> dict:
    """Assemble the correlated postmortem bundle.

    The router passes its lock-snapshotted ``routing`` table (one dict
    per replica with ``replica``/``alive``/``draining``/``routed``/
    ``dead_reason``/``queue_depth``), its counter deltas, its own event
    tail, and every replica tracer's span dump keyed by replica name.
    ``replica_events`` overrides a replica's event tail (local replicas
    read their engine ring directly — complete even for a replica the
    supervisor just declared dead); anything else falls back to the
    collector's federated tail copy, which survives a remote replica's
    process. Spans are stitched by trace id — the bundle's ``traces``
    block is one entry per request lifecycle across however many
    replicas served it."""
    stitched = stitch_traces(dumps)
    ages = collector.scrape_ages() if collector is not None else {}
    fed = collector.events_tail() if collector is not None else {}
    block = collector.block() if collector is not None else None
    fed_rows = {r["replica"]: r for r in block["per_replica"]} \
        if block is not None else {}
    replicas: Dict[str, dict] = {}
    for entry in routing:
        name = entry["replica"]
        events = (replica_events or {}).get(name)
        if events is None:
            events = fed.get(name, [])
        replicas[name] = {
            "alive": entry["alive"],
            "draining": entry.get("draining", False),
            "dead_reason": entry.get("dead_reason"),
            "routed": entry.get("routed", 0),
            "queue_depth": entry.get("queue_depth", 0),
            "events": events[-event_tail:],
            "events_dropped": fed_rows.get(name, {}).get(
                "events_dropped", 0),
            "scrape_age_s": ages.get(name),
        }
    snap = metrics.snapshot()
    pool_gauges = {
        f"{e['name']}{sorted(e['labels'].items())}": e["value"]
        for e in snap.get("gauges", ())
        if e["name"].startswith(("pool.", "kv_pool", "host_tier"))}
    return {
        "schema": FLIGHT_SCHEMA,
        "reason": reason,
        "tag": tag,
        "time_unix": time.time(),
        "replicas": replicas,
        "router": {
            "replicas": len(routing),
            "alive": sum(1 for e in routing if e["alive"]),
            "counters": dict(counters),
            "routing": routing,
            "events": router_events[-event_tail:],
        },
        "traces": stitched["traces"],
        "orphan_spans": stitched["orphans"],
        "fleet": block,
        "pool_gauges": pool_gauges,
        "metrics": snap,
    }


def validate_flight(doc: dict) -> dict:
    """Validate a flight bundle against the pinned schema; returns the
    document, raises ``ValueError`` naming every problem otherwise —
    the CI round's bank step refuses a malformed postmortem."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        raise ValueError("flight bundle must be a dict")
    if doc.get("schema") != FLIGHT_SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, expected "
                        f"{FLIGHT_SCHEMA!r}")
    for key in FLIGHT_KEYS:
        if key not in doc:
            problems.append(f"missing key {key!r}")
    reps = doc.get("replicas")
    if not isinstance(reps, dict) or not reps:
        problems.append("replicas must be a non-empty dict")
    else:
        for name, entry in reps.items():
            for key in FLIGHT_REPLICA_KEYS:
                if key not in entry:
                    problems.append(f"replica {name!r} missing {key!r}")
            if not isinstance(entry.get("events"), list):
                problems.append(f"replica {name!r} events must be a "
                                f"list (the ring tail)")
    router = doc.get("router")
    if not isinstance(router, dict):
        problems.append("router block must be a dict")
    else:
        for key in ("replicas", "alive", "counters", "routing",
                    "events"):
            if key not in router:
                problems.append(f"router block missing {key!r}")
    if not isinstance(doc.get("traces"), dict):
        problems.append("traces must be a dict keyed by trace_id")
    if not isinstance(doc.get("orphan_spans"), list):
        problems.append("orphan_spans must be a list")
    if problems:
        raise ValueError("invalid flight bundle: " + "; ".join(problems))
    return doc
