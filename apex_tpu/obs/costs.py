"""Analytical roofline cost model over the lint harness's jaxprs.

The IR lint tier (``analysis/ir/harness.py``) already traces every real
entry point in the repo — kernels, fused optimizers, the serving
engine's admission/decode programs — into jaxprs on CPU, devicelessly.
This module walks those same jaxprs and prices them: per-equation FLOPs,
HBM bytes moved, peak live bytes, and arithmetic intensity, rolled up
into a per-program roofline estimate against a declared chip profile
(v5e by default: 394 TFLOP/s bf16, 819 GB/s HBM). With the TPU tunnel
down, this is the repo's perf trajectory of record: the numbers are
deterministic functions of the staged programs, so the perf ledger
(``obs/ledger.py``) can gate on them exactly.

Counting conventions (fixed — the ledger's exactness depends on them
being revision-stable, not on them being cycle-accurate):

- ``dot_general``: ``2 · prod(batch) · prod(lhs free) · prod(rhs free)
  · prod(contract)`` FLOPs (multiply+add).
- elementwise primitives (transcendentals included): one FLOP per
  output element.
- reductions / cumulative ops: one FLOP per *operand* element.
- layout/movement ops (reshape, transpose, gather, slice, convert, …):
  zero FLOPs.
- HBM bytes: every non-literal operand read once + every result written
  once per execution — an upper bound under XLA fusion, but a
  *consistent* one, and exact for the weight/KV streams that dominate
  serving decode.
- ``scan`` bodies multiply by ``length`` (weights close over the body,
  so the weight stream is charged once per step — the physical HBM
  behavior of TPU decode); ``while`` bodies are charged one trip (noted
  in the report); ``cond`` charges its most expensive branch;
  ``pallas_call`` uses the kernel's declared ``cost_estimate`` when
  present, else walks the kernel jaxpr times the grid.
- peak live bytes: a liveness sweep over the top-level equation list
  (inner-jaxpr scratch is not modeled — pool/weight residency dominates
  every program here).
- all bytes are LOGICAL (what the program streams), not tiled-padded
  (what arrays occupy on chip). The padding math lives once, in
  ``apex_tpu/analysis/mem/layout.py``; the mem lint tier prices the
  padded side for HBM-fit proofs, and reports here carry a note when
  the two diverge materially.

``python -m apex_tpu.obs.costs`` emits the report (text, or ``--json``)
covering EVERY registered case, including the decode chunk's
weight-vs-KV byte split — the number behind docs/serving.md's
"weight-bound decode" claim.
"""

from __future__ import annotations

import dataclasses
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

__all__ = ["ChipProfile", "PROFILES", "EqnCost", "CaseCost",
           "cost_of_jaxpr", "cost_report", "decode_split",
           "tp_decode_split", "spec_decode_split", "host_tier_split",
           "ledger_metrics", "main"]

GIB = 1024 ** 3


# --------------------------------------------------------------------------
# chip profiles
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ChipProfile:
    """Peak rates for one accelerator. ``flops_per_sec`` is keyed by the
    model's dtype classes (``bf16`` covers fp16 too, ``int8`` the 8-bit
    integer MXU path, ``f32`` everything wider); unknown dtypes price at
    the f32 rate — conservative for the roofline.
    ``host_link_bytes_per_sec`` is the host<->device DMA stream (PCIe
    for the inference parts) the tiered KV pool's demote/promote copies
    ride — two orders of magnitude under HBM, which is exactly why the
    tier only ever moves whole pages at sync boundaries."""

    name: str
    flops_per_sec: Dict[str, float]
    hbm_bytes_per_sec: float
    hbm_bytes: int
    host_link_bytes_per_sec: float = 32e9

    def peak_flops(self, dtype_key: str) -> float:
        return self.flops_per_sec.get(dtype_key,
                                      self.flops_per_sec["f32"])


#: pluggable profile registry (``--profile``); numbers are the public
#: per-chip peak specs (host link: PCIe gen3 x16 ~32 GB/s on v5e/v4
#: hosts, gen4 x16 ~64 GB/s on v5p)
PROFILES: Dict[str, ChipProfile] = {
    "v5e": ChipProfile("v5e",
                       {"bf16": 394e12, "f32": 197e12, "int8": 788e12},
                       hbm_bytes_per_sec=819e9, hbm_bytes=16 * GIB,
                       host_link_bytes_per_sec=32e9),
    "v5p": ChipProfile("v5p",
                       {"bf16": 459e12, "f32": 229e12, "int8": 918e12},
                       hbm_bytes_per_sec=2765e9, hbm_bytes=95 * GIB,
                       host_link_bytes_per_sec=64e9),
    "v4": ChipProfile("v4",
                      {"bf16": 275e12, "f32": 137e12, "int8": 275e12},
                      hbm_bytes_per_sec=1228e9, hbm_bytes=32 * GIB,
                      host_link_bytes_per_sec=32e9),
}


def _dtype_key(dtype) -> str:
    name = str(getattr(dtype, "name", dtype))
    if name in ("bfloat16", "float16"):
        return "bf16"
    # extended dtypes (PRNG keys) have no ``kind`` — price at f32
    if getattr(dtype, "kind", "") in "iu" \
            and getattr(dtype, "itemsize", 0) == 1:
        return "int8"
    return "f32"


# --------------------------------------------------------------------------
# per-equation pricing
# --------------------------------------------------------------------------

#: primitives priced at one FLOP per OPERAND element
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "cumsum", "cumprod",
    "cummax", "cummin", "cumlogsumexp", "reduce_window_sum",
    "reduce_window_max",
})

#: pure data movement / layout — zero FLOPs, bytes still counted
_ZERO_FLOP_PRIMS = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "gather",
    "scatter", "convert_element_type", "bitcast_convert_type", "copy",
    "copy_p", "iota", "rev", "pad", "select_n", "stop_gradient",
    "device_put", "split", "expand_dims", "real", "imag",
    "reduce_precision", "clamp_gradient", "tie_in", "opt_barrier",
    "optimization_barrier",
    # pallas/state ref ops: loads/stores are data movement, not math
    "get", "swap", "load", "store", "masked_load", "masked_swap",
    "addupdate", "broadcast_to",
})

#: params that hold a sub-jaxpr in higher-order primitives we recurse
#: into generically (multiplier 1)
_JAXPR_PARAMS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr",
                 "body_jaxpr")


def _aval_elems(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def _aval_bytes(aval) -> int:
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return 0
    # extended dtypes (PRNG keys) have no itemsize; 4 B/elem is close
    # enough for what is always metadata-sized state
    itemsize = getattr(dt, "itemsize", 4)
    return _aval_elems(aval) * int(itemsize)


def _is_literal(v) -> bool:
    return not hasattr(v, "count") and hasattr(v, "val")


def _eqn_flops(eqn) -> int:
    """FLOPs of one leaf equation per the module's conventions."""
    name = eqn.primitive.name
    if name == "dot_general":
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        batch = 1
        for d in lb:
            batch *= int(lhs.shape[d])
        contract = 1
        for d in lc:
            contract *= int(lhs.shape[d])
        lhs_free = _aval_elems(lhs) // max(batch * contract, 1)
        rhs_free = _aval_elems(rhs) // max(batch * contract, 1)
        return 2 * batch * lhs_free * rhs_free * contract
    if name == "conv_general_dilated":
        out = eqn.outvars[0].aval
        rhs = eqn.invars[1].aval
        # 2 · output elements · kernel taps per output feature
        taps = _aval_elems(rhs) // max(int(rhs.shape[
            eqn.params["dimension_numbers"].rhs_spec[0]]), 1)
        return 2 * _aval_elems(out) * taps
    if name in _ZERO_FLOP_PRIMS:
        return 0
    if name in _REDUCE_PRIMS:
        return sum(_aval_elems(v.aval) for v in eqn.invars
                   if not _is_literal(v))
    # elementwise default: one FLOP per output element
    return sum(_aval_elems(v.aval) for v in eqn.outvars)


def _eqn_bytes(eqn) -> int:
    read = sum(_aval_bytes(v.aval) for v in eqn.invars
               if not _is_literal(v))
    written = sum(_aval_bytes(v.aval) for v in eqn.outvars)
    return read + written


def _eqn_dtype_key(eqn) -> str:
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        if dt is not None:
            return _dtype_key(dt)
    return "f32"


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------

@dataclasses.dataclass
class EqnCost:
    """One leaf equation's aggregate cost (already multiplied through
    enclosing scan lengths / pallas grids)."""

    primitive: str
    flops: int
    bytes: int
    dtype_key: str
    count: int = 1
    anchor: Optional[Tuple[str, int]] = None     # (repo-rel file, line)


class _Walk:
    """Accumulator for one program: leaf costs keyed by
    (primitive, anchor) so repeated equations fold together."""

    def __init__(self, root: Optional[Path]):
        self.root = root
        self.leaves: Dict[Tuple[str, Optional[Tuple[str, int]], str],
                          EqnCost] = {}
        self.notes: List[str] = []

    def _anchor(self, eqn) -> Optional[Tuple[str, int]]:
        if self.root is None:
            return None
        from apex_tpu.analysis.ir.ir_report import eqn_anchor
        return eqn_anchor(eqn, self.root)

    def add(self, eqn, mult: int, flops: int, nbytes: int) -> None:
        key = (eqn.primitive.name, self._anchor(eqn), _eqn_dtype_key(eqn))
        leaf = self.leaves.get(key)
        if leaf is None:
            self.leaves[key] = EqnCost(
                primitive=key[0], flops=flops * mult, bytes=nbytes * mult,
                dtype_key=key[2], count=mult, anchor=key[1])
        else:
            leaf.flops += flops * mult
            leaf.bytes += nbytes * mult
            leaf.count += mult

    # -- recursion ---------------------------------------------------------

    def walk(self, jaxpr, mult: int = 1) -> None:
        for eqn in jaxpr.eqns:
            self._walk_eqn(eqn, mult)

    def _walk_eqn(self, eqn, mult: int) -> None:
        name = eqn.primitive.name
        if name == "shard_map":
            # the body's avals are the LOCAL shard shapes, so a sharded
            # program's flops/bytes price PER CHIP — the per-device
            # roofline a TP mesh actually runs (docs/tp_serving.md)
            self.notes.append(
                "shard_map body priced per chip (local shard shapes)")
            self.walk(eqn.params["jaxpr"], mult)
            return
        if name == "scan":
            length = int(eqn.params.get("length", 1))
            self.walk(eqn.params["jaxpr"].jaxpr, mult * length)
            return
        if name == "while":
            self.notes.append(
                "while loop charged one trip (trip count unknown)")
            self.walk(eqn.params["cond_jaxpr"].jaxpr, mult)
            self.walk(eqn.params["body_jaxpr"].jaxpr, mult)
            return
        if name == "cond":
            # charge the most expensive branch
            best: Optional[_Walk] = None
            best_cost = -1.0
            for br in eqn.params["branches"]:
                sub = _Walk(self.root)
                sub.walk(br.jaxpr, mult)
                cost = sum(l.flops + l.bytes for l in sub.leaves.values())
                if cost > best_cost:
                    best, best_cost = sub, cost
            if best is not None:
                self._merge(best)
            return
        if name == "pallas_call":
            self._walk_pallas(eqn, mult)
            return
        inner = [eqn.params[k] for k in _JAXPR_PARAMS if k in eqn.params]
        if not inner:
            # any other higher-order primitive: recurse into every
            # (Closed)Jaxpr-valued param rather than treating the call
            # as an opaque leaf
            for v in eqn.params.values():
                if hasattr(v, "eqns") \
                        or hasattr(getattr(v, "jaxpr", None), "eqns"):
                    inner.append(v)
        if inner:
            for sub in inner:
                self.walk(sub.jaxpr if hasattr(sub, "jaxpr") else sub,
                          mult)
            return
        self.add(eqn, mult, _eqn_flops(eqn), _eqn_bytes(eqn))

    def _walk_pallas(self, eqn, mult: int) -> None:
        est = eqn.params.get("cost_estimate")
        nbytes = _eqn_bytes(eqn)     # operands/results cross HBM once
        if est is not None and getattr(est, "flops", None) is not None:
            flops = int(est.flops) + int(getattr(est, "transcendentals",
                                                 0) or 0)
            ba = getattr(est, "bytes_accessed", None)
            if ba:
                nbytes = int(ba)
            self.add(eqn, mult, flops, nbytes)
            return
        grid = 1
        gm = eqn.params.get("grid_mapping")
        for d in getattr(gm, "grid", ()) or ():
            if isinstance(d, int):
                grid *= d
        sub = _Walk(self.root)
        sub.walk(eqn.params["jaxpr"], mult * grid)
        kernel_flops = sum(l.flops for l in sub.leaves.values())
        self.add(eqn, mult, kernel_flops // max(mult, 1), nbytes)
        self.notes.extend(sub.notes)

    def _merge(self, other: "_Walk") -> None:
        for key, leaf in other.leaves.items():
            mine = self.leaves.get(key)
            if mine is None:
                self.leaves[key] = leaf
            else:
                mine.flops += leaf.flops
                mine.bytes += leaf.bytes
                mine.count += leaf.count
        self.notes.extend(other.notes)


def _peak_live_bytes(jaxpr) -> int:
    """Liveness sweep over the top-level equation list: a var is live
    from its definition (program entry for inputs/consts) to its last
    use (program exit for outputs). Inner-jaxpr scratch is not modeled."""
    last_use: Dict[object, int] = {}
    n = len(jaxpr.eqns)
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not _is_literal(v):
            last_use[v] = n
    live_bytes: Dict[object, int] = {
        v: _aval_bytes(v.aval)
        for v in list(jaxpr.invars) + list(jaxpr.constvars)
        if v in last_use}
    cur = sum(live_bytes.values())
    peak = cur
    for i, eqn in enumerate(jaxpr.eqns):
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        peak = max(peak, cur + out_bytes)
        for v in eqn.outvars:
            if last_use.get(v, i) > i:
                live_bytes[v] = _aval_bytes(v.aval)
                cur += live_bytes[v]
        for v in eqn.invars:
            if not _is_literal(v) and last_use.get(v) == i \
                    and v in live_bytes:
                cur -= live_bytes.pop(v)
    return peak


# --------------------------------------------------------------------------
# per-case rollup
# --------------------------------------------------------------------------

@dataclasses.dataclass
class CaseCost:
    name: str
    domain: str
    flops: int
    hbm_bytes: int
    peak_live_bytes: int
    arith_intensity: float
    flop_time_ms: float
    byte_time_ms: float
    predicted_ms: float
    bound: str                       # "compute" | "memory"
    by_primitive: Dict[str, Dict[str, int]]
    top_eqns: List[dict]
    notes: List[str]

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def cost_of_jaxpr(closed, profile: ChipProfile, *,
                  root: Optional[Path] = None, name: str = "<program>",
                  domain: str = "ops", top_k: int = 5) -> CaseCost:
    """Price one ClosedJaxpr against ``profile``. ``root`` enables
    source-line attribution (anchors resolved like IR lint findings).

    All byte counts here are LOGICAL — the bytes the program streams,
    which is what bandwidth/roofline math wants. On chip, arrays occupy
    their TPU tiled-layout PADDED size (minor dim to 128 lanes, second-
    minor to the dtype's sublane multiple); when that gap is material
    for the program's boundary arrays, a note says so and points at the
    mem lint tier, which prices the padded side (HBM *fit*, not
    bandwidth — ``apex_tpu/analysis/mem/layout.py`` is the one place
    the padding math lives)."""
    from apex_tpu.analysis.mem.layout import (aval_logical_bytes,
                                              aval_padded_bytes)

    w = _Walk(root)
    w.walk(closed.jaxpr)
    b_logical = b_padded = 0
    for v in list(closed.jaxpr.invars) + list(closed.jaxpr.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            b_logical += aval_logical_bytes(aval)
            b_padded += aval_padded_bytes(aval)
    if b_logical and b_padded >= 1.25 * b_logical:
        w.notes.append(
            f"tiled layout: boundary arrays occupy "
            f"{b_padded / GIB:.3f} GiB on chip vs {b_logical / GIB:.3f} "
            f"GiB logical ({b_padded / b_logical:.2f}x) — bytes here "
            f"price the logical stream; the mem lint tier prices the "
            f"padded residency")
    flops = sum(l.flops for l in w.leaves.values())
    nbytes = sum(l.bytes for l in w.leaves.values())
    flop_t = sum(l.flops / profile.peak_flops(l.dtype_key)
                 for l in w.leaves.values())
    byte_t = nbytes / profile.hbm_bytes_per_sec
    # roofline per equation: each leaf pays the slower of its two walls
    pred_s = sum(max(l.flops / profile.peak_flops(l.dtype_key),
                     l.bytes / profile.hbm_bytes_per_sec)
                 for l in w.leaves.values())
    by_prim: Dict[str, Dict[str, int]] = {}
    for leaf in w.leaves.values():
        slot = by_prim.setdefault(leaf.primitive,
                                  {"flops": 0, "bytes": 0, "count": 0})
        slot["flops"] += leaf.flops
        slot["bytes"] += leaf.bytes
        slot["count"] += leaf.count
    ranked = sorted(
        w.leaves.values(),
        key=lambda l: -max(l.flops / profile.peak_flops(l.dtype_key),
                           l.bytes / profile.hbm_bytes_per_sec))
    top = []
    for leaf in ranked[:top_k]:
        t_us = 1e6 * max(leaf.flops / profile.peak_flops(leaf.dtype_key),
                         leaf.bytes / profile.hbm_bytes_per_sec)
        top.append({
            "primitive": leaf.primitive, "flops": leaf.flops,
            "bytes": leaf.bytes, "count": leaf.count,
            "dtype": leaf.dtype_key, "predicted_us": round(t_us, 3),
            "file": leaf.anchor[0] if leaf.anchor else None,
            "line": leaf.anchor[1] if leaf.anchor else None,
        })
    return CaseCost(
        name=name, domain=domain, flops=flops, hbm_bytes=nbytes,
        peak_live_bytes=_peak_live_bytes(closed.jaxpr),
        arith_intensity=flops / nbytes if nbytes else 0.0,
        flop_time_ms=flop_t * 1e3, byte_time_ms=byte_t * 1e3,
        predicted_ms=pred_s * 1e3,
        bound="compute" if flop_t >= byte_t else "memory",
        by_primitive=by_prim, top_eqns=top,
        notes=sorted(set(w.notes)))


# --------------------------------------------------------------------------
# the decode chunk's weight-vs-KV byte split
# --------------------------------------------------------------------------

def _kv_step_bytes_max(cache):
    """Worst-case KV pool bytes one decode step reads: per layer, each
    slot's kernel reads its block-table row — at most
    ``max_pages_per_seq`` pages — bounded by the pool size (page 0 is
    the null sink). Page bytes derive from the pages array's ACTUAL
    dtype (``_aval_bytes``), so a quantized int8/fp8 pool prices 2-4x
    narrower than bf16/f32 without a special case; a quantized pool's
    per-(page, kv_head) scale reads (``k_scales``/``v_scales``, one f32
    row per page read) are counted on top. Returns ``(kv_bytes,
    pool_pages)``; shared by the single-chip and tensor-parallel splits
    so the bound can never drift between them."""
    num_slots, max_pages = cache["block_tables"].shape
    kv_step = 0
    pool_pages = None
    for layer in cache["layers"]:
        for key in ("k_pages", "v_pages"):
            pages = layer[key]
            pool_pages = int(pages.shape[0])
            page_bytes = _aval_bytes(pages) // pool_pages
            scales = layer.get(key[0] + "_scales")
            if scales is not None:
                page_bytes += _aval_bytes(scales) // pool_pages
            kv_step += min(pool_pages - 1, num_slots * max_pages) \
                * page_bytes
    return kv_step, pool_pages


def decode_split(prog) -> dict:
    """The serving decode chunk's per-step HBM traffic, split into the
    weight stream vs the (worst-case) KV page reads — computed from the
    case's abstract arguments, so docs/serving.md's "weight-bound
    decode" claim is a number, not prose. ``prog`` is the
    ``gpt2s_engine_decode_chunk`` CaseProgram (args: cache, variables,
    per-slot state)."""
    import jax

    cache, dvars = prog.args[0], prog.args[1]
    # per-LEAF bytes at each leaf's ACTUAL dtype (_aval_bytes), never
    # param-count x model dtype: a quantized tree (int8/fp8 weights, f32
    # scale rows, uint8 int4 nibbles) reports its true stream, scale
    # reads included — the w8/w4 ratio pins divide these numbers
    weight_bytes = sum(_aval_bytes(leaf)
                      for leaf in jax.tree.leaves(dvars))
    num_slots, max_pages = cache["block_tables"].shape
    kv_step, pool_pages = _kv_step_bytes_max(cache)
    total = weight_bytes + kv_step
    return {
        "weight_bytes_per_step": int(weight_bytes),
        "kv_bytes_per_step_max": int(kv_step),
        "weight_fraction": weight_bytes / total if total else 0.0,
        "num_slots": int(num_slots), "max_pages_per_seq": int(max_pages),
        "pool_pages": pool_pages,
    }


def tp_decode_split(prog, profile: ChipProfile,
                    tp_worlds=(1, 2, 4)) -> dict:
    """Per-CHIP HBM traffic of the tensor-parallel decode chunk at
    tp = 1/2/4 — the sharding story as numbers (docs/tp_serving.md):
    head-sharded weights and K/V pages divide by ``tp``, replicated
    leaves (norms, biases, position table) do not, so both the per-chip
    byte stream and the weight fraction are computed, not prose.
    ``prog`` is the ``tp2_engine_decode_chunk`` CaseProgram; its
    builder-attached ``meta`` carries the sharded/replicated weight
    split (``analysis/ir/harness.py`` — the jaxpr alone cannot say
    which leaf shards). Also prices the mesh-tp per-chip step against
    ``profile``'s HBM bandwidth (decode is memory-bound) — the banded
    ledger metric ``tp2.paged_decode.predicted_step_ms``."""
    meta = prog.meta or {}
    cache = prog.args[0]
    num_slots = cache["block_tables"].shape[0]
    kv_step_total, pool_pages = _kv_step_bytes_max(cache)
    sharded_w = int(meta["sharded_weight_bytes"])
    repl_w = int(meta["replicated_weight_bytes"])
    mesh_tp = int(meta["tp"])
    per_tp = {}
    for tp in tp_worlds:
        w = sharded_w / tp + repl_w
        kv = kv_step_total / tp
        total = w + kv
        per_tp[str(tp)] = {
            "weight_bytes_per_chip_per_step": int(w),
            "kv_bytes_per_chip_per_step_max": int(kv),
            "hbm_bytes_per_chip_per_step": int(total),
            "weight_fraction": w / total if total else 0.0,
        }
    at_mesh = per_tp[str(mesh_tp)]
    predicted_ms = (at_mesh["hbm_bytes_per_chip_per_step"]
                    / profile.hbm_bytes_per_sec * 1e3)
    return {
        "tp_mesh": mesh_tp,
        "num_slots": int(num_slots),
        "pool_pages": pool_pages,
        "per_tp": per_tp,
        "predicted_step_ms_per_chip": predicted_ms,
    }


def spec_decode_split(prog, profile: ChipProfile) -> dict:
    """The speculative round's weight economics (ISSUE 13): one round
    streams the target weights ONCE (the ``s = k`` verify step) plus
    the draft weights ``k`` times (the draft scan), and emits between 1
    and ``k`` accepted tokens — so the per-ACCEPTED-token weight stream
    is ``(W_target + k * W_draft) / a`` at acceptance length ``a``.
    Decode is weight-bound (``decode_split``), so this ratio against
    the non-speculative per-token stream (``W_target``) IS the speedup
    model: speculation pays whenever ``k * W_draft < (a - 1) *
    W_target``. ``prog`` is the ``gpt2s_engine_spec_step_chunk``
    CaseProgram; its builder-attached ``meta`` carries the two weight
    byte counts and ``k`` (``analysis/ir/harness.py``). Also prices the
    per-acceptance-point round time against ``profile``'s HBM
    bandwidth — the banded ledger metrics
    ``spec_decode.predicted_step_ms_a<a>``."""
    meta = prog.meta or {}
    k = int(meta["k"])
    target_w = int(meta["target_weight_bytes"])
    draft_w = int(meta["draft_weight_bytes"])
    cache, dcache = prog.args[0], prog.args[1]
    kv_target, _ = _kv_step_bytes_max(cache)
    kv_draft, _ = _kv_step_bytes_max(dcache)
    # per round: one target verify pass + k draft passes, each reading
    # its pool's worst-case pages
    round_bytes = (target_w + kv_target) + k * (draft_w + kv_draft)
    round_weight = target_w + k * draft_w
    per_acceptance = {}
    for a in range(1, k + 1):
        per_acceptance[str(a)] = {
            "weight_bytes_per_accepted_token": int(round_weight // a),
            "hbm_bytes_per_accepted_token": int(round_bytes // a),
            "predicted_step_ms": (round_bytes / a
                                  / profile.hbm_bytes_per_sec * 1e3),
        }
    return {
        "k": k, "draft_len": k - 1,
        "target_weight_bytes": target_w,
        "draft_weight_bytes": draft_w,
        "round_weight_bytes": int(round_weight),
        "round_hbm_bytes": int(round_bytes),
        "per_acceptance": per_acceptance,
        # the breakeven acceptance length: smallest a whose per-token
        # weight stream beats the non-speculative W_target
        "breakeven_acceptance": next(
            (a for a in range(1, k + 1)
             if round_weight // a < target_w), None),
    }


def host_tier_split(prog, profile: ChipProfile) -> dict:
    """The tiered KV pool's host-link DMA stream (ISSUE 17): one
    demote (``gather_pages``) or promote (``promote_pages``) moves a
    null-padded ``HOST_COPY_CHUNK`` batch of pages' K/V tiles — plus
    per-(page, kv_head) scale rows on quantized pools — across the
    host link, priced against ``profile.host_link_bytes_per_sec``
    rather than HBM. ``prog`` is the ``gpt2s_host_tier_gather``
    CaseProgram (args: cache, page row); the chunk bytes are the
    gather's output tree evaluated abstractly off the cache leaves, so
    the number tracks the pool dtype (an int8 pool moves narrow tiles
    and f32 scales). The chunk time is what one promote adds to the
    admission it extends — the banded ledger metric
    ``host_tier.promote_chunk_predicted_ms``."""
    import jax

    from apex_tpu.serving import kv_pool

    cache, row = prog.args[0], prog.args[1]
    tiles = jax.eval_shape(kv_pool.gather_pages, cache, row)
    chunk_bytes = sum(_aval_bytes(leaf)
                      for leaf in jax.tree.leaves(tiles))
    chunk_pages = int(row.shape[0])
    dma_ms = chunk_bytes / profile.host_link_bytes_per_sec * 1e3
    return {
        "chunk_pages": chunk_pages,
        "chunk_bytes": int(chunk_bytes),
        "bytes_per_page": int(chunk_bytes // chunk_pages),
        "host_link_bytes_per_sec": float(profile.host_link_bytes_per_sec),
        "predicted_chunk_dma_ms": dma_ms,
    }


# --------------------------------------------------------------------------
# whole-registry report
# --------------------------------------------------------------------------

def cost_report(root, *, profile: str = "v5e", case: Optional[str] = None,
                top_k: int = 5) -> dict:
    """Trace every registered analysis case (or one, ``case=``) and
    price it. Returns the JSON-ready report document; a case that fails
    to trace lands in ``errors`` instead of killing the run."""
    from apex_tpu.analysis.ir.harness import analysis_cases, build_case_ir

    root = Path(root).resolve()
    prof = PROFILES[profile]
    cases = analysis_cases(root)
    if case is not None:
        cases = [c for c in cases if c.name == case]
        if not cases:
            raise ValueError(f"unknown case: {case}")
    out_cases: List[dict] = []
    errors: List[dict] = []
    split = None
    tp_split = None
    spec_split = None
    int8kv_split = None
    int8kv_tp_split = None
    w8_split = None
    w4_split = None
    w8_tp_split = None
    host_split = None
    for c in cases:
        try:
            ir = build_case_ir(c)
            cost = cost_of_jaxpr(ir.closed, prof, root=root, name=c.name,
                                 domain=c.domain, top_k=top_k)
            out_cases.append(cost.to_json())
            if c.name == "gpt2s_engine_decode_chunk":
                # per-STEP split, read straight off the abstract args
                split = decode_split(ir.prog)
            if c.name == "tp2_engine_decode_chunk":
                # per-CHIP split of the SHARDED decode chunk
                tp_split = tp_decode_split(ir.prog, prof)
            if c.name == "gpt2s_engine_spec_step_chunk":
                # per-ACCEPTED-TOKEN split of the speculative round
                spec_split = spec_decode_split(ir.prog, prof)
            if c.name == "gpt2s_int8kv_engine_decode_chunk":
                # same split over the QUANTIZED pool: the narrow KV
                # stream + scale reads (docs/serving.md)
                int8kv_split = decode_split(ir.prog)
            if c.name == "tp2_int8kv_engine_decode_chunk":
                int8kv_tp_split = tp_decode_split(ir.prog, prof)
            if c.name == "gpt2s_w8_engine_decode_chunk":
                # split over the QUANTIZED weight tree: int8 block
                # linears + f32 scale rows, fp everything else — the
                # per-leaf dtype bytes ARE the narrow stream
                w8_split = decode_split(ir.prog)
            if c.name == "gpt2s_w4_engine_decode_chunk":
                w4_split = decode_split(ir.prog)
            if c.name == "tp2_w8_engine_decode_chunk":
                w8_tp_split = tp_decode_split(ir.prog, prof)
            if c.name == "gpt2s_host_tier_gather":
                # the demote/promote DMA chunk over the host link
                host_split = host_tier_split(ir.prog, prof)
        except Exception as e:       # noqa: BLE001 — report, don't crash
            errors.append({"case": c.name,
                           "error": f"{type(e).__name__}: {e}"})
    totals = {
        "flops": sum(c["flops"] for c in out_cases),
        "hbm_bytes": sum(c["hbm_bytes"] for c in out_cases),
        "predicted_ms": sum(c["predicted_ms"] for c in out_cases),
    }
    by_domain: Dict[str, Dict[str, float]] = {}
    for c in out_cases:
        slot = by_domain.setdefault(
            c["domain"], {"flops": 0, "hbm_bytes": 0, "predicted_ms": 0.0,
                          "cases": 0})
        slot["flops"] += c["flops"]
        slot["hbm_bytes"] += c["hbm_bytes"]
        slot["predicted_ms"] += c["predicted_ms"]
        slot["cases"] += 1
    return {"schema": 1, "profile": dataclasses.asdict(prof),
            "root": str(root), "cases": out_cases, "totals": totals,
            "by_domain": by_domain, "decode_split": split,
            "tp_decode_split": tp_split,
            "spec_decode_split": spec_split,
            "int8kv_decode_split": int8kv_split,
            "int8kv_tp_decode_split": int8kv_tp_split,
            "w8_decode_split": w8_split,
            "w4_decode_split": w4_split,
            "w8_tp_decode_split": w8_tp_split,
            "host_tier_split": host_split,
            "errors": errors}


def ledger_metrics(report: dict) -> Dict[str, float]:
    """Flatten a report into the deterministic ``cost.*`` metric set the
    perf ledger stores and gates on exactly."""
    m: Dict[str, float] = {
        "cost.total_flops": float(report["totals"]["flops"]),
        "cost.total_hbm_bytes": float(report["totals"]["hbm_bytes"]),
        "cost.total_predicted_ms": float(report["totals"]["predicted_ms"]),
    }
    for dom, slot in sorted(report.get("by_domain", {}).items()):
        m[f"cost.domain.{dom}.predicted_ms"] = float(slot["predicted_ms"])
    for c in report["cases"]:
        m[f"cost.case.{c['name']}.flops"] = float(c["flops"])
        m[f"cost.case.{c['name']}.predicted_ms"] = float(c["predicted_ms"])
    split = report.get("decode_split")
    if split:
        m["cost.decode.weight_bytes_per_step"] = \
            float(split["weight_bytes_per_step"])
        m["cost.decode.kv_bytes_per_step_max"] = \
            float(split["kv_bytes_per_step_max"])
        m["cost.decode.weight_fraction"] = float(split["weight_fraction"])
    tsplit = report.get("tp_decode_split")
    if tsplit:
        for tp, slot in sorted(tsplit["per_tp"].items()):
            m[f"cost.tp_decode.hbm_bytes_per_chip_per_step_tp{tp}"] = \
                float(slot["hbm_bytes_per_chip_per_step"])
            m[f"cost.tp_decode.weight_fraction_tp{tp}"] = \
                float(slot["weight_fraction"])
        # deliberately NOT cost.*-prefixed: the per-chip step time is the
        # tp2 serving headline and gates on the direction-aware ±band
        # (lower-better "_ms"), not the exact-match ratchet
        m["tp2.paged_decode.predicted_step_ms"] = \
            float(tsplit["predicted_step_ms_per_chip"])
    qsplit = report.get("int8kv_decode_split")
    if qsplit:
        m["cost.decode.int8_kv.kv_bytes_per_step_max"] = \
            float(qsplit["kv_bytes_per_step_max"])
        m["cost.decode.int8_kv.weight_fraction"] = \
            float(qsplit["weight_fraction"])
        if split:
            # the PR's acceptance number: the narrow pool's per-step KV
            # stream as a fraction of the fp pool's (<= 0.55 pinned by
            # tests/test_quantized_kv.py)
            m["cost.decode.int8_kv.kv_bytes_ratio_vs_fp"] = \
                float(qsplit["kv_bytes_per_step_max"]) / \
                float(split["kv_bytes_per_step_max"])
    qtsplit = report.get("int8kv_tp_decode_split")
    if qtsplit:
        for tp, slot in sorted(qtsplit["per_tp"].items()):
            m[f"cost.tp_decode.int8_kv.kv_bytes_per_chip_per_step_tp"
              f"{tp}"] = float(slot["kv_bytes_per_chip_per_step_max"])
            m[f"cost.tp_decode.int8_kv.weight_fraction_tp{tp}"] = \
                float(slot["weight_fraction"])
    wsplit = report.get("w8_decode_split")
    if wsplit:
        m["cost.decode.w8.weight_bytes_per_step"] = \
            float(wsplit["weight_bytes_per_step"])
        m["cost.decode.w8.weight_fraction"] = \
            float(wsplit["weight_fraction"])
        if split:
            # the PR's acceptance number: the quantized tree's per-step
            # weight stream as a fraction of the fp tree's (<= 0.55
            # pinned by tests/test_quantized_weights.py)
            m["cost.decode.w8.weight_bytes_ratio_vs_bf16"] = \
                float(wsplit["weight_bytes_per_step"]) / \
                float(split["weight_bytes_per_step"])
    w4split = report.get("w4_decode_split")
    if w4split:
        m["cost.decode.w4.weight_bytes_per_step"] = \
            float(w4split["weight_bytes_per_step"])
        if split:
            # int4 nibbles + per-group scale reads, vs the same fp tree
            # (<= 0.35 pinned by tests/test_quantized_weights.py)
            m["cost.decode.w4.weight_bytes_ratio_vs_bf16"] = \
                float(w4split["weight_bytes_per_step"]) / \
                float(split["weight_bytes_per_step"])
    wtsplit = report.get("w8_tp_decode_split")
    if wtsplit:
        for tp, slot in sorted(wtsplit["per_tp"].items()):
            m[f"cost.tp_decode.w8.hbm_bytes_per_chip_per_step_tp{tp}"] = \
                float(slot["hbm_bytes_per_chip_per_step"])
            m[f"cost.tp_decode.w8.weight_fraction_tp{tp}"] = \
                float(slot["weight_fraction"])
    hsplit = report.get("host_tier_split")
    if hsplit:
        m["cost.decode.host_tier.chunk_bytes"] = \
            float(hsplit["chunk_bytes"])
        m["cost.decode.host_tier.bytes_per_page"] = \
            float(hsplit["bytes_per_page"])
        # same banding rationale as tp2.paged_decode above: the promote
        # chunk's host-link DMA span is a headline ms and gates on the
        # direction-aware band, not the exact-match ratchet
        m["host_tier.promote_chunk_predicted_ms"] = \
            float(hsplit["predicted_chunk_dma_ms"])
    ssplit = report.get("spec_decode_split")
    if ssplit:
        m["cost.spec_decode.k"] = float(ssplit["k"])
        m["cost.spec_decode.round_weight_bytes"] = \
            float(ssplit["round_weight_bytes"])
        m["cost.spec_decode.round_hbm_bytes"] = \
            float(ssplit["round_hbm_bytes"])
        for a, slot in sorted(ssplit["per_acceptance"].items()):
            m[f"cost.spec_decode.weight_bytes_per_token_a{a}"] = \
                float(slot["weight_bytes_per_accepted_token"])
            # same banding rationale as tp2.paged_decode above: the
            # per-acceptance-point round time is a headline, not a hash
            m[f"spec_decode.predicted_step_ms_a{a}"] = \
                float(slot["predicted_step_ms"])
    return m


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _fmt_qty(v: float, unit: str = "") -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                          (1e3, "K")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}{unit}"
    return f"{v:.1f}{unit}"


def _text_report(report: dict) -> str:
    prof = report["profile"]
    lines = [
        f"apex-tpu cost model — profile {prof['name']} "
        f"({prof['flops_per_sec']['bf16'] / 1e12:.0f} TFLOP/s bf16, "
        f"{prof['hbm_bytes_per_sec'] / 1e9:.0f} GB/s HBM)",
        "",
        f"{'case':44s} {'domain':10s} {'flops':>9s} {'bytes':>9s} "
        f"{'AI':>7s} {'pred':>9s} bound",
    ]
    for c in sorted(report["cases"], key=lambda c: -c["predicted_ms"]):
        lines.append(
            f"{c['name']:44s} {c['domain']:10s} "
            f"{_fmt_qty(c['flops']):>9s} {_fmt_qty(c['hbm_bytes'], 'B'):>9s} "
            f"{c['arith_intensity']:7.2f} {c['predicted_ms']:8.3f}ms "
            f"{c['bound']}")
    t = report["totals"]
    lines += ["", f"total: {_fmt_qty(t['flops'])} flops, "
                  f"{_fmt_qty(t['hbm_bytes'], 'B')} moved, "
                  f"{t['predicted_ms']:.3f} ms predicted across "
                  f"{len(report['cases'])} programs"]
    split = report.get("decode_split")
    if split:
        lines += [
            "",
            "decode chunk per-step HBM traffic "
            f"(slots={split['num_slots']}):",
            f"  weights {_fmt_qty(split['weight_bytes_per_step'], 'B')} "
            f"vs KV <= {_fmt_qty(split['kv_bytes_per_step_max'], 'B')} "
            f"-> weight fraction {split['weight_fraction']:.3f} "
            "(weight-bound decode, docs/serving.md)",
        ]
    qsplit = report.get("int8kv_decode_split")
    if qsplit:
        ratio = (qsplit["kv_bytes_per_step_max"]
                 / split["kv_bytes_per_step_max"]) if split else None
        lines.append(
            "  int8-kv pool: KV <= "
            f"{_fmt_qty(qsplit['kv_bytes_per_step_max'], 'B')}/step"
            + (f" ({ratio:.3f}x the fp pool's stream, scales included)"
               if ratio is not None else ""))
    tsplit = report.get("tp_decode_split")
    if tsplit:
        lines += [
            "",
            "tensor-parallel decode chunk, per-chip HBM/step "
            f"(slots={tsplit['num_slots']}, mesh tp={tsplit['tp_mesh']}):",
        ]
        for tp, slot in sorted(tsplit["per_tp"].items(), key=lambda kv:
                               int(kv[0])):
            lines.append(
                f"  tp={tp}: weights "
                f"{_fmt_qty(slot['weight_bytes_per_chip_per_step'], 'B')}"
                f" + KV <= "
                f"{_fmt_qty(slot['kv_bytes_per_chip_per_step_max'], 'B')}"
                f" = {_fmt_qty(slot['hbm_bytes_per_chip_per_step'], 'B')}"
                f"/chip/step, weight fraction "
                f"{slot['weight_fraction']:.3f}")
        lines.append(
            f"  predicted step @ mesh tp: "
            f"{tsplit['predicted_step_ms_per_chip']:.3f} ms/chip "
            "(HBM-bound)")
    ssplit = report.get("spec_decode_split")
    if ssplit:
        lines += [
            "",
            "speculative round, per-accepted-token weight stream "
            f"(k={ssplit['k']}, round "
            f"{_fmt_qty(ssplit['round_weight_bytes'], 'B')} weights):",
        ]
        for a, slot in sorted(ssplit["per_acceptance"].items(),
                              key=lambda kv: int(kv[0])):
            lines.append(
                f"  a={a}: "
                f"{_fmt_qty(slot['weight_bytes_per_accepted_token'], 'B')}"
                f"/token, {slot['predicted_step_ms']:.3f} ms "
                f"(non-spec {_fmt_qty(ssplit['target_weight_bytes'], 'B')}"
                "/token)")
        lines.append(
            f"  breakeven acceptance: {ssplit['breakeven_acceptance']} "
            "(docs/serving.md)")
    top = []
    for c in report["cases"]:
        for e in c["top_eqns"]:
            top.append((e["predicted_us"], c["name"], e))
    top.sort(key=lambda t: -t[0])
    if top:
        lines += ["", "top equations (roofline time):"]
        for t_us, cname, e in top[:10]:
            where = f"{e['file']}:{e['line']}" if e["file"] else "<jax>"
            lines.append(
                f"  {t_us:10.1f}us {e['primitive']:18s} "
                f"x{e['count']:<5d} {_fmt_qty(e['flops']):>9s} "
                f"{_fmt_qty(e['bytes'], 'B'):>9s}  {cname}  {where}")
    for err in report["errors"]:
        lines.append(f"ERROR {err['case']}: {err['error']}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.obs.costs",
        description="Roofline cost report over every lint-harness "
                    "program (docs/observability.md)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: the package's repo)")
    parser.add_argument("--profile", default="v5e",
                        choices=sorted(PROFILES))
    parser.add_argument("--case", default=None,
                        help="price a single registered case")
    parser.add_argument("--top-k", type=int, default=5)
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="also write the full JSON report")
    args = parser.parse_args(argv)
    root = Path(args.root) if args.root \
        else Path(__file__).resolve().parents[2]
    report = cost_report(root, profile=args.profile, case=args.case,
                         top_k=args.top_k)
    sys.stdout.write(_text_report(report))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[costs] JSON report written to {args.json}")
    return 1 if report["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
