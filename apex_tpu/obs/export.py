"""Metric export: Prometheus text exposition + JSON snapshots.

Readers of the ``apex_tpu.utils.metrics`` registry — nothing here ever
touches a device. Three transports:

- :func:`prometheus_text` — the text exposition format (v0.0.4) any
  Prometheus-compatible scraper ingests: counters and gauges as single
  samples, histograms as the canonical ``_bucket``/``_sum``/``_count``
  triplet with cumulative ``le`` buckets, and the raw ``record()``
  series as ``_count``/``_mean``/``_last`` gauges. Output is sorted and
  deterministic for a given registry state (the golden-file test pins
  it).
- :func:`json_snapshot` / :func:`write_snapshot` — the full registry as
  one JSON document (CI artifacts: ``run_tpu_round.sh`` banks one per
  round next to the bench JSON).
- :func:`serve` — optional stdlib ``http.server`` endpoint exposing
  ``/metrics`` (Prometheus), ``/metrics.json``, ``/healthz`` (liveness:
  pump-alive + queue depth of the frontend passed via ``serve(...,
  frontend=)``), and ``/costs`` (the latest cost-model snapshot
  registered via :func:`publish_costs`) on a daemon thread; returns the
  server (``.server_address`` for the bound port, ``.shutdown()`` to
  stop). No third-party client library, per the no-new-deps rule.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from apex_tpu.utils import metrics

__all__ = ["prometheus_text", "json_snapshot", "write_snapshot", "serve",
           "publish_costs", "latest_costs", "health_doc", "describe"]

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")

# per-instrument description registry: `# HELP` text per metric family
# (registry names — sanitized on emit). Seeded with the core serving
# families; describe() registers more. Families without an entry get a
# generated default so every TYPE line still carries a HELP line (the
# exposition-parse test pins the pairing).
_HELP_LOCK = threading.Lock()
_HELP: Dict[str, str] = {
    "serving.ttft_ms": "Time to first token per request (ms).",
    "serving.tpot_ms": "Steady-state time per output token (ms).",
    "serving.queue_wait_ms": "Enqueue-to-admit wait per request (ms).",
    "serving.decode_step_ms": "Wall time per batched decode step (ms).",
    "serving.queue_depth": "Requests waiting for admission.",
    "serving.slots_in_use": "Decode slots currently occupied.",
    "serving.slo_burn": "SLO miss rate over the rolling retirement "
                        "window.",
    "serving.admitted": "Requests admitted to decode slots.",
    "serving.retired": "Requests retired (complete/cancelled/failed).",
    "router.replicas_alive": "Live replicas behind the router.",
    "router.replica_queue_depth": "Queue depth per routed replica.",
    "fleet.ttft_ms_p95": "Federated per-replica TTFT p95 (ms).",
    "fleet.tpot_ms_p95": "Federated per-replica TPOT p95 (ms).",
    "fleet.queue_depth": "Federated per-replica queue depth.",
    "fleet.slo_burn": "Federated per-replica SLO burn rate.",
    "fleet.scrape_age_s": "Seconds since the replica's last "
                          "successful federation scrape.",
    "kv_pool.free_pages": "Free pages in the device KV pool.",
    "http.connections": "Open HTTP connections.",
    "http.streams_active": "Live SSE token streams.",
}


def describe(name: str, help_text: str) -> None:
    """Register the ``# HELP`` description for a metric family (by
    registry name, e.g. ``serving.ttft_ms``)."""
    with _HELP_LOCK:
        _HELP[name] = " ".join(str(help_text).split())


def _help_for(prom_name: str) -> str:
    """The HELP text for a sanitized family name (falls back to a
    generated default — HELP/TYPE pairing is unconditional)."""
    with _HELP_LOCK:
        for name, text in _HELP.items():
            if _prom_name(name) == prom_name:
                return text
    return f"apex-tpu metric {prom_name}."


def _prom_name(name: str) -> str:
    """Sanitize a registry name (``serving.ttft_ms``) into a Prometheus
    metric name (``serving_ttft_ms``)."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not _NAME_OK.match(out):
        out = "_" + out
    return out


def _prom_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    esc = {k: str(v).replace("\\", r"\\").replace('"', r'\"')
           .replace("\n", r"\n") for k, v in labels.items()}
    inner = ",".join(f'{_prom_name(k)}="{esc[k]}"'
                     for k in sorted(esc))
    return "{" + inner + "}"


def _merge_labels(labels: Dict[str, str], **extra) -> str:
    merged = dict(labels)
    merged.update(extra)
    return _prom_labels(merged)


def _fmt(v: float) -> str:
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"            # valid exposition literal — a NaN
        #                             metric must not kill the exporter
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a ``metrics.snapshot()`` (current registry if omitted) in
    the Prometheus text exposition format, trailing newline included."""
    if snap is None:
        snap = metrics.snapshot()
    lines = []

    def sample(name, labels_str, value):
        lines.append(f"{name}{labels_str} {_fmt(value)}")

    def family(name, prom_type):
        # ONE `# HELP` + `# TYPE` pair per metric family: all label
        # sets of a name are samples of the same family (a second TYPE
        # line for a name is invalid text exposition — two
        # engine-labeled counters hit this), and every TYPE line is
        # preceded by its HELP line from the description registry
        lines.append(f"# HELP {name} {_help_for(name)}")
        lines.append(f"# TYPE {name} {prom_type}")

    for kind, prom_type in (("counters", "counter"), ("gauges", "gauge")):
        seen = set()
        for entry in sorted(snap.get(kind, ()),
                            key=lambda e: (e["name"], sorted(
                                e["labels"].items()))):
            name = _prom_name(entry["name"])
            if name not in seen:
                seen.add(name)
                family(name, prom_type)
            sample(name, _prom_labels(entry["labels"]), entry["value"])

    seen = set()
    for entry in sorted(snap.get("histograms", ()),
                        key=lambda e: (e["name"], sorted(
                            e["labels"].items()))):
        name = _prom_name(entry["name"])
        if name not in seen:
            seen.add(name)
            family(name, "histogram")
        for le, cum in entry["buckets"]:
            le_str = "+Inf" if le is None else format(le, ".6g")
            sample(name + "_bucket",
                   _merge_labels(entry["labels"], le=le_str), cum)
        sample(name + "_sum", _prom_labels(entry["labels"]), entry["sum"])
        sample(name + "_count", _prom_labels(entry["labels"]),
               entry["count"])

    # a name that is BOTH an instrument and a raw series (StepTimer
    # writes its histogram and its record() series under one name) must
    # export once: the typed instrument wins, else `x_count` would appear
    # twice with conflicting TYPE metadata and the scrape is rejected
    instrumented = {_prom_name(e["name"])
                    for kind in ("counters", "gauges", "histograms")
                    for e in snap.get(kind, ())}
    for raw_name in sorted(snap.get("series", ())):
        s = snap["series"][raw_name]
        name = _prom_name(raw_name)
        if name in instrumented:
            continue
        for suffix, value in (("_count", s["count"]), ("_mean", s["mean"]),
                              ("_last", s["last"])):
            family(name + suffix, "gauge")
            sample(name + suffix, "", value)

    return "\n".join(lines) + "\n" if lines else ""


def json_snapshot(extra: Optional[dict] = None) -> dict:
    """The registry snapshot as a JSON-ready document with a timestamp
    (and optional caller context, e.g. the bench tag)."""
    doc = {"time_unix": time.time(), **metrics.snapshot()}
    if extra:
        doc.update(extra)
    return doc


def write_snapshot(path: str, fmt: Optional[str] = None,
                   extra: Optional[dict] = None) -> str:
    """Write the current registry to ``path`` — Prometheus text when
    ``fmt='prom'`` (or the path ends in ``.prom``/``.txt``), JSON
    otherwise. Returns the path."""
    if fmt is None:
        fmt = "prom" if path.endswith((".prom", ".txt")) else "json"
    if fmt not in ("prom", "json"):
        raise ValueError(f"unknown snapshot format {fmt!r}")
    with open(path, "w") as f:
        if fmt == "prom":
            f.write(prometheus_text())
        else:
            json.dump(json_snapshot(extra), f, indent=1, sort_keys=True)
            f.write("\n")
    return path


# latest published cost-model snapshot (``/costs``): one process-wide
# document, written by whoever ran the cost CLI/report last
_COSTS_LOCK = threading.Lock()
_COSTS_DOC: Optional[dict] = None


def publish_costs(doc: Optional[dict]) -> None:
    """Make a cost report (``apex_tpu.obs.costs.cost_report(...)``) the
    document ``/costs`` serves (``None`` unpublishes: back to 404)."""
    global _COSTS_DOC
    with _COSTS_LOCK:
        _COSTS_DOC = doc


def latest_costs() -> Optional[dict]:
    with _COSTS_LOCK:
        return _COSTS_DOC


def health_doc(frontend=None, router=None) -> dict:
    """The ``/healthz`` payload: process liveness plus — when a serving
    frontend is wired in — pump-thread liveness, queue depth, active
    slots, and the pump's terminal failure if it died. Shape pinned by
    tests/test_observability.py (the frontend-only shape is unchanged;
    ``router=`` ADDS a ``router`` block with per-replica liveness and
    queue depth — the router-level health the HTTP surface serves)."""
    doc = {"ok": True, "time_unix": time.time(), "frontend": False,
           "pump_alive": False, "queue_depth": None, "active_slots": None,
           "failure": None}
    if frontend is not None:
        failure = frontend.failure
        doc.update(
            frontend=True, pump_alive=frontend.pump_alive,
            queue_depth=frontend.queue_depth,
            active_slots=frontend.active_slots,
            failure=repr(failure) if failure is not None else None)
        doc["ok"] = failure is None
    if router is not None:
        # fleet-plane staleness (PR 19): liveness is readable from
        # /healthz alone — supervision-tick age, per-replica failover
        # counts, and federation scrape age ride along. All three read
        # through getattr so a router-shaped stub (tests) stays valid.
        fleet = getattr(router, "fleet", None)
        ages = fleet.scrape_ages() if fleet is not None else {}
        tick_age = getattr(router, "last_tick_age_s", None)
        per_replica = []
        for rep in router.replicas:
            per_replica.append({
                "replica": rep.index,
                "alive": rep.alive,
                "draining": rep.draining,
                "pump_alive": rep.frontend.pump_alive if rep.alive
                else False,
                "queue_depth": rep.frontend.queue_depth if rep.alive
                else None,
                "failure": repr(rep.dead_reason)
                if rep.dead_reason is not None else None,
                "last_tick_age_s": tick_age,
                "failovers": getattr(rep, "failovers", 0),
                "scrape_age_s": ages.get(f"replica{rep.index}"),
            })
        n_alive = sum(1 for r in per_replica if r["alive"])
        doc["router"] = {"replicas": len(per_replica), "alive": n_alive,
                         "queue_depth": sum(r["queue_depth"] or 0
                                            for r in per_replica),
                         "per_replica": per_replica}
        doc["ok"] = doc["ok"] and n_alive > 0
    return doc


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (stdlib handler contract)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = prometheus_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/metrics.json":
            body = (json.dumps(json_snapshot(), sort_keys=True)
                    + "\n").encode()
            ctype = "application/json"
        elif path == "/healthz":
            doc = health_doc(getattr(self.server, "frontend", None),
                             router=getattr(self.server, "router", None))
            body = (json.dumps(doc, sort_keys=True) + "\n").encode()
            ctype = "application/json"
        elif path == "/costs":
            doc = latest_costs()
            if doc is None:
                self.send_error(404, "no cost snapshot published")
                return
            body = (json.dumps(doc, sort_keys=True) + "\n").encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):     # silence per-request stderr lines
        pass


def serve(port: int = 0, host: str = "127.0.0.1",
          frontend=None, router=None) -> ThreadingHTTPServer:
    """Start the metrics endpoint on a daemon thread. ``port=0`` binds an
    ephemeral port (read it from ``server.server_address[1]``).
    ``frontend=`` wires a :class:`~apex_tpu.serving.frontend.
    ServingFrontend` into ``/healthz``; ``router=`` a
    :class:`~apex_tpu.serving.router.ReplicaRouter` (per-replica
    liveness and queue depth in the ``router`` block)."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.frontend = frontend
    server.router = router
    thread = threading.Thread(target=server.serve_forever,
                              name="apex-tpu-metrics", daemon=True)
    thread.start()
    return server


# --------------------------------------------------------------------------
# golden regeneration (``python -m apex_tpu.obs.export --golden``)
# --------------------------------------------------------------------------

def seed_golden_registry() -> None:
    """Seed the registry with the FIXED state the golden exposition
    pins (``tests/golden/observability.prom``). One representative of
    every exposition shape, each a real production family (the contract
    tier proves golden families against registered instruments): an
    unlabeled counter, a labeled counter, a gauge, a histogram with its
    ``_bucket``/``_sum``/``_count`` triplet, and a raw ``record()``
    series with its ``_count``/``_mean``/``_last`` gauges. Clears the
    registry first — the golden describes exactly this state."""
    metrics.clear()
    metrics.counter("serving.admitted").inc(3)
    metrics.counter("jit.compiles", labels={"fn": "decode_step"}).inc(2)
    metrics.gauge("kv_pool.free_pages").set(12)
    h = metrics.histogram("serving.ttft_ms", base=1.0, growth=2.0,
                          n_buckets=6)
    for v in (0.5, 1.0, 3.0, 100.0):
        h.observe(v)
    metrics.record("serving.decode_steps", 9)


def _default_golden_path() -> str:
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tests", "golden", "observability.prom")


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.obs.export",
        description="Regenerate the golden Prometheus exposition from "
                    "the canonical seeded registry state (instead of "
                    "hand-editing it).")
    parser.add_argument("--golden", action="store_true", required=True,
                        help="write the golden exposition file")
    parser.add_argument("--out", default=None,
                        help="output path (default: the in-repo "
                             "tests/golden/observability.prom)")
    args = parser.parse_args(argv)
    path = args.out or _default_golden_path()
    seed_golden_registry()
    text = prometheus_text()
    with open(path, "w") as f:
        f.write(text)
    print(f"[export] golden exposition written to {path} "
          f"({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
