"""Persistent perf ledger + regression gate (``PERF_LEDGER.jsonl``).

Rounds 3–5 taught the lesson this module exists for: the TPU tunnel died
and the repo's perf trajectory silently went EMPTY — three rounds of
``BENCH_r0*.json`` record nothing but backend-init failures, so none of
the serving work since has a checked baseline. The ledger fixes both
halves:

- **Trajectory**: every round appends one JSON line per source — the
  deviceless cost-model rollups (``obs/costs.py``, deterministic on
  CPU), and the bench/decode fields when the tunnel cooperates — each
  stamped with git rev + timestamp. ``run_tpu_round.sh`` appends the
  cost entry BEFORE the tunnel probe, so a dead tunnel can no longer
  empty a round.
- **Gate**: ``python -m apex_tpu.obs.ledger --check`` recomputes HEAD's
  metrics and compares them against the most recent ledger values.
  Deterministic ``cost.*`` metrics must match EXACTLY (they only change
  when the staged programs change — which is precisely what a reviewer
  must see); wall-time metrics get a tolerance band (default ±20 %),
  direction-aware (throughput may rise freely, latency may fall
  freely). Exit 1 on regression/drift, 2 on a broken ledger.

The ratchet workflow mirrors tpu-lint's baseline: an intentional
cost-model change fails ``--check`` until the author runs
``python -m apex_tpu.obs.ledger --append`` and commits the new entry —
the perf delta is then an explicit, reviewable line in the PR.

Entry format (one JSON object per line)::

    {"schema": 1, "kind": "cost"|"bench"|"seed", "tag": "r06",
     "git_rev": "<sha>[-dirty]", "time_unix": 1699...,
     "metrics": {"cost.total_flops": ..., ...}, "meta": {...}}
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import os
import subprocess
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

__all__ = ["LEDGER_NAME", "load", "append_entry", "head_cost_metrics",
           "bench_metrics_from_file", "check", "main"]

LEDGER_NAME = "PERF_LEDGER.jsonl"

#: substrings classifying a wall-time metric's good direction; anything
#: matching neither is recorded but not gated (informational counters)
_HIGHER_BETTER = ("tokens_per_sec", "_per_sec", "hit_rate", "step_savings",
                  "speedup", "recovered_rate")
_LOWER_BETTER = ("_ms", "misses", "miss_rate", "bubble")

#: [0, 1] ratios with small integer denominators (one request flipping a
#: ~8-deadline scenario moves miss_rate by 0.125 — a relative ±20 % band
#: would flag scheduling noise as a regression): gate on ABSOLUTE
#: worsening beyond this instead
_RATE_SUFFIXES = ("miss_rate", "hit_rate", "recovered_rate")
_RATE_ABS_TOL = 0.25


def _git_rev(root: Path) -> str:
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, capture_output=True,
            text=True, timeout=10).stdout.strip()
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=root,
            capture_output=True, text=True, timeout=10).stdout.strip()
        return (rev + "-dirty") if dirty else rev or "unknown"
    except Exception:       # noqa: BLE001 — the ledger works without git
        return "unknown"


# --------------------------------------------------------------------------
# storage
# --------------------------------------------------------------------------

def load(path) -> List[dict]:
    """Parse the ledger; raises ValueError on a corrupt line (a broken
    trajectory should fail loudly, not truncate silently)."""
    entries = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{i}: corrupt ledger line: {e}") from e
            if not isinstance(entry, dict) or "metrics" not in entry:
                raise ValueError(
                    f"{path}:{i}: ledger entry without metrics")
            entries.append(entry)
    return entries


def append_entry(path, *, kind: str, tag: str,
                 metrics: Dict[str, float], root=None,
                 meta: Optional[dict] = None,
                 when: Optional[float] = None) -> dict:
    entry = {
        "schema": 1, "kind": kind, "tag": tag,
        "git_rev": _git_rev(Path(root) if root else Path(path).parent),
        "time_unix": round(when if when is not None else time.time(), 3),
        "metrics": {k: metrics[k] for k in sorted(metrics)},
    }
    if meta:
        entry["meta"] = meta
    with open(path, "a") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")
    return entry


# --------------------------------------------------------------------------
# metric sources
# --------------------------------------------------------------------------

def head_cost_metrics(root, *, costs_json: Optional[str] = None,
                      profile: str = "v5e") -> Dict[str, float]:
    """HEAD's deterministic cost metrics — from a pre-computed
    ``--json`` report when given (``run_tpu_round.sh`` banks one per
    round), else by tracing the registry now (~15 s on CPU)."""
    from apex_tpu.obs import costs

    if costs_json:
        with open(costs_json) as f:
            report = json.load(f)
    else:
        report = costs.cost_report(root, profile=profile)
    if report.get("errors"):
        raise RuntimeError(
            "cost report has trace errors; fix those before gating: "
            + "; ".join(e["case"] for e in report["errors"]))
    return costs.ledger_metrics(report)


#: per-scenario SLO fields extracted from a SCENARIOS_<tag>.json doc
#: (``python -m apex_tpu.serving.scenarios --json``) as
#: ``scenario.<name>.<field>`` — each matches a direction class below
#: (``_ms`` relative band / ``miss_rate`` absolute ±``_RATE_ABS_TOL``)
_SCENARIO_FIELDS = ("ttft_ms_p95", "tpot_ms_p95", "deadline_miss_rate")

#: per-scenario ROUTER fields (the replicated-serving chaos/A-B tier,
#: docs/router.md): extracted from a report's ``router`` block as
#: ``scenario.<name>.<field>``. ``failover_recovered_rate`` and the
#: hit-rate pair gate on the absolute rate band; the delta is the
#: affinity-beats-round-robin proof (higher-better, rate band)
_SCENARIO_ROUTER_FIELDS = (
    "failover_recovered_rate",
    "affinity_hit_rate",
    # the A/B pair lives under the report's ``compare_round_robin``
    # sub-block, not the pinned ``ROUTER_FIELDS`` top level — the
    # extractor reads the merged block the scenario runner flattens
    # tpu-lint: disable=contract-ledger-class-drift -- A/B keys, see above
    "round_robin_hit_rate",
    # tpu-lint: disable=contract-ledger-class-drift -- A/B keys, see above
    "affinity_delta_hit_rate",
)

#: per-scenario HOST-TIER fields (the tiered KV pool's churn A/B,
#: docs/serving.md "Tiered KV pool"): extracted from a report's
#: ``host_tier`` block as ``scenario.<name>.<field>``. The hit-rate
#: trio and ``promote_hit_rate`` gate on the absolute rate band as
#: higher-better; ``tier_delta_hit_rate`` is the tier-beats-reprefill
#: proof (strictly positive at a thrash-sized pool)
_SCENARIO_HOST_TIER_FIELDS = ("tier_on_hit_rate", "tier_off_hit_rate",
                              "tier_delta_hit_rate", "promote_hit_rate")

#: per-scenario FLEET fields (the federated observability plane,
#: docs/observability.md "Fleet plane"): extracted from a report's
#: ``fleet`` block as ``scenario.<name>.fleet_<field>``. The latency
#: aggregates band-gate as ``_ms`` lower-better; the rest are
#: informational counters banked so the alerting/federation trajectory
#: stays reviewable per round
_SCENARIO_FLEET_FIELDS = (
    "ttft_ms_p95", "tpot_ms_p95",
    # the rest are deliberately informational (no gating class): raw
    # counters/levels whose healthy values depend on the scenario's
    # chaos schedule — banked for trajectory review, never gated
    # tpu-lint: disable=contract-ledger-class-drift -- informational, see above
    "queue_depth",
    # tpu-lint: disable=contract-ledger-class-drift -- informational counter
    "slo_burn", "compile_storms",
    # tpu-lint: disable=contract-ledger-class-drift -- informational counter
    "alerts_fired",
)

#: per-scenario HTTP fields (the over-the-wire chaos tier,
#: docs/http.md): extracted from a report's ``http`` block as
#: ``scenario.<name>.http_<field>``. Counters, so informational —
#: recorded in the banked trajectory (the spill/disconnect proof stays
#: reviewable per round) while the scenario's SLO percentiles above do
#: the band-gating
#: all five are chaos-schedule-shaped counters: informational by
#: design (the scenario's SLO percentiles do the band-gating) — banked
#: so the spill/disconnect proof stays reviewable per round
_SCENARIO_HTTP_FIELDS = (
    # tpu-lint: disable=contract-ledger-class-drift -- informational, see above
    "backpressure_spills", "disconnects",
    # tpu-lint: disable=contract-ledger-class-drift -- informational, see above
    "conn_reset_retries", "slow_reader_stalls",
    # tpu-lint: disable=contract-ledger-class-drift -- informational, see above
    "errors",
)

#: numeric bench-record fields worth tracking besides the headline value
_BENCH_FIELDS = (
    "step_ms", "int8_speedup", "step_savings",
    "gpt2_paged_decode_ttft_ms_p50", "gpt2_paged_decode_ttft_ms_p95",
    "decode_step_ms_p50", "decode_step_ms_p95",
    "gpt2_tp2_paged_decode_ttft_ms_p50",
    "gpt2_tp2_paged_decode_ttft_ms_p95",
    "gpt2_tp2_paged_decode_tpot_ms_p50",
    "gpt2_tp2_paged_decode_tpot_ms_p95",
    "gpt2_frontend_ttft_ms_p50", "gpt2_frontend_ttft_ms_p95",
    "gpt2_frontend_tpot_ms_p50", "gpt2_frontend_tpot_ms_p95",
    "gpt2_frontend_deadline_miss_rate", "prefix_hit_rate",
    "pump.bubble_ms",
    # tpu-lint: disable=contract-ledger-class-drift -- recompile count: trajectory only
    "jit.compiles",
    # ISSUE 13: in-engine speculative decode + chunked-prefill TTFT
    # tpu-lint: disable=contract-ledger-class-drift -- acceptance length: trajectory only
    "mean_acceptance_len",
    "gpt2_frontend_chunked_ttft_ms_p50", "gpt2_frontend_chunked_ttft_ms_p95",
    "gpt2_frontend_monolithic_ttft_ms_p50",
    "gpt2_frontend_monolithic_ttft_ms_p95",
    # ISSUE 16: quantized weight streaming (int8 policy, fused dequant)
    "gpt2_w8_paged_decode_ttft_ms_p50", "gpt2_w8_paged_decode_ttft_ms_p95",
    # tpu-lint: disable=contract-ledger-class-drift -- compression ratio: trajectory only
    "weight_bytes_ratio_vs_fp",
    # ISSUE 17: tiered KV pool (host-RAM spill under the device pool)
    # tpu-lint: disable=contract-ledger-class-drift -- churn counters: trajectory only
    "host_tier_demotes", "host_tier_promotes",
    "host_tier_promote_hit_rate",
)


def _scenario_metrics(doc: dict) -> Dict[str, float]:
    """Flatten a scenarios document's aggregate SLO fields into ledger
    metrics (``scenario.<name>.ttft_ms_p95`` etc.)."""
    out: Dict[str, float] = {}
    for name, rep in sorted(doc.get("scenarios", {}).items()):
        agg = rep.get("aggregate", {}) if isinstance(rep, dict) else {}
        for field in _SCENARIO_FIELDS:
            v = agg.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"scenario.{name}.{field}"] = float(v)
        router = rep.get("router", {}) if isinstance(rep, dict) else {}
        for field in _SCENARIO_ROUTER_FIELDS:
            v = router.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"scenario.{name}.{field}"] = float(v)
        tier = rep.get("host_tier", {}) if isinstance(rep, dict) else {}
        for field in _SCENARIO_HOST_TIER_FIELDS:
            v = tier.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"scenario.{name}.{field}"] = float(v)
        fleet = rep.get("fleet", {}) if isinstance(rep, dict) else {}
        for field in _SCENARIO_FLEET_FIELDS:
            v = fleet.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"scenario.{name}.fleet_{field}"] = float(v)
        http = rep.get("http", {}) if isinstance(rep, dict) else {}
        for field in _SCENARIO_HTTP_FIELDS:
            v = http.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"scenario.{name}.http_{field}"] = float(v)
    return out


def bench_metrics_from_file(path) -> Tuple[Dict[str, float], dict]:
    """Extract (metrics, meta) from a bench artifact. Accepts the
    driver's wrapper shape (``BENCH_r0*.json``: one object with a
    ``parsed`` record), a bare record, JSONL of records
    (``DECODE_*.json``), or a scenarios document
    (``SCENARIOS_*.json`` — per-scenario SLO fields, see
    ``_SCENARIO_FIELDS``)."""
    text = Path(path).read_text().strip()
    records: List[dict] = []
    meta: dict = {"source": os.path.basename(str(path))}
    try:
        doc = json.loads(text)
        if (isinstance(doc, dict)
                and str(doc.get("schema", "")).startswith(
                    "apex-tpu/scenarios")):
            meta["schema"] = doc["schema"]
            return _scenario_metrics(doc), meta
        if isinstance(doc, dict) and "parsed" in doc:
            meta["rc"] = doc.get("rc")
            if isinstance(doc.get("parsed"), dict):
                records = [doc["parsed"]]
        elif isinstance(doc, dict):
            records = [doc]
    except json.JSONDecodeError:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                records.append(rec)
    out: Dict[str, float] = {}
    errors = []
    for rec in records:
        name = rec.get("metric")
        if name and isinstance(rec.get("value"), (int, float)):
            out[name] = float(rec["value"])
        if rec.get("error"):
            errors.append(str(rec["error"])[:200])
        for field in _BENCH_FIELDS:
            v = rec.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[field] = float(v)
    if errors:
        meta["errors"] = errors
    return out, meta


# --------------------------------------------------------------------------
# the gate
# --------------------------------------------------------------------------

@dataclasses.dataclass
class Regression:
    metric: str
    baseline: float
    head: float
    kind: str                         # "exact-drift" | "band"
    baseline_tag: str

    def __str__(self):
        return (f"{self.metric}: {self.baseline} -> {self.head} "
                f"[{self.kind}, baseline {self.baseline_tag}]")


def _direction(name: str) -> Optional[str]:
    if any(s in name for s in _HIGHER_BETTER):
        return "higher"
    if any(s in name for s in _LOWER_BETTER):
        return "lower"
    return None


def check(head: Dict[str, float], entries: List[dict], *,
          band_pct: float = 20.0) -> List[Regression]:
    """Compare HEAD metrics against the most recent ledger value of
    EACH metric, scanning the whole history — cost entries append every
    round (deliberately, even with the tunnel dead), so a fixed entry
    window would age the bench metrics out of the baseline and silently
    stop gating them. Only metrics present on BOTH sides gate — a newly
    added metric passes, a retired one is the next append's business."""
    baseline: Dict[str, Tuple[float, str]] = {}
    for entry in entries:            # oldest -> newest: newest wins
        tag = f"{entry.get('tag', '?')}@{entry.get('git_rev', '?')[:12]}"
        for name, value in entry.get("metrics", {}).items():
            if isinstance(value, (int, float)):
                baseline[name] = (float(value), tag)
    out: List[Regression] = []
    for name, head_v in sorted(head.items()):
        if name not in baseline:
            continue
        base_v, tag = baseline[name]
        if name.startswith("cost."):
            # deterministic: any drift is a (possibly intentional)
            # change that must be appended, i.e. reviewed
            if head_v != base_v:
                out.append(Regression(name, base_v, head_v,
                                      "exact-drift", tag))
            continue
        direction = _direction(name)
        if direction is None:
            continue                 # informational counter
        worse = (base_v - head_v) if direction == "higher" \
            else (head_v - base_v)
        if name.endswith(_RATE_SUFFIXES):
            # quantized [0,1] ratio: absolute tolerance, not relative —
            # and checked BEFORE the zero-baseline skip, because a 0.0
            # miss-rate baseline is a healthy perfect score that must
            # keep gating (the zero skip exists for dead-round seeds,
            # which record throughputs, not rates; a 0.0 higher-better
            # rate can never flag anyway since worse = -head <= 0)
            if worse > _RATE_ABS_TOL:
                out.append(Regression(name, base_v, head_v, "band", tag))
            continue
        if base_v == 0.0:
            continue                 # dead baseline (failed-round seed)
        if worse > abs(base_v) * band_pct / 100.0:
            out.append(Regression(name, base_v, head_v, "band", tag))
    return out


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _seed_history(root: Path, path: Path) -> int:
    """Backfill the ledger from the banked round artifacts
    (``BENCH_r0*.json`` wrappers; failed rounds land with value 0.0 and
    their error in meta — an honest record of the empty stretch).
    Idempotent: a round whose seed entry already exists is skipped, so
    re-running cannot duplicate the committed trajectory."""
    seeded = set()
    if path.exists():
        seeded = {(e.get("kind"), e.get("tag")) for e in load(path)}
    n = 0
    for bench in sorted(_glob.glob(str(root / "BENCH_r[0-9]*.json"))):
        base = os.path.basename(bench)
        tag = base[len("BENCH_"):].split(".")[0].split("_")[0]
        if ("seed", tag) in seeded:
            continue
        metrics, meta = bench_metrics_from_file(bench)
        if not metrics:
            continue
        append_entry(path, kind="seed", tag=tag, metrics=metrics,
                     root=root, meta=meta,
                     when=os.path.getmtime(bench))
        n += 1
    return n


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.obs.ledger",
        description="Perf ledger: append round entries, gate HEAD "
                    "against the trajectory (docs/observability.md)")
    parser.add_argument("--root", default=None)
    parser.add_argument("--ledger", default=None,
                        help=f"path (default <root>/{LEDGER_NAME})")
    parser.add_argument("--tag", default="head")
    parser.add_argument("--costs", default=None, metavar="JSON",
                        help="pre-computed obs.costs --json report")
    parser.add_argument("--bench", default=None, metavar="JSON",
                        help="bench/decode artifact to extract metrics "
                             "from")
    parser.add_argument("--profile", default="v5e")
    parser.add_argument("--band-pct", type=float, default=20.0)
    action = parser.add_mutually_exclusive_group(required=True)
    action.add_argument("--check", action="store_true",
                        help="exit 1 if HEAD regressed vs the ledger")
    action.add_argument("--append", action="store_true",
                        help="append HEAD's entry (cost metrics, plus "
                             "--bench fields when given)")
    action.add_argument("--seed-history", action="store_true",
                        help="backfill from banked BENCH_r0*.json")
    action.add_argument("--show", action="store_true")
    args = parser.parse_args(argv)

    root = Path(args.root).resolve() if args.root \
        else Path(__file__).resolve().parents[2]
    path = Path(args.ledger) if args.ledger else root / LEDGER_NAME

    if args.seed_history:
        n = _seed_history(root, path)
        print(f"[ledger] seeded {n} historical entries into {path}")
        return 0

    if args.show:
        try:
            entries = load(path)
        except (OSError, ValueError) as e:
            print(f"[ledger] {e}")
            return 2
        for entry in entries:
            named = entry.get("metrics", {})
            print(f"{entry.get('tag'):>6s} {entry.get('kind'):>5s} "
                  f"{entry.get('git_rev', '')[:12]:12s} "
                  f"{len(named)} metrics")
        return 0

    if args.append:
        try:
            if args.bench:
                metrics, meta = bench_metrics_from_file(args.bench)
                entry = append_entry(path, kind="bench", tag=args.tag,
                                     metrics=metrics, root=root,
                                     meta=meta)
            else:
                metrics = head_cost_metrics(root, costs_json=args.costs,
                                            profile=args.profile)
                entry = append_entry(path, kind="cost", tag=args.tag,
                                     metrics=metrics, root=root)
        except (OSError, ValueError, RuntimeError,
                json.JSONDecodeError) as e:
            print(f"[ledger] append failed: {e}")
            return 2
        print(f"[ledger] appended {entry['kind']} entry "
              f"({len(entry['metrics'])} metrics) as {entry['git_rev']}")
        return 0

    # --check
    if not path.exists():
        print(f"[ledger] {path} missing — the perf trajectory is empty. "
              f"Seed it: python -m apex_tpu.obs.ledger --seed-history "
              f"&& ... --append")
        return 2
    try:
        entries = load(path)
    except ValueError as e:
        print(f"[ledger] {e}")
        return 2
    if not entries:
        print(f"[ledger] {path} is empty — append an entry first")
        return 2
    try:
        head = head_cost_metrics(root, costs_json=args.costs,
                                 profile=args.profile)
        if args.bench:
            bench, _ = bench_metrics_from_file(args.bench)
            head.update(bench)
    except (OSError, ValueError, RuntimeError,
            json.JSONDecodeError) as e:
        print(f"[ledger] cannot compute HEAD metrics: {e}")
        return 2
    regressions = check(head, entries, band_pct=args.band_pct)
    if regressions:
        print(f"[ledger] {len(regressions)} regression(s) vs "
              f"{path.name}:")
        for r in regressions:
            print(f"  {r}")
        print("[ledger] if intentional, append + commit the new entry: "
              "python -m apex_tpu.obs.ledger --append --tag <tag>")
        return 1
    print(f"[ledger] OK — {len(head)} HEAD metrics checked against "
          f"{len(entries)} entries, no regressions")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
