"""Whole-repo interprocedural linking for tpu-lint.

:class:`ProjectIndex` upgrades the per-module analysis to one graph over
the entire scanned surface, in the classic two-phase shape:

1. **index** — every file is parsed into a
   :class:`~apex_tpu.analysis.walker.ModuleIndex` (the caller does this;
   each module records its import table, its dotted call references, and
   the jit/scan/pallas callee marks it could not resolve locally);
2. **link** — imports are resolved to their defining modules
   (``from apex_tpu.serving import kv_pool`` / ``apex_tpu.utils.metrics``
   attribute chains / ``__init__`` re-export hops), unresolved jit-entry
   marks land on their real targets, and jit reachability is recomputed
   over the GLOBAL call graph and written back into each module.

The payoff is that module rules see through helpers imported from other
files with no per-rule changes: ``host-sync-in-jit`` flags an
``np.asarray`` inside a ``utils/`` helper the serving scheduler's jitted
scan body calls, and ``jit-donated-reuse`` tracks buffers donated to a
jit wrapper *imported* from another module (the home module's
``donate_argnums`` travel with the name, via ``extra_wrappers``).

Like the walker, linking is purely syntactic — nothing is imported or
executed; an unresolvable reference simply contributes no edge.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from apex_tpu.analysis.walker import FunctionInfo, ModuleIndex

#: import-following depth bound: re-export chains in this repo are 1-2
#: hops (``serving/__init__`` -> ``scheduler``); 8 is generous and keeps
#: accidental cycles (``a`` re-exporting from ``b`` and vice versa) finite
_MAX_HOPS = 8


def module_name_of(rel_path: str) -> Optional[str]:
    """``apex_tpu/serving/kv_pool.py`` -> ``apex_tpu.serving.kv_pool``;
    package ``__init__.py`` files name the package itself; repo-root
    drivers (``tpu_aot.py``) are top-level modules."""
    if not rel_path.endswith(".py"):
        return None
    parts = rel_path[:-3].replace("\\", "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts or not all(p.isidentifier() for p in parts):
        return None
    return ".".join(parts)


class ProjectIndex:
    """Cross-module linker over one scan's ModuleIndexes (phase 2)."""

    def __init__(self, modules: Dict[str, ModuleIndex]):
        #: rel posix path -> module index (phase-1 output)
        self.modules = modules
        self.by_module: Dict[str, ModuleIndex] = {}
        for rel, mi in modules.items():
            mn = module_name_of(rel)
            if mn:
                self.by_module[mn] = mi
        #: id(mi) -> local name -> absolute dotted target
        self._abs: Dict[int, Dict[str, str]] = {}

    # ------------------------------------------------------------- phase 2

    def link(self) -> None:
        """Resolve imports, apply cross-module jit-entry marks, recompute
        global reachability (written back into each ``mi.reachable``),
        and share jit wrappers with their importers."""
        for mi in self.modules.values():
            self._abs[id(mi)] = self._absolute_imports(mi)
        self._apply_unresolved_marks()
        self._propagate_reachability()
        self._share_wrappers()

    def _absolute_imports(self, mi: ModuleIndex) -> Dict[str, str]:
        out: Dict[str, str] = {}
        mn = module_name_of(mi.path)
        pkg_parts: List[str] = []
        if mn:
            parts = mn.split(".")
            # the package CONTEXT relative imports resolve against:
            # a package's __init__ is the package itself
            pkg_parts = parts if mi.path.endswith("__init__.py") \
                else parts[:-1]
        for ent in mi.imports:
            if ent.level:
                if ent.level - 1 > len(pkg_parts):
                    continue                    # escapes the scanned tree
                base = pkg_parts[:len(pkg_parts) - (ent.level - 1)]
                target = ".".join(base + ([ent.module] if ent.module
                                          else []))
            else:
                target = ent.module
            if ent.attr:
                target = f"{target}.{ent.attr}" if target else ent.attr
            if target:
                out[ent.local] = target
        return out

    def _resolve_chain(self, mi: ModuleIndex, ref: str, hops: int = 0
                       ) -> Optional[Tuple[ModuleIndex, str]]:
        """Follow ``ref`` (a dotted name as written in ``mi``) through
        import bindings to its defining module: returns ``(module,
        attr-path within it)`` or None. Re-exports (``__init__`` modules
        importing a name from the implementation module) are followed up
        to ``_MAX_HOPS``."""
        if hops > _MAX_HOPS or not ref:
            return None
        parts = ref.split(".")
        amap = self._abs.get(id(mi), {})
        if parts[0] in amap:
            rest = parts[1:]
            abs_ref = amap[parts[0]] + ("." + ".".join(rest) if rest
                                        else "")
        else:
            abs_ref = ref
        aparts = abs_ref.split(".")
        for cut in range(len(aparts) - 1, 0, -1):
            m2 = self.by_module.get(".".join(aparts[:cut]))
            if m2 is None:
                continue
            attr = ".".join(aparts[cut:])
            head = aparts[cut]
            amap2 = self._abs.get(id(m2), {})
            if head in amap2 and head not in m2.functions:
                # re-exported: keep following in the binding module
                return self._resolve_chain(m2, attr, hops + 1)
            return (m2, attr)
        return None

    def resolve_function(self, mi: ModuleIndex, ref: str
                         ) -> Optional[Tuple[ModuleIndex, FunctionInfo]]:
        chain = self._resolve_chain(mi, ref)
        if chain is None:
            return None
        m2, attr = chain
        # ``attr`` is a qualname within m2: a top-level function, or an
        # exact ``Class.method`` path — anything else contributes no edge
        info = m2.functions.get(attr)
        return (m2, info) if info is not None else None

    # ----------------------------------------------------- reachability

    def _apply_unresolved_marks(self) -> None:
        for mi in self.modules.values():
            for ref, reason in mi.unresolved_marks:
                hit = self.resolve_function(mi, ref)
                if hit is None:
                    continue
                _, info = hit
                tagged = f"{reason} (from {mi.path})"
                if tagged not in info.jit_reasons:
                    info.jit_reasons.append(tagged)

    def _propagate_reachability(self) -> None:
        """Global BFS from every jit entry; REPLACES each module's
        ``reachable`` with the interprocedural result (a superset of the
        module-local one: local edges are a subset of global edges)."""
        reach: Dict[Tuple[int, str], List[str]] = {}
        work: List[Tuple[ModuleIndex, str, List[str]]] = []
        for mi in self.modules.values():
            for qn, info in mi.functions.items():
                if info.jit_reasons:
                    reach[(id(mi), qn)] = list(info.jit_reasons)
                    work.append((mi, qn, reach[(id(mi), qn)]))
        while work:
            mi, qn, chain = work.pop()
            nxt: List[Tuple[ModuleIndex, str]] = []
            for tail in mi._calls.get(qn, ()):
                for info in mi.by_name.get(tail, ()):
                    nxt.append((mi, info.qualname))
            for sub, info in mi.functions.items():
                if info.parent == qn:
                    nxt.append((mi, sub))
            for ref in mi.calls_dotted.get(qn, ()):
                hit = self.resolve_function(mi, ref)
                if hit is not None:
                    nxt.append((hit[0], hit[1].qualname))
            for m2, qn2 in nxt:
                if m2.functions[qn2].host_boundary:
                    continue     # declared never-traced: edge stops here
                key = (id(m2), qn2)
                if key not in reach:
                    hop = qn if m2 is mi else f"{mi.path}::{qn}"
                    reach[key] = chain + [f"called from {hop}"]
                    work.append((m2, qn2, reach[key]))
        by_id = {id(mi): mi for mi in self.modules.values()}
        fresh: Dict[int, Dict[str, List[str]]] = {id(mi): {}
                                                  for mi in by_id.values()}
        for (mid, qn), chain in reach.items():
            fresh[mid][qn] = chain
        for mid, mi in by_id.items():
            mi.reachable = fresh[mid]

    # --------------------------------------------------------- wrappers

    def _share_wrappers(self) -> None:
        """Give every importer of a jit wrapper (``w = jax.jit(f,
        donate_argnums=...)`` in another module) the home module's
        wrapper info under the IMPORTING name, so ``jit-donated-reuse``
        and ``jit-unhashable-static`` judge call sites through the
        import."""
        from apex_tpu.analysis.rules import _jit_wrappers

        home: Dict[int, dict] = {id(mi): _jit_wrappers(mi, local_only=True)
                                 for mi in self.modules.values()}
        for mi in self.modules.values():
            for local in self._abs.get(id(mi), {}):
                if local in mi.by_name:
                    continue                     # locally shadowed
                chain = self._resolve_chain(mi, local)
                if chain is None:
                    continue
                m2, attr = chain
                info = home[id(m2)].get(attr)
                if info is not None:
                    mi.extra_wrappers[local] = info
