"""Inline suppressions: ``# tpu-lint: disable=rule-a,rule-b -- why``.

A suppression applies to findings whose node overlaps the comment's
line, or — when the comment stands alone on its own line — to the next
line (the conventional "decorate the statement above it" form).
``disable=all`` silences every rule on that line; use sparingly.

Parsing is deliberately strict about where rules end and prose begins:
the rule list stops at ``--`` (everything after is the justification),
and a comma-separated token only counts as a rule name when it is a
single word — ``disable=rule -- wrong call, all good here`` must not
quietly become ``disable=all``. Pragmas are read from real COMMENT
tokens (via ``tokenize``), so pragma-shaped text inside a string
literal or docstring is inert.

The repo convention (ISSUE 3) is that an *intentional* finding gets an
inline suppression **with** a one-line justification, while only
justified legacy debt goes in the baseline file.
"""

from __future__ import annotations

import io
import re
import tokenize
from typing import Dict, Set

from apex_tpu.analysis.walker import Finding

_PRAGMA = re.compile(r"#\s*tpu-lint:\s*disable=([a-zA-Z0-9_,\- ]+)")


def _parse_rules(spec: str) -> Set[str]:
    rules: Set[str] = set()
    spec = spec.split("--")[0]          # "-- why" is justification
    for tok in spec.split(","):
        words = tok.split()
        if len(words) == 1:             # multi-word token = prose, skip
            rules.add(words[0])
    return rules


class Suppressions:
    """Per-file map of line number -> suppressed rule names."""

    def __init__(self, source: str):
        self.by_line: Dict[int, Set[str]] = {}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return      # unparseable files already carry a parse-error
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA.search(tok.string)
            if not m:
                continue
            rules = _parse_rules(m.group(1))
            if not rules:
                continue
            line = tok.start[0]
            self.by_line.setdefault(line, set()).update(rules)
            if not tok.line[:tok.start[1]].strip():
                # comment-only line: also covers the following line
                self.by_line.setdefault(line + 1, set()).update(rules)

    def covers(self, finding: Finding) -> bool:
        for line in range(finding.line, finding.end_line + 1):
            rules = self.by_line.get(line)
            if rules and (finding.rule in rules or "all" in rules):
                return True
        return False
