"""Shared rule-namespace registry for tpu-lint's tiers.

The five tiers (AST, jaxpr IR, host-concurrency, memory-budget,
wire/observability contracts) share one CLI, one suppression-pragma
syntax, and one baseline file; what keeps them from clobbering each
other's recorded debt is the RULE NAMESPACE: ``ir-*`` rules belong to
the IR tier, ``conc-*`` to the concurrency tier, ``mem-*`` to the
memory tier, ``contract-*`` to the contract tier, and everything else
to the AST tier. This module is the single place that
mapping lives — ``cli.py``'s tier-partitioned ``--write-baseline`` and
any future consumer derive a rule's tier from here instead of
re-implementing per-tier string checks (which is how the IR tier's
``startswith("ir-")`` special case would have silently mis-filed
``conc-*`` keys).
"""

from __future__ import annotations

#: prefix -> tier name, longest-prefix-first if that ever matters.
#: Adding a tier = adding one entry here; the baseline partitioning,
#: ``--list-rules`` grouping, and tests pick it up automatically.
TIER_PREFIXES = (
    ("ir-", "ir"),
    ("conc-", "conc"),
    ("mem-", "mem"),
    ("contract-", "contract"),
)

AST_TIER = "ast"


def tier_of(rule_name: str) -> str:
    """The tier a rule name belongs to (``ast`` when no prefix claims
    it — the AST tier owns the unprefixed namespace)."""
    for prefix, tier in TIER_PREFIXES:
        if rule_name.startswith(prefix):
            return tier
    return AST_TIER


def tier_of_key(baseline_key: str) -> str:
    """Tier of a baseline key (``path::rule::scope``); keys without a
    rule component count as AST (legacy shape, pre-tier)."""
    parts = baseline_key.split("::")
    return tier_of(parts[1]) if len(parts) > 2 else AST_TIER
