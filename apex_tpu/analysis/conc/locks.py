"""Lockset machinery for the tpu-lint concurrency tier.

:class:`ConcModel` is the shared fact base every ``conc-*`` rule reads:

- **lock registry** — ``self.X = threading.Lock()/RLock()`` in a class
  body and module-level ``NAME = threading.Lock()`` assignments become
  named locks (reentrancy recorded); sibling sync primitives
  (``queue.Queue``, ``threading.Event``/``Condition``/``Thread``) are
  classified so rules can tell a blocking ``q.get()`` from ``dict.get``
  and never treat a lock attribute as a data field;
- **locksets** — ``with self._lock:`` nesting plus linear
  ``acquire()``..``release()`` spans give every AST node in a function
  the set of locks lexically held there; a cross-function fixpoint
  (entry lockset of ``f`` = intersection over ``f``'s call sites of the
  locks held at each) extends that interprocedurally, so a helper only
  ever called under the metrics registry lock counts as guarded;
- **acquisition events** — every lock acquisition with the set held at
  that moment, the raw material for the acquires-while-holding order
  graph and the double-acquire check;
- **field accesses** — every ``self.ATTR`` read/write outside
  ``__init__``, keyed ``(module, class, attr)`` with its effective
  lockset, feeding the Eraser-style GuardedBy inference.

Like the AST tier, everything here is purely syntactic over
:class:`~apex_tpu.analysis.walker.ModuleIndex` objects — nothing is
imported or executed, and unresolvable references simply contribute no
fact (precision over recall, per the tier-1 design bias).
"""

from __future__ import annotations

import ast
import dataclasses
from collections import Counter
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from apex_tpu.analysis.project import ProjectIndex
from apex_tpu.analysis.walker import (ModuleIndex, call_name, name_tail,
                                      unwrap_partial, walk_shallow)

#: constructor tails -> sync-primitive kind. A ``self.X = <ctor>()``
#: assignment anywhere in a class registers the attribute's kind; kinds
#: other than "lock"/"rlock" exist so rules can classify receivers
#: (blocking ``.get``/``.wait``/``.join``) and exclude sync primitives
#: from the shared-field analysis (they synchronize themselves).
_CTOR_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Queue": "queue",
    "LifoQueue": "queue",
    "PriorityQueue": "queue",
    "SimpleQueue": "queue",
    "Event": "event",
    "Condition": "condition",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
    "Thread": "thread",
    "Timer": "thread",
}

#: asyncio's same-named primitives are a DIFFERENT color: they suspend
#: the awaiting task, never a thread, so they must not enter the lock
#: registry (an ``async with asyncio.Lock()`` can never guard a field
#: against the pump thread, and treating it as a threading lock would
#: both manufacture false guards and hide real await-under-lock bugs).
#: They still classify as sync kinds so the shared-field analysis skips
#: them — they synchronize their tasks, just not across threads.
_ASYNC_CTOR_KINDS = {
    "Lock": "alock",
    "Event": "aevent",
    "Condition": "acondition",
    "Semaphore": "asemaphore",
    "BoundedSemaphore": "asemaphore",
    "Queue": "aqueue",
    "LifoQueue": "aqueue",
    "PriorityQueue": "aqueue",
}

SYNC_KINDS = (frozenset(_CTOR_KINDS.values())
              | frozenset(_ASYNC_CTOR_KINDS.values()))


@dataclasses.dataclass(frozen=True)
class LockKey:
    """Identity of one lock object: a class attribute (``owner`` is the
    class qualname) or a module global (``owner`` is None)."""

    module: str                 # rel posix path of the defining module
    owner: Optional[str]        # class qualname, or None for a global
    attr: str                   # attribute / global name
    reentrant: bool = dataclasses.field(default=False, compare=False)

    def display(self) -> str:
        base = f"{self.owner}.{self.attr}" if self.owner else self.attr
        return base


@dataclasses.dataclass(frozen=True)
class FuncKey:
    module: str
    qualname: str


@dataclasses.dataclass
class FieldAccess:
    """One ``self.ATTR`` access site outside ``__init__``."""

    field: Tuple[str, str, str]      # (module, class, attr)
    func: FuncKey
    node: ast.AST
    write: bool
    locks: FrozenSet[LockKey] = frozenset()   # effective (local + entry)


@dataclasses.dataclass
class Acquisition:
    lock: LockKey
    held: FrozenSet[LockKey]         # locks held when this one is taken
    node: ast.AST
    func: FuncKey


class _FuncCtx:
    """Per-function analysis context."""

    __slots__ = ("key", "mi", "info", "owner_class", "self_name",
                 "node_locks", "acquisitions", "parents")

    def __init__(self, key, mi, info, owner_class, self_name):
        self.key = key
        self.mi = mi
        self.info = info
        self.owner_class = owner_class      # class qualname or None
        self.self_name = self_name          # "self" param name or None
        self.node_locks: Dict[int, FrozenSet[LockKey]] = {}
        self.acquisitions: List[Acquisition] = []
        self.parents: Dict[int, ast.AST] = {}


def _class_qualnames(tree: ast.AST) -> Dict[str, ast.ClassDef]:
    """Class qualnames mirroring ModuleIndex's function-qualname scheme
    (``Outer.Inner`` for nested classes, classes inside functions keep
    the function prefix)."""
    out: Dict[str, ast.ClassDef] = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                qn = f"{prefix}{child.name}" if prefix else child.name
                out[qn] = child
                visit(child, qn + ".")
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _is_sync_ctor(node: ast.AST) -> Optional[str]:
    """Kind string when ``node`` is a recognized sync-primitive
    constructor call (``threading.Lock()``, ``queue.Queue()``, bare
    ``Lock()`` after a from-import), else None."""
    if not isinstance(node, ast.Call):
        return None
    cn = call_name(node)
    if cn is None:
        return None
    parts = cn.split(".")
    if len(parts) >= 2 and parts[-2] == "asyncio":
        # asyncio.Lock() et al: the task-colored kinds (see
        # _ASYNC_CTOR_KINDS) — never threading locks. A bare `Lock()`
        # after a from-import keeps the threading reading (syntactic
        # tier: precision over recall; the repo spells asyncio dotted).
        return _ASYNC_CTOR_KINDS.get(parts[-1])
    return _CTOR_KINDS.get(parts[-1])


class ConcModel:
    """Whole-surface concurrency fact base (see module docstring)."""

    def __init__(self, modules: Dict[str, ModuleIndex],
                 project: Optional[ProjectIndex] = None):
        self.modules = modules
        self.project = project if project is not None \
            else ProjectIndex(modules)
        if project is None:
            self.project.link()
        #: (module, class-or-None, attr) -> kind from SYNC_KINDS
        self.attr_kinds: Dict[Tuple[str, Optional[str], str], str] = {}
        #: registered locks by the same key
        self.locks: Dict[Tuple[str, Optional[str], str], LockKey] = {}
        self.funcs: Dict[FuncKey, _FuncCtx] = {}
        #: caller -> [(call node, callee FuncKey)]
        self.call_edges: Dict[FuncKey, List[Tuple[ast.AST, FuncKey]]] = {}
        self.entry_locks: Dict[FuncKey, FrozenSet[LockKey]] = {}
        self.accesses: List[FieldAccess] = []
        #: FuncKey -> thread-root names reaching it (threads.py fills it)
        self.colors: Dict[FuncKey, FrozenSet[str]] = {}

        self._index()
        self._register_sync_attrs()
        self._compute_locksets()
        self._collect_call_edges()
        self._entry_fixpoint()
        self._collect_field_accesses()

    # ------------------------------------------------------------ indexing

    def _index(self) -> None:
        self._classes: Dict[str, Dict[str, ast.ClassDef]] = {}
        for rel, mi in self.modules.items():
            classes = _class_qualnames(mi.tree)
            self._classes[rel] = classes
            for qn, info in mi.functions.items():
                owner = self._owning_class(classes, qn)
                self_name = None
                if owner is not None:
                    # the shallowest function under the class prefix is
                    # the method whose first param names the instance;
                    # nested defs close over the same name
                    method_qn = qn[:len(owner) + 1] \
                        + qn[len(owner) + 1:].split(".")[0]
                    method = mi.functions.get(method_qn)
                    if method is not None and method.params:
                        first = method.params[0]
                        if first in ("self", "cls"):
                            self_name = first
                key = FuncKey(rel, qn)
                self.funcs[key] = _FuncCtx(key, mi, info, owner, self_name)

    @staticmethod
    def _owning_class(classes: Dict[str, ast.ClassDef],
                      qualname: str) -> Optional[str]:
        best = None
        for cqn in classes:
            if qualname.startswith(cqn + ".") \
                    and (best is None or len(cqn) > len(best)):
                best = cqn
        return best

    def _register_sync_attrs(self) -> None:
        """``self.X = <sync ctor>()`` in any method registers
        ``(module, class, X)``; module-level ``NAME = <sync ctor>()``
        registers ``(module, None, NAME)``. Locks/RLocks additionally
        enter the lock registry with their reentrancy."""
        def register(module, owner, attr, kind):
            fkey = (module, owner, attr)
            self.attr_kinds.setdefault(fkey, kind)
            if kind in ("lock", "rlock"):
                self.locks.setdefault(
                    fkey, LockKey(module, owner, attr,
                                  reentrant=(kind == "rlock")))

        for rel, mi in self.modules.items():
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Assign) \
                        or len(node.targets) != 1:
                    continue
                kind = _is_sync_ctor(node.value)
                if kind is None:
                    continue
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    # module-level global (assignments inside functions
                    # are rule conc-useless-local-lock's business)
                    if mi.scope_of(tgt) == "<module>":
                        register(rel, None, tgt.id, kind)
                elif isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name):
                    info = mi.enclosing_function(tgt)
                    if info is None:
                        continue
                    key = FuncKey(rel, info.qualname)
                    ctx = self.funcs.get(key)
                    if ctx is None or ctx.owner_class is None:
                        continue
                    if tgt.value.id == (ctx.self_name or "self"):
                        register(rel, ctx.owner_class, tgt.attr, kind)

    # -------------------------------------------------------- lock lookup

    def attr_kind(self, ctx: "_FuncCtx", expr: ast.AST) -> Optional[str]:
        """Sync-primitive kind of ``expr`` when it resolves to a
        registered attribute/global of this function's view, else None."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and ctx.self_name is not None \
                and expr.value.id == ctx.self_name:
            return self.attr_kinds.get(
                (ctx.key.module, ctx.owner_class, expr.attr))
        if isinstance(expr, ast.Name):
            return self.attr_kinds.get((ctx.key.module, None, expr.id))
        return None

    def resolve_lock(self, ctx: "_FuncCtx",
                     expr: ast.AST) -> Optional[LockKey]:
        """``self._lock`` / module-global ``_LOCK`` -> its LockKey."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and ctx.self_name is not None \
                and expr.value.id == ctx.self_name:
            return self.locks.get(
                (ctx.key.module, ctx.owner_class, expr.attr))
        if isinstance(expr, ast.Name):
            return self.locks.get((ctx.key.module, None, expr.id))
        return None

    # ----------------------------------------------------------- locksets

    def _compute_locksets(self) -> None:
        for ctx in self.funcs.values():
            self._lockset_of(ctx)

    def _lockset_of(self, ctx: "_FuncCtx") -> None:
        """Per-node LOCAL locksets + acquisition events for one function.
        ``with`` nesting is exact; manual ``acquire()``/``release()`` is
        tracked linearly within each statement block (good enough for
        the straight-line spans the repo and fixtures use)."""

        def mark(node: ast.AST, held: FrozenSet[LockKey]) -> None:
            # walk_shallow: nested defs get their own locksets (their
            # bodies run when CALLED, not under this lexical lock);
            # lambdas inherit (they usually run in place, e.g. sort keys)
            ctx.node_locks[id(node)] = held
            for sub in walk_shallow(node):
                ctx.node_locks[id(sub)] = held

        def manual_ops(stmt: ast.stmt) -> Iterator[Tuple[str, LockKey,
                                                         ast.AST]]:
            for sub in walk_shallow(stmt):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in ("acquire", "release"):
                    lk = self.resolve_lock(ctx, sub.func.value)
                    if lk is not None:
                        yield sub.func.attr, lk, sub

        def visit_block(stmts: List[ast.stmt],
                        held: FrozenSet[LockKey]) -> None:
            extra: FrozenSet[LockKey] = frozenset()
            for stmt in stmts:
                eff = held | extra
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    inner = eff
                    for item in stmt.items:
                        mark(item.context_expr, inner)
                        if item.optional_vars is not None:
                            mark(item.optional_vars, inner)
                        lk = self.resolve_lock(ctx, item.context_expr)
                        if lk is not None:
                            ctx.acquisitions.append(Acquisition(
                                lk, inner, item.context_expr, ctx.key))
                            inner = inner | {lk}
                    visit_block(stmt.body, inner)
                elif isinstance(stmt, (ast.If, ast.While)):
                    mark(stmt.test, eff)
                    visit_block(stmt.body, eff)
                    visit_block(stmt.orelse, eff)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    mark(stmt.target, eff)
                    mark(stmt.iter, eff)
                    visit_block(stmt.body, eff)
                    visit_block(stmt.orelse, eff)
                elif isinstance(stmt, ast.Try):
                    visit_block(stmt.body, eff)
                    for h in stmt.handlers:
                        if h.type is not None:
                            mark(h.type, eff)
                        visit_block(h.body, eff)
                    visit_block(stmt.orelse, eff)
                    visit_block(stmt.finalbody, eff)
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.ClassDef)):
                    for dec in getattr(stmt, "decorator_list", ()):
                        mark(dec, eff)
                else:
                    mark(stmt, eff)
                    for op, lk, node in manual_ops(stmt):
                        if op == "acquire":
                            ctx.acquisitions.append(Acquisition(
                                lk, eff, node, ctx.key))
                            extra = extra | {lk}
                        else:
                            extra = extra - {lk}

        body = getattr(ctx.info.node, "body", [])
        visit_block(body, frozenset())
        # parent map for the field-access classifier
        for node in walk_shallow(ctx.info.node):
            for child in ast.iter_child_nodes(node):
                ctx.parents[id(child)] = node

    def local_locks(self, func: FuncKey, node: ast.AST) -> FrozenSet[LockKey]:
        ctx = self.funcs.get(func)
        if ctx is None:
            return frozenset()
        return ctx.node_locks.get(id(node), frozenset())

    def effective_locks(self, func: FuncKey,
                        node: ast.AST) -> FrozenSet[LockKey]:
        return (self.local_locks(func, node)
                | self.entry_locks.get(func, frozenset()))

    # --------------------------------------------------------- call graph

    #: method names every builtin collection/string also has — an attr
    #: call through one of these on a non-self receiver is far more
    #: likely ``dict.update``/``list.pop`` than a module method of the
    #: same name, and a wrong edge here poisons the entry-lock fixpoint
    #: (``entry.update(...)`` under the metrics lock must not make
    #: ``AverageMeter.update`` look lock-guarded)
    _COLLECTION_METHODS = frozenset(
        n for t in (dict, list, set, frozenset, str, bytes, tuple)
        for n in dir(t) if not n.startswith("__"))

    def _resolve_callees(self, mi: ModuleIndex, callee: ast.AST,
                         ctx: Optional["_FuncCtx"] = None
                         ) -> List[FuncKey]:
        """Receiver-aware resolution — tighter than the walker's
        tail-matching, because lockset/coloring facts flow through these
        edges and a spurious edge manufactures false guards:

        - bare ``f(...)`` -> top-level functions named ``f``, plus
          nested defs lexically visible from the caller (a bare name
          cannot reach another class's method);
        - ``self.m(...)`` -> the owning class's ``m`` exactly;
        - ``x.m(...)`` -> same-module METHODS named ``m``, unless ``m``
          is a builtin-collection method name (see above); dotted refs
          (``kv_pool.observe_pool``) resolve precisely via the project
          linker either way.
        """
        target = unwrap_partial(callee)
        tail = name_tail(target)
        out: List[FuncKey] = []
        if isinstance(target, ast.Name):
            caller_qn = ctx.key.qualname if ctx is not None else None
            for info in mi.by_name.get(tail, ()):
                if "." not in info.qualname:
                    out.append(FuncKey(mi.path, info.qualname))
                elif caller_qn is not None and info.parent is not None \
                        and (caller_qn == info.parent
                             or caller_qn.startswith(info.parent + ".")):
                    out.append(FuncKey(mi.path, info.qualname))
            if out:
                return out
        elif isinstance(target, ast.Attribute):
            recv_is_self = (ctx is not None and ctx.self_name is not None
                            and isinstance(target.value, ast.Name)
                            and target.value.id == ctx.self_name)
            if recv_is_self and ctx.owner_class is not None:
                info = mi.functions.get(f"{ctx.owner_class}.{tail}")
                if info is not None:
                    return [FuncKey(mi.path, info.qualname)]
            elif not recv_is_self \
                    and tail not in self._COLLECTION_METHODS:
                out.extend(FuncKey(mi.path, info.qualname)
                           for info in mi.by_name.get(tail, ())
                           if "." in info.qualname)
                if out:
                    return out
        from apex_tpu.analysis.walker import dotted_name
        dn = dotted_name(target)
        if dn:
            hit = self.project.resolve_function(mi, dn)
            if hit is not None:
                out.append(FuncKey(hit[0].path, hit[1].qualname))
        return out

    def _collect_call_edges(self) -> None:
        for key, ctx in self.funcs.items():
            edges: List[Tuple[ast.AST, FuncKey]] = []
            for node in walk_shallow(ctx.info.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = unwrap_partial(node.func) \
                    if isinstance(node.func, ast.Call) else node.func
                for ck in self._resolve_callees(ctx.mi, callee, ctx):
                    if ck in self.funcs:
                        edges.append((node, ck))
            self.call_edges[key] = edges

    def _entry_fixpoint(self) -> None:
        """entry(f) = intersection over f's call sites of (caller entry
        | locks held at the site). Intersection is the precise direction
        for race detection: a callee reached both with and without a
        lock counts as unguarded. Functions with no known callers start
        (and stay) at the empty set — anything may call them lock-free."""
        TOP = None                   # lattice top: intersection identity
        callers: Dict[FuncKey, List[Tuple[FuncKey, ast.AST]]] = {}
        for caller, edges in self.call_edges.items():
            for node, callee in edges:
                if callee == caller:
                    continue         # a function's FIRST activation
                #                      always arrives from outside —
                #                      recursive self-calls say nothing
                #                      about its entry lockset
                callers.setdefault(callee, []).append((caller, node))
        entry: Dict[FuncKey, Optional[FrozenSet[LockKey]]] = {}
        for key in self.funcs:
            entry[key] = TOP if key in callers else frozenset()

        def converge() -> None:
            changed, rounds = True, 0
            while changed and rounds < 50:
                changed = False
                rounds += 1
                for key, sites in callers.items():
                    acc: Optional[FrozenSet[LockKey]] = TOP
                    for caller, node in sites:
                        ce = entry[caller]
                        if ce is TOP:
                            continue   # unresolved caller: contributes top
                        site = self.local_locks(caller, node) | ce
                        acc = site if acc is TOP else (acc & site)
                    if acc is not TOP and acc != entry[key]:
                        entry[key] = acc
                        changed = True

        converge()
        # call cycles with no resolved external entry stay at top; pin
        # them to the empty set (anything could call into the cycle
        # lock-free) and re-converge so their downstream callees still
        # get their call-site locks
        if any(v is TOP for v in entry.values()):
            for k, v in entry.items():
                if v is TOP:
                    entry[k] = frozenset()
            converge()
        self.entry_locks = {k: (v if v is not TOP else frozenset())
                            for k, v in entry.items()}

    # ------------------------------------------------------ field accesses

    _INIT_NAMES = ("__init__", "__post_init__", "__new__")

    #: method names that mutate their receiver in place —
    #: ``self._tokens.append(tok)`` is a WRITE to the field for race
    #: purposes even though the attribute node itself is a Load
    _MUTATOR_METHODS = frozenset({
        "append", "appendleft", "extend", "extendleft", "insert", "pop",
        "popleft", "popitem", "remove", "clear", "update", "add",
        "discard", "setdefault", "sort", "reverse", "rotate",
    })

    def _collect_field_accesses(self) -> None:
        for key, ctx in self.funcs.items():
            if ctx.owner_class is None or ctx.self_name is None:
                continue
            method = key.qualname.split(".")
            if any(part in self._INIT_NAMES for part in method):
                continue             # construction is thread-confined
            mi = ctx.mi
            for node in walk_shallow(ctx.info.node):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == ctx.self_name):
                    continue
                fkey = (key.module, ctx.owner_class, node.attr)
                if self.attr_kinds.get(fkey) in SYNC_KINDS:
                    continue         # sync primitives guard themselves
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    write = True
                else:
                    parent = ctx.parents.get(id(node))
                    if isinstance(parent, ast.Call) \
                            and parent.func is node \
                            and f"{ctx.owner_class}.{node.attr}" \
                            in mi.functions:
                        continue     # plain method call, not a field
                    write = False
                    if isinstance(parent, ast.Attribute) \
                            and parent.value is node:
                        gp = ctx.parents.get(id(parent))
                        if isinstance(gp, ast.Call) \
                                and gp.func is parent \
                                and parent.attr in self._MUTATOR_METHODS:
                            write = True    # in-place container mutation
                    elif isinstance(parent, ast.Subscript) \
                            and parent.value is node \
                            and isinstance(parent.ctx,
                                           (ast.Store, ast.Del)):
                        write = True        # self.f[k] = v mutates f
                self.accesses.append(FieldAccess(
                    field=fkey, func=key, node=node, write=write,
                    locks=self.effective_locks(key, node)))

    # ------------------------------------------------------- derived views

    def acquisition_events(self) -> Iterator[Acquisition]:
        for ctx in self.funcs.values():
            for acq in ctx.acquisitions:
                # effective held set = lexically held | caller context
                yield Acquisition(
                    acq.lock,
                    acq.held | self.entry_locks.get(acq.func, frozenset()),
                    acq.node, acq.func)

    def inferred_guards(self) -> Dict[Tuple[str, str, str],
                                      Tuple[LockKey, int, int]]:
        """Eraser-style GuardedBy inference: for each field with at
        least one write outside ``__init__``, the lock held at the
        largest share of its access sites — inferred as the field's
        guard when that share is at least half. Returns
        ``field -> (lock, guarded_sites, total_sites)``; fields whose
        accesses never hold any lock are absent (lock-free by design,
        e.g. pump-confined state — not this tier's business)."""
        by_field: Dict[Tuple[str, str, str], List[FieldAccess]] = {}
        for acc in self.accesses:
            by_field.setdefault(acc.field, []).append(acc)
        out: Dict[Tuple[str, str, str], Tuple[LockKey, int, int]] = {}
        for field, sites in by_field.items():
            if not any(s.write for s in sites):
                continue
            counts: Counter = Counter()
            for s in sites:
                counts.update(s.locks)
            if not counts:
                continue
            lock, n = counts.most_common(1)[0]
            if n * 2 >= len(sites):
                out[field] = (lock, n, len(sites))
        return out
