"""tpu-lint concurrency rules: the host-side hazard classes PR 6 bought.

The serving front-end made the host side genuinely concurrent (a pump
thread, thread-safe ``submit``/``StreamHandle``, the ``/metrics`` server
thread, XLA runtime callback threads feeding the metrics registry), and
none of the existing tiers can see a field touched from two threads
without its lock, a device sync under a lock that stalls the pump, or a
refcount leaked on an early-exit path. Each rule here walks the shared
:class:`~apex_tpu.analysis.conc.locks.ConcModel` fact base.

Same precision bias as the other tiers: every check fires only on
statically resolvable patterns — registered lock objects, literal span
names, receiver-classified blocking calls — and the Eraser-style field
rule only speaks when the code itself establishes a guard convention
(a field is flagged only when at least half its access sites hold one
specific lock; lock-free-by-design state never fires).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, FrozenSet, Iterator, List, Optional, \
    Set, Tuple

from apex_tpu.analysis.conc.locks import ConcModel, LockKey, _FuncCtx
from apex_tpu.analysis.conc.threads import describe_threads
from apex_tpu.analysis.rules import _expr_key
from apex_tpu.analysis.walker import (Finding, call_name, kwarg,
                                      name_tail, walk_shallow)


@dataclasses.dataclass(frozen=True)
class ConcRule:
    name: str
    severity: str
    summary: str
    check: Callable                  # check(model: ConcModel) -> Iterator


CONC_RULES: Dict[str, ConcRule] = {}


def conc_rule(name: str, severity: str, summary: str):
    def deco(fn):
        CONC_RULES[name] = ConcRule(name=name, severity=severity,
                                    summary=summary, check=fn)
        return fn
    return deco


def _finding(rule: ConcRule, module: str, node: ast.AST, message: str,
             scope: str) -> Finding:
    return Finding(
        rule=rule.name, severity=rule.severity, path=module,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1,
        message=message, scope=scope,
        end_line=getattr(node, "end_lineno", 0)
        or getattr(node, "lineno", 1))


def _lockset_str(locks: FrozenSet[LockKey]) -> str:
    if not locks:
        return "no lock"
    return "{" + ", ".join(sorted(lk.display() for lk in locks)) + "}"


# --------------------------------------------------------------------------
# 1. conc-unguarded-shared-field
# --------------------------------------------------------------------------

@conc_rule("conc-unguarded-shared-field", "error",
           "field inferred @GuardedBy(lock) — at least half its access "
           "sites hold one specific lock — is accessed lock-free from "
           "code that runs on more than one thread")
def check_unguarded_shared_field(model: ConcModel) -> Iterator[Finding]:
    r = CONC_RULES["conc-unguarded-shared-field"]
    guards = model.inferred_guards()
    by_field: Dict[tuple, list] = {}
    for acc in model.accesses:
        by_field.setdefault(acc.field, []).append(acc)
    for field, (lock, n, total) in sorted(
            guards.items(), key=lambda kv: kv[0]):
        sites = by_field[field]
        # shared = some access site runs on a non-caller thread; a field
        # only ever touched from API-caller context has no second thread
        # for the missing lock to race against (as far as we can see)
        if not any(model.colors.get(s.func) for s in sites):
            continue
        _, cls, attr = field
        for s in sites:
            if lock in s.locks:
                continue
            yield _finding(
                r, s.func.module, s.node,
                f"`{cls}.{attr}` is inferred @GuardedBy"
                f"({lock.display()}) — held at {n}/{total} access sites "
                f"— but this {'write' if s.write else 'read'} in "
                f"`{s.func.qualname}` (threads "
                f"{describe_threads(model, s.func)}) holds "
                f"{_lockset_str(s.locks)}",
                scope=s.func.qualname)


# --------------------------------------------------------------------------
# 2. conc-lock-order-cycle
# --------------------------------------------------------------------------

@conc_rule("conc-lock-order-cycle", "error",
           "cycle in the acquires-while-holding graph — two call paths "
           "take the same locks in opposite orders (ABBA deadlock)")
def check_lock_order_cycle(model: ConcModel) -> Iterator[Finding]:
    r = CONC_RULES["conc-lock-order-cycle"]
    edges: Dict[LockKey, Dict[LockKey, object]] = {}
    for acq in model.acquisition_events():
        for held in acq.held:
            if held == acq.lock:
                continue             # self re-entry is rule 6's business
            edges.setdefault(held, {}).setdefault(acq.lock, acq)

    # Tarjan SCCs over the tiny lock graph; any SCC with >= 2 locks (or
    # reciprocal edges) is an inversion
    index: Dict[LockKey, int] = {}
    low: Dict[LockKey, int] = {}
    onstack: Set[LockKey] = set()
    stack: List[LockKey] = []
    sccs: List[List[LockKey]] = []
    counter = [0]

    def strongconnect(v: LockKey) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        for w in edges.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in onstack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc = []
            while True:
                w = stack.pop()
                onstack.discard(w)
                scc.append(w)
                if w == v:
                    break
            if len(scc) > 1:
                sccs.append(scc)

    for v in sorted(edges, key=lambda lk: lk.display()):
        if v not in index:
            strongconnect(v)

    for scc in sccs:
        members = sorted(scc, key=lambda lk: lk.display())
        sites = []
        for a in members:
            for b, acq in edges.get(a, {}).items():
                if b in scc:
                    sites.append((a, b, acq))
        sites.sort(key=lambda s: (s[2].func.module, s[2].node.lineno))
        where = "; ".join(
            f"{a.display()} -> {b.display()} at "
            f"{acq.func.module}:{acq.node.lineno}"
            for a, b, acq in sites[:4])
        anchor = sites[0][2]
        yield _finding(
            r, anchor.func.module, anchor.node,
            f"lock-order cycle over "
            f"{{{', '.join(lk.display() for lk in members)}}}: {where} — "
            "two threads taking these in opposite orders deadlock",
            scope=anchor.func.qualname)


# --------------------------------------------------------------------------
# 3. conc-blocking-under-lock
# --------------------------------------------------------------------------

_DEVICE_SYNCS = {"jax.device_get", "device_get"}
_EVENTISH = ("evt", "event", "cond")
_FUTUREISH = ("handle", "future", "fut")


def _blocking_reason(model: ConcModel, ctx: _FuncCtx,
                     call: ast.Call) -> Optional[str]:
    cn = call_name(call)
    tail = cn.split(".")[-1] if cn else None
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        recv = call.func.value
        if attr == "block_until_ready":
            return "`.block_until_ready()` blocks on the device"
        if model.resolve_lock(ctx, recv) is not None:
            return None              # lock ops are rules 2/5/6's domain
        kind = model.attr_kind(ctx, recv)
        rt = (name_tail(recv) or "").lower()
        if attr == "get" and (kind == "queue" or "queue" in rt
                              or rt in ("q", "_q")):
            return "`Queue.get()` blocks until an item arrives"
        if attr == "join" and (kind == "thread" or "thread" in rt):
            return "`Thread.join()` blocks on the other thread"
        if attr == "wait" and (kind in ("event", "condition")
                               or any(w in rt for w in _EVENTISH)):
            return "`.wait()` blocks until another thread signals"
        if attr == "result" and any(w in rt for w in _FUTUREISH):
            return "`.result()` blocks for another thread's work"
    if cn in _DEVICE_SYNCS:
        return "`jax.device_get` synchronizes with the device"
    if tail == "block_until_ready":
        return "`jax.block_until_ready` blocks on the device"
    if cn in ("time.sleep", "sleep"):
        return "`sleep` parks the thread"
    return None


@conc_rule("conc-blocking-under-lock", "warning",
           "blocking operation (device sync, queue.get, thread join, "
           "Event.wait, handle.result, sleep) while holding a lock — "
           "every thread contending for the lock stalls with it")
def check_blocking_under_lock(model: ConcModel) -> Iterator[Finding]:
    r = CONC_RULES["conc-blocking-under-lock"]
    for key, ctx in sorted(model.funcs.items(),
                           key=lambda kv: (kv[0].module, kv[0].qualname)):
        # walk_shallow: a nested def's body runs when CALLED (often on
        # another thread, lock-free) — it is its own ctx with its own
        # entry lockset, and visiting it here would both inherit the
        # enclosing function's locks and double-report
        for node in walk_shallow(ctx.info.node):
            if not isinstance(node, ast.Call):
                continue
            held = model.effective_locks(key, node)
            if not held:
                continue
            why = _blocking_reason(model, ctx, node)
            if why:
                yield _finding(
                    r, key.module, node,
                    f"{why}, but `{key.qualname}` holds "
                    f"{_lockset_str(held)} here — the lock is pinned "
                    "for the operation's full latency",
                    scope=key.qualname)


# --------------------------------------------------------------------------
# 4-5. resource pairing (pages / prefix refs / spans, and bare locks)
# --------------------------------------------------------------------------

# promote_pages pops device pages off the free stack exactly like an
# allocation (the frontend calls it through its compiled `_promote_jit`
# wrapper); the obligation discharges when insert_promoted grafts the
# page into the radix tree, which owns its refcount from then on.
_POOL_ACQ = {"alloc_slot", "alloc_slot_shared", "promote_pages",
             "_promote_jit"}
_POOL_REL = {"release_slot", "free_slot", "insert_promoted"}

#: event kinds the pairing walk understands
_ACQ, _REL, _ESC = "acq", "rel", "esc"


def _in_order(node: ast.AST) -> Iterator[ast.AST]:
    """Source-order DFS that stays in the current runtime scope."""
    stack = [node]
    order: List[ast.AST] = []
    while stack:
        n = stack.pop()
        order.append(n)
        children = [c for c in ast.iter_child_nodes(n)
                    if not isinstance(c, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef))]
        stack.extend(reversed(children))
    return iter(order)


def _classify_resources(model: ConcModel, ctx: _FuncCtx,
                        node: ast.AST) -> Iterator[tuple]:
    """(kind, key, node) events for the page/prefix-ref/span protocols."""
    if isinstance(node, (ast.Assign, ast.Return, ast.Yield)):
        # a handle stored into an attribute/name or returned escapes the
        # function: ownership transferred, the pairing obligation too
        value = node.value
        if value is not None:
            for sub in ast.walk(value):
                k = _expr_key(sub)
                if k is not None:
                    yield (_ESC, ("ref", k), node)
        return
    if not isinstance(node, ast.Call):
        return
    cn = call_name(node)
    tail = cn.split(".")[-1] if cn else None
    if tail in _POOL_ACQ:
        yield (_ACQ, ("pool",), node)
        return
    if tail in _POOL_REL:
        yield (_REL, ("pool",), node)
        return
    if not isinstance(node.func, ast.Attribute):
        return
    recv_key = _expr_key(node.func.value)
    if tail == "release_and_insert":
        yield (_REL, ("pool",), node)
        for arg in node.args:
            k = _expr_key(arg)
            if k is not None:
                yield (_REL, ("ref", k), node)
        return
    if model.resolve_lock(ctx, node.func.value) is not None:
        return                       # lock ops: the lock classifier's job
    if tail == "acquire" and node.args:
        k = _expr_key(node.args[0])
        if k is not None:
            yield (_ACQ, ("ref", k), node)
    elif tail == "release" and node.args:
        k = _expr_key(node.args[0])
        if k is not None:
            yield (_REL, ("ref", k), node)
    elif tail == "begin" and len(node.args) >= 2 \
            and isinstance(node.args[1], ast.Constant) \
            and isinstance(node.args[1].value, str):
        yield (_ACQ, ("span", recv_key, node.args[1].value), node)
    elif tail == "end" and len(node.args) >= 2 \
            and isinstance(node.args[1], ast.Constant) \
            and isinstance(node.args[1].value, str):
        yield (_REL, ("span", recv_key, node.args[1].value), node)


def _classify_locks(model: ConcModel, ctx: _FuncCtx,
                    node: ast.AST) -> Iterator[tuple]:
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return
    lk = model.resolve_lock(ctx, node.func.value)
    if lk is None:
        return
    if node.func.attr == "acquire":
        yield (_ACQ, ("lock", lk), node)
    elif node.func.attr == "release":
        yield (_REL, ("lock", lk), node)


class _PairWalk:
    """Path-sensitive-enough acquire/release matching over one function.

    State is the set of open acquire tokens; branch merges INTERSECT
    (a token counts as released if any path released it — the tier's
    precision bias: report only exits NO path can reach with the
    resource closed). ``finally`` blocks release for every exit they
    enclose. With ``gate=True`` only acquire keys that have a matching
    in-function release are tracked at all — a protocol whose release
    lives in another function (the engine's admit/retire split, the span
    tracer's cross-phase begin/end) is an ownership transfer, not a
    leak.
    """

    def __init__(self, model: ConcModel, ctx: _FuncCtx,
                 classify: Callable, gate: bool):
        self.model = model
        self.ctx = ctx
        self.classify = classify
        self.tokens: Dict[int, tuple] = {}   # id -> (key, node)
        self.leaks: Dict[int, ast.AST] = {}  # token id -> exit node
        acq_keys: Set[tuple] = set()
        rel_keys: Set[tuple] = set()
        for n in _in_order(ctx.info.node):
            for kind, key, node in classify(model, ctx, n):
                if kind == _ACQ:
                    acq_keys.add(key)
                elif kind == _REL:
                    rel_keys.add(key)
        self.tracked = acq_keys & rel_keys if gate else acq_keys

    def run(self) -> Iterator[Tuple[ast.AST, ast.AST]]:
        body = getattr(self.ctx.info.node, "body", [])
        final = self._block(body, frozenset(), [])
        if final is not None and final:
            self._report(final, self.ctx.info.node, [])
        for tid, exit_node in sorted(self.leaks.items(),
                                     key=lambda kv: kv[1].lineno):
            yield self.tokens[tid][1], exit_node

    # -- events ---------------------------------------------------------

    def _apply(self, node: ast.AST, cur: FrozenSet[int]) -> FrozenSet[int]:
        out = set(cur)
        for n in _in_order(node):
            for kind, key, knode in self.classify(self.model, self.ctx, n):
                if kind == _ACQ and key in self.tracked:
                    self.tokens[id(knode)] = (key, knode)
                    out.add(id(knode))
                elif kind in (_REL, _ESC):
                    out = {t for t in out if self.tokens[t][0] != key}
        return frozenset(out)

    def _report(self, cur: FrozenSet[int], exit_node: ast.AST,
                fin: List[Set[tuple]]) -> None:
        covered = set().union(*fin) if fin else set()
        for tid in cur:
            if self.tokens[tid][0] in covered:
                continue
            self.leaks.setdefault(tid, exit_node)

    # -- control flow ---------------------------------------------------

    def _block(self, stmts: List[ast.stmt],
               cur: Optional[FrozenSet[int]],
               fin: List[Set[tuple]]) -> Optional[FrozenSet[int]]:
        for stmt in stmts:
            if cur is None:
                return None
            cur = self._stmt(stmt, cur, fin)
        return cur

    @staticmethod
    def _merge(a: Optional[FrozenSet[int]],
               b: Optional[FrozenSet[int]]) -> Optional[FrozenSet[int]]:
        if a is None:
            return b
        if b is None:
            return a
        return a & b

    def _stmt(self, stmt: ast.stmt, cur: FrozenSet[int],
              fin: List[Set[tuple]]) -> Optional[FrozenSet[int]]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return cur
        if isinstance(stmt, (ast.Return, ast.Raise)):
            if getattr(stmt, "value", None) is not None:
                cur = self._apply(stmt.value, cur)
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                cur = self._apply(stmt.exc, cur)
            self._report(cur, stmt, fin)
            return None
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return cur
        if isinstance(stmt, ast.If):
            cur = self._apply(stmt.test, cur)
            return self._merge(self._block(list(stmt.body), cur, fin),
                               self._block(list(stmt.orelse), cur, fin))
        if isinstance(stmt, (ast.While,)):
            cur = self._apply(stmt.test, cur)
            once = self._block(list(stmt.body), cur, fin)
            after = self._merge(once, cur) if once is not None else cur
            return self._block(list(stmt.orelse), after, fin)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            cur = self._apply(stmt.iter, cur)
            once = self._block(list(stmt.body), cur, fin)
            after = self._merge(once, cur) if once is not None else cur
            return self._block(list(stmt.orelse), after, fin)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                cur = self._apply(item.context_expr, cur)
            return self._block(list(stmt.body), cur, fin)
        if isinstance(stmt, ast.Try):
            fin_keys: Set[tuple] = set()
            for n in stmt.finalbody:
                for sub in _in_order(n):
                    for kind, key, _ in self.classify(self.model,
                                                      self.ctx, sub):
                        if kind == _REL:
                            fin_keys.add(key)
            inner_fin = fin + [fin_keys] if fin_keys else fin
            body_out = self._block(list(stmt.body), cur, inner_fin)
            outs = [body_out]
            for h in stmt.handlers:
                outs.append(self._block(list(h.body), cur, inner_fin))
            if stmt.orelse and body_out is not None:
                outs[0] = self._block(list(stmt.orelse), body_out,
                                      inner_fin)
            merged: Optional[FrozenSet[int]] = None
            for o in outs:
                merged = self._merge(merged, o)
            if merged is not None:
                for n in stmt.finalbody:
                    merged = self._apply(n, merged)
            return merged
        return self._apply(stmt, cur)


@conc_rule("conc-resource-leak", "error",
           "alloc/acquire/begin with a matching release in the same "
           "function, but an early return/raise path exits with the "
           "resource still open (leaked pages, dangling prefix "
           "refcount, unclosed span)")
def check_resource_leak(model: ConcModel) -> Iterator[Finding]:
    r = CONC_RULES["conc-resource-leak"]
    for key, ctx in sorted(model.funcs.items(),
                           key=lambda kv: (kv[0].module, kv[0].qualname)):
        walk = _PairWalk(model, ctx, _classify_resources, gate=True)
        if not walk.tracked:
            continue
        for acq_node, exit_node in walk.run():
            what = call_name(acq_node) or "resource"
            yield _finding(
                r, key.module, acq_node,
                f"`{what}(...)` in `{key.qualname}` is not released on "
                f"the exit at line {exit_node.lineno} — this function "
                "pairs acquire with release on its other paths, so the "
                "early exit leaks the resource",
                scope=key.qualname)


@conc_rule("conc-unreleased-lock", "error",
           "manual lock.acquire() with an exit path that skips the "
           "release (and no enclosing try/finally) — prefer `with`")
def check_unreleased_lock(model: ConcModel) -> Iterator[Finding]:
    r = CONC_RULES["conc-unreleased-lock"]
    for key, ctx in sorted(model.funcs.items(),
                           key=lambda kv: (kv[0].module, kv[0].qualname)):
        walk = _PairWalk(model, ctx, _classify_locks, gate=False)
        if not walk.tracked:
            continue
        for acq_node, exit_node in walk.run():
            yield _finding(
                r, key.module, acq_node,
                f"lock acquired here is still held at the exit on line "
                f"{exit_node.lineno} of `{key.qualname}` — use `with`, "
                "or release in a `finally`",
                scope=key.qualname)


# --------------------------------------------------------------------------
# 6. conc-double-acquire
# --------------------------------------------------------------------------

@conc_rule("conc-double-acquire", "error",
           "re-acquiring a non-reentrant threading.Lock already held on "
           "this path — self-deadlock (RLocks are exempt)")
def check_double_acquire(model: ConcModel) -> Iterator[Finding]:
    r = CONC_RULES["conc-double-acquire"]
    seen: Set[Tuple[str, int]] = set()
    for acq in model.acquisition_events():
        if acq.lock not in acq.held or acq.lock.reentrant:
            continue
        where = (acq.func.module, acq.node.lineno)
        if where in seen:
            continue
        seen.add(where)
        yield _finding(
            r, acq.func.module, acq.node,
            f"`{acq.lock.display()}` is a non-reentrant Lock and is "
            f"already held when `{acq.func.qualname}` acquires it again "
            "— this thread deadlocks on itself",
            scope=acq.func.qualname)


# --------------------------------------------------------------------------
# 7. conc-thread-leak
# --------------------------------------------------------------------------

@conc_rule("conc-thread-leak", "warning",
           "non-daemon thread started but never joined — it pins "
           "interpreter shutdown; pass daemon=True or join it")
def check_thread_leak(model: ConcModel) -> Iterator[Finding]:
    r = CONC_RULES["conc-thread-leak"]
    for rel, mi in sorted(model.modules.items()):
        joined: Set[str] = set()
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                t = name_tail(node.func.value)
                if t:
                    joined.add(t)
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if not cn or cn.split(".")[-1] not in ("Thread", "Timer"):
                continue
            daemon = kwarg(node, "daemon")
            if isinstance(daemon, ast.Constant) and daemon.value is True:
                continue
            # assigned somewhere a later .join() reaches?
            info = mi.enclosing_function(node)
            scope = info.qualname if info else "<module>"
            target = None
            parent_assigns = [a for a in ast.walk(mi.tree)
                              if isinstance(a, ast.Assign)
                              and a.value is node]
            for a in parent_assigns:
                t = name_tail(a.targets[0])
                if t:
                    target = t
            if target is not None and target in joined:
                continue
            yield _finding(
                r, rel, node,
                "thread is neither daemon=True nor joined anywhere in "
                "this module — it outlives (and blocks) interpreter "
                "shutdown",
                scope=scope)


# --------------------------------------------------------------------------
# 8. conc-useless-local-lock
# --------------------------------------------------------------------------

@conc_rule("conc-useless-local-lock", "warning",
           "lock created inside a function and used only there — a "
           "fresh lock per call excludes nobody")
def check_useless_local_lock(model: ConcModel) -> Iterator[Finding]:
    from apex_tpu.analysis.conc.locks import _is_sync_ctor

    r = CONC_RULES["conc-useless-local-lock"]
    for key, ctx in sorted(model.funcs.items(),
                           key=lambda kv: (kv[0].module, kv[0].qualname)):
        if key.qualname.split(".")[-1] in ("__init__", "__post_init__"):
            continue
        locals_: Dict[str, ast.AST] = {}
        for node in _in_order(ctx.info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _is_sync_ctor(node.value) in ("lock", "rlock"):
                locals_[node.targets[0].id] = node.value
        if not locals_:
            continue
        for node in _in_order(ctx.info.node):
            used = None
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    t = name_tail(item.context_expr)
                    if t in locals_:
                        used = t
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                t = name_tail(node.func.value)
                if t in locals_:
                    used = t
            if used is not None:
                ctor = locals_.pop(used)
                yield _finding(
                    r, key.module, ctor,
                    f"`{used}` is created fresh on every call of "
                    f"`{key.qualname}` and locked in the same function "
                    "— no two threads ever share it; hoist it to the "
                    "instance or module",
                    scope=key.qualname)


# --------------------------------------------------------------------------
# 9. conc-await-under-lock
# --------------------------------------------------------------------------

@conc_rule("conc-await-under-lock", "error",
           "`await` while holding a threading lock — the suspension "
           "keeps the lock, so every other task on the event loop that "
           "contends for it wedges the whole loop (and any real thread "
           "contending for it stalls for the awaited I/O's latency)")
def check_await_under_lock(model: ConcModel) -> Iterator[Finding]:
    """An asyncio task that suspends while holding a *threading* lock
    is the cross-color deadlock the HTTP surface must never ship: the
    loop thread parks at the ``await`` with the lock still held, so a
    contending pump/submitter thread blocks the OS thread, and a
    contending *task* blocks the loop itself — which is the only thing
    that could ever run the release. Only registered sync locks fire;
    ``async with asyncio.Lock()`` suspends instead of blocking and is
    the sanctioned pattern (its kinds never enter the lock registry —
    see ``locks._ASYNC_CTOR_KINDS``)."""
    r = CONC_RULES["conc-await-under-lock"]
    for key, ctx in sorted(model.funcs.items(),
                           key=lambda kv: (kv[0].module, kv[0].qualname)):
        # walk_shallow for the same reason as rule 3: a nested def is
        # its own ctx with its own entry lockset
        for node in walk_shallow(ctx.info.node):
            if not isinstance(node, ast.Await):
                continue
            held = model.effective_locks(key, node)
            if not held:
                continue
            yield _finding(
                r, key.module, node,
                f"`{key.qualname}` awaits while holding "
                f"{_lockset_str(held)} — the task suspends with the "
                "lock held, wedging every loop task and OS thread that "
                "contends for it; release before the await (or use an "
                "asyncio.Lock, which suspends instead of blocking)",
                scope=key.qualname)


def conc_rules() -> List[ConcRule]:
    return list(CONC_RULES.values())
