"""Thread-entry discovery and call-graph coloring for the conc tier.

Every function implicitly runs on the MAIN thread (anything may call a
public API from anywhere — the serving front-end's ``submit()`` contract
is exactly that). What this module adds is the set of *extra* threads a
function can run on, by rooting a BFS at every statically visible
thread entry point:

- ``threading.Thread(target=f, ...)`` / ``threading.Timer(t, f)`` —
  the root is named by the ctor's literal ``name=`` when present, else
  the target's name. The serving stack's threads all register this way:
  every replica frontend's ``"serving-frontend-pump"`` and the replica
  router's ``"serving-router-supervisor"`` (whose tick — failure
  detection, token forwarding, failover — colors the whole
  ``ReplicaRouter`` call chain; ``tests/test_conc_lint.py`` pins both
  colorings and the router's GuardedBy map);
- ``<executor|pool>.submit(f, ...)`` — worker-pool dispatch (the
  receiver must *look like* an executor so the serving front-end's
  ``submit(request)`` ingest API never becomes a false root);
- ``do_*`` methods of ``BaseHTTPRequestHandler`` subclasses — the
  ``/metrics`` endpoint's handler runs on server threads;
- the callable handed to ``jax.debug.callback`` — the metrics channel
  delivers on XLA runtime threads (the ``record()`` docstring's
  contract), so its payload is colored ``jax-callback``;
- **asyncio tasks** — coroutines handed to ``asyncio.run`` /
  ``loop.run_until_complete`` / ``run_coroutine_threadsafe``,
  spawned via ``create_task``/``ensure_future`` on a loop-ish
  receiver, or installed as ``asyncio.start_server``'s
  per-connection callback. All carry ONE color, ``asyncio``: tasks
  on a loop interleave only at ``await`` points, so they form a
  single cooperative "thread" — what matters to the rules is (a)
  that loop-confined state is not also touched from real threads
  and (b) that no task ``await``\\ s while holding a *threading*
  lock (``conc-await-under-lock``: the loop thread would keep the
  lock across the suspension and every other task contending for it
  wedges the whole loop). ``loop.run_in_executor(...)`` /
  ``asyncio.to_thread(...)`` payloads leave the loop for a worker
  pool and are colored ``executor``.

Colors propagate through the same resolved call edges the lockset
machinery uses. A function with any color is *multi-thread*: it runs on
that thread AND (implicitly) wherever else its callers live, which is
what the shared-field rule needs to know.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from apex_tpu.analysis.conc.locks import ConcModel, FuncKey
from apex_tpu.analysis.walker import (call_name, kwarg, name_tail,
                                      unwrap_partial)

#: receiver-name fragments that make an ``.submit(fn, ...)`` call a
#: worker-pool dispatch rather than an application-level submit API
_EXECUTORISH = ("executor", "pool", "workers")

_HOST_CALLBACK_FNS = {"jax.debug.callback", "debug.callback"}

#: receiver-name fragments that make a ``.create_task(coro)`` /
#: ``.ensure_future(coro)`` / ``.run_until_complete(coro)`` call an
#: event-loop dispatch (``loop``, ``self._loop``, a TaskGroup ``tg``)
#: rather than some application-level method of the same name
_LOOPISH = ("loop", "asyncio", "tg", "taskgroup")


def _coro_target(expr: Optional[ast.AST]) -> Optional[ast.AST]:
    """The function behind a task-spawn argument. Spawns usually pass
    an *invoked* coroutine (``create_task(self._watch(reader))``), so
    unwrap one Call layer to the callee; a bare reference (the
    ``start_server`` callback) passes through."""
    if isinstance(expr, ast.Call):
        return expr.func
    return expr


def _literal_name(call: ast.Call) -> Optional[str]:
    v = kwarg(call, "name")
    if isinstance(v, ast.Constant) and isinstance(v.value, str):
        return v.value
    return None


def _target_expr(call: ast.Call, tail: str) -> Optional[ast.AST]:
    if tail in ("Thread",):
        v = kwarg(call, "target")
        if v is not None:
            return v
        return call.args[1] if len(call.args) > 1 else None
    if tail in ("Timer",):
        v = kwarg(call, "function")
        if v is not None:
            return v
        return call.args[1] if len(call.args) > 1 else None
    return None


def thread_roots(model: ConcModel) -> List[Tuple[str, FuncKey]]:
    """Statically visible thread entry points: ``(thread name, func)``."""
    roots: List[Tuple[str, FuncKey]] = []

    def resolve(mi, expr, site) -> List[FuncKey]:
        if expr is None:
            return []
        # resolve from the enclosing function's context so a nested
        # target (`Thread(target=loop)` inside `start()`) is visible
        info = mi.enclosing_function(site)
        ctx = model.funcs.get(FuncKey(mi.path, info.qualname)) \
            if info is not None else None
        return model._resolve_callees(mi, unwrap_partial(expr), ctx)

    for rel, mi in model.modules.items():
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.ClassDef):
                bases = {name_tail(b) or "" for b in node.bases}
                if any(b.endswith("HTTPRequestHandler") for b in bases):
                    prefix = _handler_prefix(model, rel, node)
                    for key, ctx in model.funcs.items():
                        if key.module == rel \
                                and ctx.owner_class == prefix \
                                and key.qualname.split(".")[-1]\
                                .startswith("do_"):
                            roots.append(("http-handler", key))
                continue
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            tail = cn.split(".")[-1] if cn else None
            if tail in ("Thread", "Timer"):
                target = _target_expr(node, tail)
                for fk in resolve(mi, target, node):
                    roots.append((
                        _literal_name(node)
                        or fk.qualname.split(".")[-1], fk))
            elif tail == "submit" and isinstance(node.func, ast.Attribute):
                recv = name_tail(node.func.value) or ""
                if any(w in recv.lower() for w in _EXECUTORISH) \
                        and node.args:
                    for fk in resolve(mi, node.args[0], node):
                        roots.append(("executor", fk))
            elif cn in _HOST_CALLBACK_FNS and node.args:
                # only the bare-name / partial forms resolve — a factory
                # call in the callable position stays opaque, exactly
                # like the AST tier's exemption logic
                for fk in resolve(mi, node.args[0], node):
                    roots.append(("jax-callback", fk))
            elif tail in ("create_task", "ensure_future",
                          "run_until_complete",
                          "run_coroutine_threadsafe") and node.args:
                recv = ""
                if isinstance(node.func, ast.Attribute):
                    recv = name_tail(node.func.value) or ""
                if cn.startswith("asyncio.") \
                        or any(w in recv.lower() for w in _LOOPISH):
                    for fk in resolve(mi, _coro_target(node.args[0]),
                                      node):
                        roots.append(("asyncio", fk))
            elif cn in ("asyncio.run",) and node.args:
                for fk in resolve(mi, _coro_target(node.args[0]), node):
                    roots.append(("asyncio", fk))
            elif cn in ("asyncio.start_server",) and node.args:
                # the per-connection callback: one task per accepted
                # socket — THE root that colors an asyncio server
                for fk in resolve(mi, node.args[0], node):
                    roots.append(("asyncio", fk))
            elif cn in ("asyncio.to_thread",) and node.args:
                for fk in resolve(mi, node.args[0], node):
                    roots.append(("executor", fk))
            elif tail == "run_in_executor" and len(node.args) > 1:
                recv = ""
                if isinstance(node.func, ast.Attribute):
                    recv = name_tail(node.func.value) or ""
                if any(w in recv.lower() for w in _LOOPISH):
                    for fk in resolve(mi, node.args[1], node):
                        roots.append(("executor", fk))
    return roots


def _handler_prefix(model: ConcModel, rel: str,
                    cls: ast.ClassDef) -> Optional[str]:
    """The class qualname matching ``cls`` in the model's class table."""
    for qn, node in model._classes.get(rel, {}).items():
        if node is cls:
            return qn
    return None


def color(model: ConcModel) -> Dict[FuncKey, FrozenSet[str]]:
    """Propagate thread-root names over the call graph; writes the
    result into ``model.colors`` and returns it."""
    colors: Dict[FuncKey, Set[str]] = {}
    work: List[FuncKey] = []
    for name, key in thread_roots(model):
        cur = colors.setdefault(key, set())
        if name not in cur:
            cur.add(name)
            work.append(key)
    while work:
        key = work.pop()
        mine = colors.get(key, set())
        # lexically nested defs of a thread function also run on it
        nested = [k for k in model.funcs
                  if k.module == key.module
                  and k.qualname.startswith(key.qualname + ".")]
        callees = [ck for _, ck in model.call_edges.get(key, ())]
        for nxt in nested + callees:
            cur = colors.setdefault(nxt, set())
            if not mine <= cur:
                cur.update(mine)
                work.append(nxt)
    model.colors = {k: frozenset(v) for k, v in colors.items()}
    return model.colors


def describe_threads(model: ConcModel, key: FuncKey) -> str:
    """``{caller, serving-frontend-pump}`` — the thread set a function
    runs on, for findings (``caller`` stands for main/any API caller)."""
    extra = sorted(model.colors.get(key, ()))
    return "{" + ", ".join(["caller"] + extra) + "}"
