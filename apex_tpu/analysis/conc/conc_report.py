"""Orchestration for the tpu-lint concurrency tier.

:func:`analyze_conc_sources` is the engine: parse every module of the
scanned surface, link the interprocedural graph (reusing PR 5's
``ProjectIndex`` — the conc tier never re-walks modules on its own),
build the :class:`~apex_tpu.analysis.conc.locks.ConcModel` fact base,
color it with thread roots, run the selected ``conc-*`` rules, and
apply the ordinary inline-suppression pragmas. Like the AST tier it is
purely syntactic (stdlib ``ast``, no jax import), which is what lets
``--diff`` run it against a git base rev's sources.

:func:`analyze_conc` is the disk-backed wrapper the CLI uses: it scans
the same default surface as the AST tier (the whole-program call graph
is what gives locksets and thread colors their meaning, so the tier
always analyzes the full surface rather than path subsets).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from apex_tpu.analysis.conc import threads as _threads
from apex_tpu.analysis.conc.conc_rules import CONC_RULES
from apex_tpu.analysis.conc.locks import ConcModel
from apex_tpu.analysis.project import ProjectIndex
from apex_tpu.analysis.suppressions import Suppressions
from apex_tpu.analysis.walker import Finding, ModuleIndex


def model_from(modules: Dict[str, ModuleIndex],
               project: ProjectIndex) -> ConcModel:
    """Fact base over a pre-parsed, pre-LINKED surface — what ``--diff``
    uses so one parse+link feeds both source-only tiers."""
    model = ConcModel(modules, project)
    _threads.color(model)
    return model


def build_model(sources: Dict[str, str]
                ) -> Tuple[ConcModel, List[Finding]]:
    """Parse + link + color one surface; returns the model and any
    parse-error findings (a broken file must not hide the others)."""
    from apex_tpu.analysis.cli import parse_sources

    modules, findings = parse_sources(sources)
    project = ProjectIndex(modules)
    project.link()
    return model_from(modules, project), findings


def analyze_conc_sources(sources: Dict[str, str], *,
                         select: Optional[Iterable[str]] = None,
                         model: Optional[ConcModel] = None,
                         ) -> Tuple[List[Finding], int]:
    """Run the conc rules over an in-memory ``{rel path: source}`` map;
    returns ``(surviving findings, #suppressed)``. ``model`` supplies a
    pre-built fact base (the caller then owns its parse-error
    findings)."""
    chosen = set(select) if select is not None else set(CONC_RULES)
    unknown = chosen - set(CONC_RULES)
    if unknown:
        raise ValueError(
            f"unknown conc rule(s): {', '.join(sorted(unknown))}")
    findings: List[Finding] = []
    if model is None:
        model, findings = build_model(sources)
    raw: List[Finding] = []
    for name in sorted(chosen):
        raw.extend(CONC_RULES[name].check(model))
    suppressed = 0
    supp_cache: Dict[str, Suppressions] = {}
    for f in raw:
        supp = supp_cache.get(f.path)
        if supp is None:
            supp = Suppressions(sources.get(f.path, ""))
            supp_cache[f.path] = supp
        if supp.covers(f):
            suppressed += 1
        else:
            findings.append(f)
    return findings, suppressed


def analyze_conc(root, *, select: Optional[Iterable[str]] = None,
                 ) -> Tuple[List[Finding], int]:
    """Disk-backed run over the default lint surface under ``root``."""
    from apex_tpu.analysis.cli import read_sources

    sources, findings = read_sources(Path(root).resolve())
    more, suppressed = analyze_conc_sources(sources, select=select)
    findings.extend(more)
    return findings, suppressed
