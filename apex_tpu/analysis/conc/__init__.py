"""tpu-lint concurrency tier: host-thread & resource-lifecycle analysis.

The third lint tier (``--conc``). The AST tier reads what the source
says about *traced* code; the IR tier reads what JAX stages; this tier
reads what the HOST side does across threads — the pump thread, the
``/metrics`` exporter, XLA callback delivery, and every API caller —
over the same interprocedural call graph PR 5 built (``project.py``).

Four fact layers (``threads.py`` + ``locks.py``), eight rules
(``conc_rules.py``):

- **thread coloring** — ``threading.Thread``/``Timer`` targets,
  executor submits, HTTP-handler ``do_*`` methods, and
  ``jax.debug.callback`` payloads root a call-graph BFS, so every
  function knows which extra threads it runs on;
- **locksets + GuardedBy inference** — ``with lock:`` spans propagate
  through call sites; a field whose access sites mostly hold one lock
  is inferred guarded by it, and lock-free accesses from multi-thread
  code are ``conc-unguarded-shared-field`` findings;
- **lock-order graph** — ``conc-lock-order-cycle`` (ABBA),
  ``conc-double-acquire`` (non-reentrant self-deadlock),
  ``conc-blocking-under-lock`` (device syncs / queue waits that pin a
  lock), ``conc-unreleased-lock``, ``conc-useless-local-lock``,
  ``conc-thread-leak``;
- **resource pairing** — ``conc-resource-leak``: alloc/acquire/begin
  with an in-function release but an early return/raise that skips it.

Usage::

    python -m apex_tpu.analysis --conc
    python -m apex_tpu.analysis --conc --select conc-lock-order-cycle

Findings share the AST tier's suppression pragmas, baseline file
(tier-partitioned by the ``conc-`` prefix — ``analysis/tiers.py``), and
``--diff`` mode.
"""

from apex_tpu.analysis.conc.conc_report import (analyze_conc,
                                                analyze_conc_sources,
                                                build_model, model_from)
from apex_tpu.analysis.conc.conc_rules import CONC_RULES, ConcRule
from apex_tpu.analysis.conc.locks import ConcModel, FuncKey, LockKey
from apex_tpu.analysis.conc.threads import color, thread_roots

__all__ = [
    "CONC_RULES",
    "ConcModel",
    "ConcRule",
    "FuncKey",
    "LockKey",
    "analyze_conc",
    "analyze_conc_sources",
    "build_model",
    "color",
    "model_from",
    "thread_roots",
]
