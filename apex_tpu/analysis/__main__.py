import sys

from apex_tpu.analysis.cli import main

sys.exit(main())
