"""AST groundwork for tpu-lint: per-module index + jit reachability.

``ModuleIndex`` parses one file and answers the questions every rule
needs: what functions exist (including nested defs and their qualnames),
which of them are *jit entry points* (jitted directly, a ``lax.scan`` /
``while_loop`` / ``fori_loop`` / ``cond`` / ``switch`` body, or a Pallas
kernel), and which functions are *reachable* from those entry points
through same-module calls. Reachability is the backbone of the
host-sync rule: ``np.asarray`` in the host scheduling loop is fine, the
same call three frames below a jitted ``lax.scan`` body is a device
sync every step.

Resolution is name-based and module-local here; the module additionally
RECORDS what it cannot resolve locally — its import table
(:class:`ImportEntry`), the dotted names each function calls
(``calls_dotted``), and jit/scan/pallas callee references whose target is
not a module-local function (``unresolved_marks``) — so
:mod:`apex_tpu.analysis.project` can link the whole scanned surface into
one interprocedural graph in a second phase. Either way the analyzer has
zero import side effects — it never executes the code it reads.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

#: ``# tpu-lint: host-boundary -- why`` on (or directly above) a ``def``
#: declares that the function is BY CONTRACT never executed under a
#: trace — it drives jitted programs from the host (the serving engine's
#: scheduling loop, ``generate_paged``). The reachability walk does not
#: follow call edges into a host boundary, so host ops inside it are
#: judged as host code. The declaration is load-bearing: if the function
#: is in fact traced, the lint is blind below it — hence the mandatory
#: placement on the def itself, where review sees it.
_HOST_BOUNDARY = re.compile(r"#\s*tpu-lint:\s*host-boundary\b")

#: call-position table for tracing-context entry points: dotted-name tail
#: -> indices of positional args that are traced callables. Positions past
#: these are operands, NOT callables (cond(pred, t, f, *ops),
#: switch(index, branches, *ops) — branches is a list, unpacked in _mark).
_TRACED_CALLEE_ARGS = {
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": (1,),
    "checkpoint": (0,),
    "remat": (0,),
    "custom_vjp": (0,),
    "vmap": (0,),
    "pmap": (0,),
}

_JIT_TAILS = {"jit"}
_PARTIAL_TAILS = {"partial"}

#: NON-BLOCKING host-callback entry points (the metrics channel:
#: ``apex_tpu.utils.metrics.record`` rides ``jax.debug.callback``). Their
#: callable argument runs on the HOST with already-materialized values
#: when the step executes — it never forces a device sync, so it is
#: neither a traced body nor a jit-reachable callee. Deliberately narrow:
#: only the dotted ``debug.callback`` form qualifies (``pure_callback`` /
#: ``io_callback`` results feed back into the trace and keep their
#: ordinary treatment).
_HOST_CALLBACK_FNS = {"jax.debug.callback", "debug.callback"}


def _callable_exempt_ids(node: ast.AST) -> "Set[int]":
    """Exempt-node ids for ONE host-callback callable expression: only
    the parts that execute at DELIVERY time (on the host, with
    materialized values) are exempt — a bare name/attribute reference, a
    lambda's BODY, or a ``functools.partial``'s callable. Everything
    evaluated at TRACE time keeps full scrutiny: partial operands,
    lambda default-arg expressions, and arbitrary factory calls
    (``jax.debug.callback(make_cb(x), y)`` runs ``make_cb(x)`` while
    tracing — exempting nothing there, not even the call node itself)."""
    out: Set[int] = set()
    while True:
        if isinstance(node, ast.Lambda):
            out.add(id(node))
            out.update(id(sub) for sub in ast.walk(node.body))
            return out
        if isinstance(node, (ast.Name, ast.Attribute)):
            out.add(id(node))
            out.update(id(sub) for sub in ast.walk(node))
            return out
        if isinstance(node, ast.Call):
            cn = call_name(node)
            if cn and cn.split(".")[-1] in _PARTIAL_TAILS and node.args:
                out.add(id(node))
                out.add(id(node.func))
                node = node.args[0]      # the partial's callable
                continue
        return set()                     # factory call / other expr:
    #                                      wholly trace-time, no exemption


def host_callback_exempt_ids(root: ast.AST) -> "Set[int]":
    """ids of the delivery-time parts of every non-blocking host
    callback's CALLABLE argument under ``root`` — the nodes the host-sync
    rule and the call-graph builder both skip (see
    :func:`_callable_exempt_ids` for what stays scrutinized)."""
    out: Set[int] = set()
    for node in walk_shallow(root):
        if isinstance(node, ast.Call) \
                and call_name(node) in _HOST_CALLBACK_FNS and node.args:
            out.update(_callable_exempt_ids(node.args[0]))
    return out


@dataclasses.dataclass
class Finding:
    """One lint finding, locatable and baseline-addressable."""

    rule: str
    severity: str            # "error" | "warning"
    path: str                # repo-relative posix path
    line: int
    col: int
    message: str
    scope: str = "<module>"  # enclosing function qualname
    end_line: int = 0        # last source line of the offending node

    def __post_init__(self):
        if not self.end_line:
            self.end_line = self.line

    def baseline_key(self) -> str:
        """Line-number-free identity: survives unrelated edits above the
        finding (occurrence disambiguation happens in Baseline)."""
        return f"{self.path}::{self.rule}::{self.scope}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "scope": self.scope,
            "message": self.message,
        }


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def name_tail(node: ast.AST) -> Optional[str]:
    """Final component of a Name/Attribute chain (``self._free_jit`` ->
    ``_free_jit``) — how module-local callables are matched."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def unwrap_partial(node: ast.AST) -> ast.AST:
    """``functools.partial(f, ...)`` -> ``f`` (recursively)."""
    while isinstance(node, ast.Call):
        cn = call_name(node)
        if cn is None or cn.split(".")[-1] not in _PARTIAL_TAILS:
            break
        if not node.args:
            break
        node = node.args[0]
    return node


def const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal int or tuple-of-ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def const_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def walk_shallow(root: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested function/class
    definitions (they are indexed — and scanned — separately). Lambdas
    ARE descended into: they belong to their enclosing scope."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@dataclasses.dataclass(frozen=True)
class ImportEntry:
    """One imported binding: ``from <module> import <attr> as <local>``
    (``attr=None`` for plain ``import <module> [as <local>]``);
    ``level`` counts leading dots of a relative import."""

    local: str
    module: str
    attr: Optional[str]
    level: int = 0


@dataclasses.dataclass
class FunctionInfo:
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    qualname: str
    params: Tuple[str, ...]
    parent: Optional[str]         # enclosing function qualname, if any
    jit_reasons: List[str] = dataclasses.field(default_factory=list)
    host_boundary: bool = False   # declared never-traced (see pragma)

    @property
    def name(self) -> str:
        return self.qualname.split(".")[-1]


class ModuleIndex:
    """Parsed file + function table + jit-entry marking + reachability."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.functions: Dict[str, FunctionInfo] = {}
        self.by_name: Dict[str, List[FunctionInfo]] = {}
        self._enclosing: Dict[int, str] = {}   # id(node) -> qualname
        self._calls: Dict[str, Set[str]] = {}  # qualname -> callee tails
        #: qualname -> DOTTED callee refs for the cross-module linker
        #: (``kv_pool.free_slot``, or a bare imported name)
        self.calls_dotted: Dict[str, Set[str]] = {}
        #: jit/scan/pallas callee refs with no module-local target:
        #: (dotted ref, reason) — resolved by project.ProjectIndex
        self.unresolved_marks: List[Tuple[str, str]] = []
        #: imported bindings, for the cross-module linker
        self.imports: List[ImportEntry] = []
        #: jit wrappers imported from other modules, injected by
        #: project.ProjectIndex (local name -> wrapper info dict)
        self.extra_wrappers: Dict[str, dict] = {}
        self._host_boundary_lines = self._find_host_boundary_lines()
        self._index_imports()
        self._index_functions()
        self._mark_jit_entries()
        self.reachable: Dict[str, List[str]] = self._compute_reachable()

    def _find_host_boundary_lines(self) -> Set[int]:
        """Lines carrying a ``host-boundary`` pragma (real comment tokens
        only, like Suppressions); a comment-only line also covers the
        following line, so the pragma can sit above a long ``def``."""
        lines: Set[int] = set()
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return lines
        for tok in tokens:
            if tok.type != tokenize.COMMENT \
                    or not _HOST_BOUNDARY.search(tok.string):
                continue
            lines.add(tok.start[0])
            if not tok.line[:tok.start[1]].strip():
                # comment-only line: the pragma decorates the next CODE
                # line — skip the rest of its comment block, so the
                # declaration may sit anywhere in the block above a def
                nxt = tok.start[0] + 1
                while nxt <= len(self.lines) \
                        and self.lines[nxt - 1].lstrip()[:1] in ("#", ""):
                    nxt += 1
                lines.add(nxt)
        return lines

    def _index_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        # ``import a.b.c as x``: x names the LEAF module
                        self.imports.append(ImportEntry(
                            local=alias.asname, module=alias.name,
                            attr=None))
                    else:
                        # ``import a.b.c`` binds only ``a`` (the top
                        # package); dotted refs keep their own full path
                        top = alias.name.split(".")[0]
                        self.imports.append(ImportEntry(
                            local=top, module=top, attr=None))
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.imports.append(ImportEntry(
                        local=alias.asname or alias.name,
                        module=node.module or "", attr=alias.name,
                        level=node.level))

    # ---------------------------------------------------------------- index

    def _index_functions(self) -> None:
        def visit(node: ast.AST, prefix: str,
                  enclosing: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}" if prefix else child.name
                    a = child.args
                    params = tuple(
                        p.arg for p in
                        (a.posonlyargs + a.args + a.kwonlyargs))
                    # header span starts at the FIRST decorator (a
                    # pragma above a decorated def attaches there), ends
                    # before the body
                    hdr_start = min(
                        [child.lineno]
                        + [d.lineno for d in child.decorator_list])
                    hdr_end = max(child.body[0].lineno, hdr_start + 1)
                    hb = bool(self._host_boundary_lines
                              & set(range(hdr_start, hdr_end)))
                    info = FunctionInfo(node=child, qualname=qn,
                                        params=params, parent=enclosing,
                                        host_boundary=hb)
                    self.functions[qn] = info
                    self.by_name.setdefault(child.name, []).append(info)
                    for sub in walk_shallow(child):
                        self._enclosing[id(sub)] = qn
                    visit(child, qn + ".", qn)
                elif isinstance(child, ast.ClassDef):
                    # methods keep Class.method qualnames but do not
                    # count as an enclosing *function*
                    visit(child, f"{prefix}{child.name}.", enclosing)
                else:
                    visit(child, prefix, enclosing)

        visit(self.tree, "", None)

        for qn, info in self.functions.items():
            called: Set[str] = set()
            dotted: Set[str] = set()
            # the payload of jax.debug.callback is host-side and
            # non-blocking — it is NOT an edge into jitted execution
            exempt = host_callback_exempt_ids(info.node)
            for node in walk_shallow(info.node):
                if isinstance(node, ast.Call) and id(node) not in exempt:
                    callee = unwrap_partial(node.func) \
                        if isinstance(node.func, ast.Call) else node.func
                    tail = name_tail(callee)
                    if tail:
                        called.add(tail)
                    dn = dotted_name(callee)
                    # dotted refs, plus bare names with no local target:
                    # both may resolve through this module's imports
                    if dn and ("." in dn or dn not in self.by_name):
                        dotted.add(dn)
                    # callables passed onward (e.g. a local fn handed to
                    # jnp.where/vmap) keep the graph connected enough
                    for arg in node.args:
                        if id(arg) in exempt:
                            continue
                        t = name_tail(unwrap_partial(arg))
                        if t and t in self.by_name:
                            called.add(t)
            self._calls[qn] = called
            self.calls_dotted[qn] = dotted

    def enclosing_function(self, node: ast.AST) -> Optional[FunctionInfo]:
        qn = self._enclosing.get(id(node))
        return self.functions.get(qn) if qn else None

    def scope_of(self, node: ast.AST) -> str:
        qn = self._enclosing.get(id(node))
        return qn if qn else "<module>"

    # ------------------------------------------------------------ jit roots

    def _mark(self, ref: Optional[ast.AST], reason: str) -> None:
        if ref is None:
            return
        if isinstance(ref, (ast.List, ast.Tuple)):
            # lax.switch takes its branches as one list argument
            for elt in ref.elts:
                self._mark(elt, reason)
            return
        target = unwrap_partial(ref)
        tail = name_tail(target)
        if not tail:
            return
        if tail not in self.by_name:
            # e.g. ``jax.jit(kv_pool.free_slot)``: the callee lives in
            # another module — record for the interprocedural linker
            dn = dotted_name(target)
            if dn:
                self.unresolved_marks.append((dn, reason))
            return
        for info in self.by_name.get(tail, ()):
            if reason not in info.jit_reasons:
                info.jit_reasons.append(reason)

    def _mark_jit_entries(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = unwrap_partial(dec) if isinstance(
                        dec, ast.Call) else dec
                    tail = name_tail(target)
                    if tail in _JIT_TAILS:
                        info = self._info_for_def(node)
                        if info and "jit-decorated" not in info.jit_reasons:
                            info.jit_reasons.append("jit-decorated")
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            tail = cn.split(".")[-1] if cn else None
            if tail in _JIT_TAILS and node.args:
                self._mark(node.args[0], "jax.jit")
            elif tail in _TRACED_CALLEE_ARGS:
                for i in _TRACED_CALLEE_ARGS[tail]:
                    if i < len(node.args):
                        self._mark(node.args[i], f"{tail} body")
            elif tail == "pallas_call" and node.args:
                self._mark(node.args[0], "pallas kernel")

    def _info_for_def(self, node: ast.AST) -> Optional[FunctionInfo]:
        for info in self.functions.values():
            if info.node is node:
                return info
        return None

    # --------------------------------------------------------- reachability

    def _compute_reachable(self) -> Dict[str, List[str]]:
        """qualname -> chain of reasons, for every function reachable from
        a jit entry point (through calls or lexical nesting)."""
        reach: Dict[str, List[str]] = {}
        work: List[Tuple[str, List[str]]] = []
        for qn, info in self.functions.items():
            if info.jit_reasons:
                reach[qn] = list(info.jit_reasons)
                work.append((qn, reach[qn]))
        while work:
            qn, chain = work.pop()
            nxt: Set[str] = set()
            for tail in self._calls.get(qn, ()):
                for info in self.by_name.get(tail, ()):
                    nxt.add(info.qualname)
            # nested defs of a traced function execute at trace time
            # (``@pl.when`` bodies, scan-step closures)
            for sub, info in self.functions.items():
                if info.parent == qn:
                    nxt.add(sub)
            for sub in nxt:
                if self.functions[sub].host_boundary:
                    continue     # declared never-traced: edge stops here
                if sub not in reach:
                    reach[sub] = chain + [f"called from {qn}"]
                    work.append((sub, reach[sub]))
        return reach

    def jit_reachable(self) -> Iterator[Tuple[FunctionInfo, List[str]]]:
        for qn, chain in self.reachable.items():
            yield self.functions[qn], chain

    # ------------------------------------------------------------- findings

    def finding(self, rule, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule.name, severity=rule.severity, path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message, scope=self.scope_of(node),
            end_line=getattr(node, "end_lineno", 0)
            or getattr(node, "lineno", 1))
