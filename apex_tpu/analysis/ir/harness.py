"""Entry-point discovery + jaxpr construction for the tpu-lint IR tier.

The AST tier reads what the code *says*; this tier reads what JAX
actually *stages*. :func:`analysis_cases` is the declarative registry of
traceable entry points — every ``tpu_aot.kernel_cases()`` program
(kernels, fused optimizers, the lock-step decode programs, the
prefix-cached admission) plus serving programs the AOT sweep does not
carry: the engine's jitted multi-step decode chunk (the
``generate(paged=True)`` hot loop) and the bucketed admission program
with its compile-count contract. :func:`build_case_ir` turns one case
into a :class:`CaseIR` via ``jax.make_jaxpr`` over
``jax.ShapeDtypeStruct`` arguments — pure tracing, no TPU, no compile;
it runs in tier-1 on CPU in seconds.

Tracing forces ``APEX_TPU_FORCE_MOSAIC=1`` so ``ops/_dispatch`` stages
the real Pallas programs (the TPU path), not the CPU interpret fallback
— the jaxpr the rules see is the jaxpr the chip would get.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import sys
from pathlib import Path
from typing import Callable, List, Optional, Sequence, Tuple

#: byte size guards shared with ir_rules (import cycle-free home)
MIB = 1024 * 1024


@dataclasses.dataclass
class CaseProgram:
    """One traceable program: ``fn(*args)`` with abstract args."""

    fn: Callable
    args: tuple
    donate: Tuple[int, ...] = ()
    #: additional argument tuples that MUST trace to at most
    #: ``max_traces`` distinct jaxprs together with ``args`` — the
    #: compile-key-cardinality contract (bucketed shapes collapse)
    variants: Sequence[tuple] = ()
    max_traces: int = 1
    x64: bool = False
    #: builder-supplied side facts consumers cannot recover from the
    #: jaxpr (e.g. the TP cases' sharded/replicated weight-byte split —
    #: ``obs/costs.py`` prices per-chip HBM from it)
    meta: Optional[dict] = None


@dataclasses.dataclass
class AnalysisCase:
    name: str
    domain: str                      # serving | models | ops | optimizers
    build: Callable[[], CaseProgram]


@dataclasses.dataclass
class CaseIR:
    """A traced case: the jaxpr bundle the IR rules consume."""

    case: AnalysisCase
    prog: CaseProgram
    closed: object                   # jax ClosedJaxpr
    variant_closed: List[object]
    donated_avals: List[object]      # flattened avals of donated args
    origin: Tuple[str, int]          # (abs file, line) of the case fn

    @property
    def name(self) -> str:
        return self.case.name

    @property
    def domain(self) -> str:
        return self.case.domain


def _origin_of(fn) -> Tuple[str, int]:
    """Best-effort def site of the case's program (partials and jit
    wrappers unwrapped) — the anchor for findings that have no single
    equation (donation, consts, cardinality)."""
    seen = 0
    while seen < 8:
        seen += 1
        if isinstance(fn, functools.partial):
            fn = fn.func
            continue
        inner = getattr(fn, "__wrapped__", None)
        if inner is not None and inner is not fn:
            fn = inner
            continue
        break
    code = getattr(fn, "__code__", None)
    if code is not None:
        return (code.co_filename, code.co_firstlineno)
    return (__file__, 1)


# --------------------------------------------------------------------------
# case registry
# --------------------------------------------------------------------------

#: kernel_cases() name -> domain (prefix match, first hit wins); the AOT
#: registry spans ops, optimizers, models and serving already — the IR
#: tier reuses it verbatim rather than maintaining a parallel list
_DOMAIN_PREFIXES = (
    ("optim_", "optimizers"),
    ("gpt2_small_decode", "models"),
    ("gpt2s_prefix_cached", "serving"),
    ("paged_attention", "serving"),
)


#: kernel_cases() names that analysis_cases() re-registers with a richer
#: CaseProgram (variants / max_traces); _aot_cases skips them so each
#: name appears exactly once in the registry
_RICHER_REGISTRATIONS = frozenset({
    "gpt2s_host_tier_gather",
    "gpt2s_host_tier_promote",
})


def _domain_for(name: str) -> str:
    for prefix, domain in _DOMAIN_PREFIXES:
        if name.startswith(prefix):
            return domain
    return "ops"


def _aot_cases(root: Path) -> List[AnalysisCase]:
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))
    # tpu_aot flips the PROCESS into Mosaic dispatch at import (its own
    # runs are all-AOT); an in-process lint consumer (the tier-1 suite)
    # must get its env back — only _force_mosaic's tracing window may
    # keep the flag
    with _force_mosaic():
        import tpu_aot

        cases = list(tpu_aot.kernel_cases())

    out: List[AnalysisCase] = []
    for case in cases:
        name, fn, args = case[0], case[1], tuple(case[2])
        if name in _RICHER_REGISTRATIONS:
            # analysis_cases() appends these by hand with variants and a
            # max_traces pin (the compile-key-cardinality probe) that the
            # bare AOT tuple can't carry — one registration per name, the
            # richer one wins
            continue
        donate = tuple(case[3]) if len(case) > 3 else ()

        def build(fn=fn, args=args, donate=donate) -> CaseProgram:
            return CaseProgram(fn=fn, args=args, donate=donate)

        out.append(AnalysisCase(name=name, domain=_domain_for(name),
                                build=build))
    return out


def _build_engine_chunk() -> CaseProgram:
    """The serving hot loop ``generate(paged=True)`` actually runs: the
    engine's jitted ``sync_every``-step ``lax.scan`` decode chunk, at a
    small GPT-2-small pool (tracing cost, not fidelity, scales with the
    pool)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.serving.scheduler import PagedDecodeEngine

    cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=4,
                               page_size=16, num_pages=33,
                               max_pages_per_seq=16, sync_every=4)
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    i32 = jnp.int32
    args = (cache_abs, dvars,
            jax.ShapeDtypeStruct((4,), i32),        # tok
            jax.ShapeDtypeStruct((4,), jnp.bool_),  # done
            jax.ShapeDtypeStruct((4,), i32),        # n_left
            jax.ShapeDtypeStruct((4, 2), jnp.uint32),  # req_keys
            jax.ShapeDtypeStruct((4,), i32))        # samp_i
    return CaseProgram(fn=engine._step_fn(), args=args)


def _weight_bytes(tree) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        n = 1
        for d in leaf.shape:
            n *= int(d)
        total += n * leaf.dtype.itemsize
    return total


def _build_spec_engine_program() -> CaseProgram:
    """The IN-ENGINE speculative decode chunk (ISSUE 13): the jitted
    ``sync_every``-round scan where each round runs ``draft_len``
    single-token draft steps over the DRAFT pool and verifies the block
    in ONE ``s = draft_len + 1`` paged target step. The draft is a
    1-layer gpt2s-dims model — the shape regime where the round's
    weight stream (W_target + k * W_draft) amortized over >= 2 accepted
    tokens beats the non-speculative per-token stream, which
    ``obs/costs.py`` prices from this case's ``meta``. The two variants
    pin that per-slot decode state (tok/done/n_left) is TRACED, never a
    compile key: concrete values and abstract structs must stage ONE
    program."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.serving.scheduler import PagedDecodeEngine

    cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    dcfg = _dc.replace(cfg, num_layers=1)
    draft = GPTModel(dcfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=4,
                               page_size=16, num_pages=33,
                               max_pages_per_seq=16, sync_every=4,
                               draft_model=draft, draft_variables=None,
                               draft_len=1)
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    dcache_abs = jax.tree.map(sds, engine.draft_cache)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    ddvars = jax.eval_shape(lambda: draft.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    i32 = jnp.int32
    args = (cache_abs, dcache_abs, dvars, ddvars,
            jax.ShapeDtypeStruct((4,), i32),        # tok (pending)
            jax.ShapeDtypeStruct((4,), jnp.bool_),  # done
            jax.ShapeDtypeStruct((4,), i32))        # n_left
    variant = (cache_abs, dcache_abs, dvars, ddvars,
               np.zeros((4,), np.int32), np.zeros((4,), bool),
               np.full((4,), 7, np.int32))
    meta = {"draft_len": engine.draft_len, "k": engine.draft_len + 1,
            "sync_every": engine.sync_every,
            "target_weight_bytes": _weight_bytes(dvars),
            "draft_weight_bytes": _weight_bytes(ddvars)}
    return CaseProgram(fn=engine._spec_step_fn(), args=args,
                       variants=[variant], max_traces=1, meta=meta)


def _build_prefill_chunk_program() -> CaseProgram:
    """The chunked-prefill step (ISSUE 13): one 16-token prompt chunk
    of one slot through the paged s>1 path. The two variants trace the
    program at concrete ``valid`` counts 5 and 7 — the chunk's true
    token count is a TRACED operand, so every prompt length shares ONE
    staged program per engine (the compile-key contract that lets the
    frontend interleave prefill chunks between decode chunks without
    recompiling)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.serving.scheduler import PagedDecodeEngine

    cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=4,
                               page_size=16, num_pages=33,
                               max_pages_per_seq=16, prefill_chunk=16)
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    i32 = jnp.int32
    args = (cache_abs, dvars,
            jax.ShapeDtypeStruct((1, 16), i32),     # chunk ids
            jax.ShapeDtypeStruct((), i32),          # slot
            jax.ShapeDtypeStruct((), i32),          # valid
            jax.ShapeDtypeStruct((2,), jnp.uint32),  # req_key
            jax.ShapeDtypeStruct((), i32))          # samp0

    def variant_for(valid: int) -> tuple:
        return (cache_abs, dvars,
                np.zeros((1, 16), np.int32), np.int32(0),
                np.int32(valid), np.zeros((2,), np.uint32), np.int32(0))

    return CaseProgram(fn=engine._prefill_chunk_fn(), args=args,
                       variants=[variant_for(5), variant_for(7)],
                       max_traces=1)


def _build_host_tier_program(kind: str) -> CaseProgram:
    """The tiered KV pool's two device programs (ISSUE 17): the
    demote-side ``gather_pages`` (a pure READ — the cache is NOT
    donated; donating it would free the pool out from under the engine,
    which the aliasing rule must be able to see) and the promote-side
    ``promote_pages`` (cache donated, like every pool-mutating
    program). Both take a fixed null-padded ``HOST_COPY_CHUNK`` page
    row plus a traced count: demote/promote DEPTH is data, never a
    compile key — the two variants build their rows at different depths
    the way the frontend does and must collapse to one jaxpr, so a
    refactor that sizes the row by depth trips
    ir-compile-key-cardinality."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.serving import kv_pool
    from apex_tpu.serving.scheduler import PagedDecodeEngine

    cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=4,
                               page_size=16, num_pages=33,
                               max_pages_per_seq=16, prefix_cache=True,
                               host_tier_bytes=1 << 24)
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    C = kv_pool.HOST_COPY_CHUNK

    def row_for(depth: int):
        row = np.zeros((C,), np.int32)
        row[:depth] = np.arange(1, depth + 1)
        return jnp.asarray(row)

    if kind == "gather":
        return CaseProgram(fn=engine._gather_jit,
                           args=(cache_abs, row_for(3)),
                           variants=[(cache_abs, row_for(7))],
                           max_traces=1)
    tiles_abs = jax.tree.map(sds, jax.eval_shape(
        kv_pool.gather_pages, cache_abs, row_for(3)))

    def args_for(depth: int) -> tuple:
        return (cache_abs, row_for(depth), jnp.int32(depth), tiles_abs)

    return CaseProgram(fn=engine._promote_jit, args=args_for(3),
                       variants=[args_for(7)], donate=(0,),
                       max_traces=1)


def _build_admit_bucketed() -> CaseProgram:
    """The engine's prompt-admission program, traced at two prompt
    lengths that land in the SAME bucket under the ENGINE'S OWN
    ``scheduler.prompt_bucket`` (the function ``run()`` pads with before
    its jit boundary — shared, not mirrored, so the contract is binding:
    if admission's bucketing ever stops collapsing raw lengths, the two
    variants stage distinct programs and ir-compile-key-cardinality
    fires)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.serving.scheduler import (PagedDecodeEngine,
                                            prompt_bucket)

    cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=4,
                               page_size=16, num_pages=33,
                               max_pages_per_seq=16)
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    i32 = jnp.int32

    def args_for(s0: int) -> tuple:
        bucket = prompt_bucket(s0, engine.page_size,
                               cfg.max_position_embeddings)
        return (cache_abs, dvars,
                jax.ShapeDtypeStruct((1, bucket), i32),   # padded ids
                jax.ShapeDtypeStruct((), i32),            # s0
                jax.ShapeDtypeStruct((), i32),            # slot
                jax.ShapeDtypeStruct((), i32),            # n_pages
                jax.ShapeDtypeStruct((2,), jnp.uint32),   # req_key
                jax.ShapeDtypeStruct((), i32))            # samp0
    bucket = prompt_bucket(90, engine.page_size,
                           cfg.max_position_embeddings)
    return CaseProgram(fn=engine._admit_fn(bucket), args=args_for(90),
                       variants=[args_for(93)], max_traces=1)


def _build_int8kv_engine_program(kind: str) -> CaseProgram:
    """The QUANTIZED-KV engine programs (docs/serving.md "Quantized KV
    pages"): the ``sync_every``-step decode chunk and the bucketed
    admission over an int8 page pool — the decode chunk stages the
    paged kernel WITH its per-(page, kv_head) scale operands and
    in-kernel dequant, the admission the quantize-on-write prefill
    scatter. Same compile-key contract as the fp cases (two same-bucket
    admission variants, ``max_traces=1``); ``obs/costs.py`` reads the
    decode chunk's abstract pool to price the narrow KV stream
    (``cost.decode.int8_kv.*``)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.serving.scheduler import (PagedDecodeEngine,
                                            prompt_bucket)

    cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=4,
                               page_size=16, num_pages=33,
                               max_pages_per_seq=16, sync_every=4,
                               kv_dtype="int8")
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    i32 = jnp.int32
    if kind == "decode":
        args = (cache_abs, dvars,
                jax.ShapeDtypeStruct((4,), i32),           # tok
                jax.ShapeDtypeStruct((4,), jnp.bool_),     # done
                jax.ShapeDtypeStruct((4,), i32),           # n_left
                jax.ShapeDtypeStruct((4, 2), jnp.uint32),  # req_keys
                jax.ShapeDtypeStruct((4,), i32))           # samp_i
        return CaseProgram(fn=engine._step_fn(), args=args)
    assert kind == "admit"

    def args_for(s0: int) -> tuple:
        bucket = prompt_bucket(s0, engine.page_size,
                               cfg.max_position_embeddings)
        return (cache_abs, dvars,
                jax.ShapeDtypeStruct((1, bucket), i32),   # padded ids
                jax.ShapeDtypeStruct((), i32),            # s0
                jax.ShapeDtypeStruct((), i32),            # slot
                jax.ShapeDtypeStruct((), i32),            # n_pages
                jax.ShapeDtypeStruct((2,), jnp.uint32),   # req_key
                jax.ShapeDtypeStruct((), i32))            # samp0
    bucket = prompt_bucket(90, engine.page_size,
                           cfg.max_position_embeddings)
    return CaseProgram(fn=engine._admit_fn(bucket), args=args_for(90),
                       variants=[args_for(93)], max_traces=1)


def _build_wq_engine_program(kind: str, policy: str) -> CaseProgram:
    """The QUANTIZED-WEIGHT engine programs (docs/serving.md "Quantized
    weight streaming"): the ``sync_every``-step decode chunk and the
    bucketed admission over a gpt2-small built with a
    ``WeightPrecisionPolicy`` — every block linear stages the fused
    dequant-matmul Pallas kernel (narrow weight + scale operands,
    dequant in VMEM next to the contraction), embeddings/norms/head
    stay fp. ``policy="int4"`` also drops the fp leaves to bf16 (the
    documented aggressive pairing). Same compile-key contract as the fp
    cases (two same-bucket admission variants, ``max_traces=1``);
    ``obs/costs.py`` reads the decode chunk's abstract weight tree to
    price the narrow stream (``cost.decode.w8.*`` / ``cost.decode.w4.*``
    — per-LEAF dtype bytes, scale reads included)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.ops.quant import WeightPrecisionPolicy
    from apex_tpu.serving.scheduler import (PagedDecodeEngine,
                                            prompt_bucket)

    extra = {"param_dtype": jnp.bfloat16} if policy == "int4" else {}
    cfg = gpt2_small_config(dtype=jnp.bfloat16,
                            weight_policy=WeightPrecisionPolicy(policy),
                            **extra)
    model = GPTModel(cfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=4,
                               page_size=16, num_pages=33,
                               max_pages_per_seq=16, sync_every=4)
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    i32 = jnp.int32
    if kind == "decode":
        args = (cache_abs, dvars,
                jax.ShapeDtypeStruct((4,), i32),           # tok
                jax.ShapeDtypeStruct((4,), jnp.bool_),     # done
                jax.ShapeDtypeStruct((4,), i32),           # n_left
                jax.ShapeDtypeStruct((4, 2), jnp.uint32),  # req_keys
                jax.ShapeDtypeStruct((4,), i32))           # samp_i
        return CaseProgram(fn=engine._step_fn(), args=args)
    assert kind == "admit"

    def args_for(s0: int) -> tuple:
        bucket = prompt_bucket(s0, engine.page_size,
                               cfg.max_position_embeddings)
        return (cache_abs, dvars,
                jax.ShapeDtypeStruct((1, bucket), i32),   # padded ids
                jax.ShapeDtypeStruct((), i32),            # s0
                jax.ShapeDtypeStruct((), i32),            # slot
                jax.ShapeDtypeStruct((), i32),            # n_pages
                jax.ShapeDtypeStruct((2,), jnp.uint32),   # req_key
                jax.ShapeDtypeStruct((), i32))            # samp0
    bucket = prompt_bucket(90, engine.page_size,
                           cfg.max_position_embeddings)
    return CaseProgram(fn=engine._admit_fn(bucket), args=args_for(90),
                       variants=[args_for(93)], max_traces=1)


def _build_frontend_program(kind: str) -> CaseProgram:
    """The serving FRONT-END's programs, bound through its own accessors
    (``ServingFrontend.admission_program`` / ``decode_program``) rather
    than the engine internals they delegate to — if the frontend's pump
    ever grows its own bucketing or decode wrapper, these cases trace
    what it actually dispatches, and ``ir-compile-key-cardinality``
    keeps binding the served compile-key contract."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.serving.frontend import ServingFrontend
    from apex_tpu.serving.scheduler import PagedDecodeEngine

    cfg = gpt2_small_config(dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=4,
                               page_size=16, num_pages=33,
                               max_pages_per_seq=16, sync_every=4)
    frontend = ServingFrontend(engine)
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((4, 8), jnp.int32)))
    i32 = jnp.int32
    if kind == "decode":
        args = (cache_abs, dvars,
                jax.ShapeDtypeStruct((4,), i32),           # tok
                jax.ShapeDtypeStruct((4,), jnp.bool_),     # done
                jax.ShapeDtypeStruct((4,), i32),           # n_left
                jax.ShapeDtypeStruct((4, 2), jnp.uint32),  # req_keys
                jax.ShapeDtypeStruct((4,), i32))           # samp_i
        return CaseProgram(fn=frontend.decode_program(), args=args)
    assert kind == "admit"

    def args_for(s0: int) -> tuple:
        _, bucket = frontend.admission_program(s0)
        return (cache_abs, dvars,
                jax.ShapeDtypeStruct((1, bucket), i32),   # padded ids
                jax.ShapeDtypeStruct((), i32),            # s0
                jax.ShapeDtypeStruct((), i32),            # slot
                jax.ShapeDtypeStruct((), i32),            # n_pages
                jax.ShapeDtypeStruct((2,), jnp.uint32),   # req_key
                jax.ShapeDtypeStruct((), i32))            # samp0
    fn, _ = frontend.admission_program(90)
    return CaseProgram(fn=fn, args=args_for(90), variants=[args_for(93)],
                       max_traces=1)


def _build_llama_windowed_program(kind: str) -> CaseProgram:
    """The windowed-Llama PAGED serving programs (the model-coverage gap
    ISSUE 9 closed): the engine's admission + ``sync_every``-step decode
    chunk over a sliding-window tiny-Llama pool — the decode chunk
    stages the band-gated paged-attention kernel, the admission the
    window-banded flash prefill. Same compile-key contract as the GPT
    cases (two same-bucket admission variants, ``max_traces=1``)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.llama import LlamaModel, llama_tiny_config
    from apex_tpu.serving.scheduler import (PagedDecodeEngine,
                                            prompt_bucket)

    cfg = llama_tiny_config(sliding_window=16)
    model = LlamaModel(cfg)
    engine = PagedDecodeEngine(model, variables=None, num_slots=2,
                               page_size=8, num_pages=17,
                               max_pages_per_seq=8, sync_every=2)
    sds = lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)  # noqa: E731
    cache_abs = jax.tree.map(sds, engine.cache)
    dvars = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)))
    i32 = jnp.int32
    if kind == "decode":
        args = (cache_abs, dvars,
                jax.ShapeDtypeStruct((2,), i32),           # tok
                jax.ShapeDtypeStruct((2,), jnp.bool_),     # done
                jax.ShapeDtypeStruct((2,), i32),           # n_left
                jax.ShapeDtypeStruct((2, 2), jnp.uint32),  # req_keys
                jax.ShapeDtypeStruct((2,), i32))           # samp_i
        return CaseProgram(fn=engine._step_fn(), args=args)
    assert kind == "admit"

    def args_for(s0: int) -> tuple:
        bucket = prompt_bucket(s0, engine.page_size,
                               cfg.max_position_embeddings)
        return (cache_abs, dvars,
                jax.ShapeDtypeStruct((1, bucket), i32),   # padded ids
                jax.ShapeDtypeStruct((), i32),            # s0
                jax.ShapeDtypeStruct((), i32),            # slot
                jax.ShapeDtypeStruct((), i32),            # n_pages
                jax.ShapeDtypeStruct((2,), jnp.uint32),   # req_key
                jax.ShapeDtypeStruct((), i32))            # samp0
    bucket = prompt_bucket(20, engine.page_size,
                           cfg.max_position_embeddings)
    return CaseProgram(fn=engine._admit_fn(bucket), args=args_for(20),
                       variants=[args_for(22)], max_traces=1)


def _build_tp_engine_program(kind: str, kv_dtype=None,
                             weight_policy=None) -> CaseProgram:
    """The TENSOR-PARALLEL serving programs (serving/tp.py,
    docs/tp_serving.md): the tp=2 engine's shard_map-wrapped admission
    and ``sync_every``-step decode chunk, traced over a deviceless
    ``AbstractMesh`` — the shard_map body (local-head paged attention,
    Megatron collectives, replicated pool bookkeeping) is exactly the
    dtype-drift and compile-key-cardinality surface this tier exists
    for, and it must lint on any host with any device count. Same
    bucketing contract as the single-chip cases (two same-bucket
    admission variants, ``max_traces=1``, bound through the engine's
    own ``prompt_bucket``/``_admit_fn``)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTModel, gpt2_small_config
    from apex_tpu.serving.scheduler import prompt_bucket
    from apex_tpu.serving.tp import (TensorParallelPagedEngine,
                                     abstract_tp_mesh,
                                     infer_variable_specs)

    tp = 2
    pol = None
    if weight_policy is not None:
        from apex_tpu.ops.quant import WeightPrecisionPolicy
        pol = WeightPrecisionPolicy(weight_policy)
    cfg = gpt2_small_config(dtype=jnp.bfloat16, tensor_parallel_size=tp,
                            weight_policy=pol)
    model = GPTModel(cfg)
    engine = TensorParallelPagedEngine(
        model, variables=None, mesh=abstract_tp_mesh(tp), num_slots=4,
        page_size=16, num_pages=33, max_pages_per_seq=16, sync_every=4,
        kv_dtype=kv_dtype)
    dvars, var_specs = infer_variable_specs(model)

    def _bytes(leaf):
        n = 1
        for d in leaf.shape:
            n *= int(d)
        return n * leaf.dtype.itemsize

    sharded = repl = 0
    # PartitionSpec is an unregistered type, i.e. a pytree LEAF — the
    # two leaf lists align one-to-one
    for leaf, spec in zip(jax.tree.leaves(dvars),
                          jax.tree.leaves(var_specs)):
        if any(s is not None for s in spec):
            sharded += _bytes(leaf)
        else:
            repl += _bytes(leaf)
    # the declared sharding contract: the mem tier's spec rules
    # (mem-spec-indivisible & co.) check these against the mesh before
    # shard_map ever traces, and its HBM sweep scopes to per-chip bytes
    from jax.sharding import PartitionSpec as P

    meta = {"tp": tp, "sharded_weight_bytes": sharded,
            "replicated_weight_bytes": repl,
            "mesh_axes": {"model": tp}}
    i32 = jnp.int32
    if kind == "decode":
        args = (engine.cache, dvars,
                jax.ShapeDtypeStruct((4,), i32),           # tok
                jax.ShapeDtypeStruct((4,), jnp.bool_),     # done
                jax.ShapeDtypeStruct((4,), i32),           # n_left
                jax.ShapeDtypeStruct((4, 2), jnp.uint32),  # req_keys
                jax.ShapeDtypeStruct((4,), i32))           # samp_i
        meta["arg_specs"] = (engine._cache_specs, var_specs,
                             P(), P(), P(), P(), P())
        return CaseProgram(fn=engine._step_fn(), args=args, meta=meta)
    assert kind == "admit"
    meta["arg_specs"] = (engine._cache_specs, var_specs,
                         P(), P(), P(), P(), P(), P())

    def args_for(s0: int) -> tuple:
        bucket = prompt_bucket(s0, engine.page_size,
                               cfg.max_position_embeddings)
        return (engine.cache, dvars,
                jax.ShapeDtypeStruct((1, bucket), i32),   # padded ids
                jax.ShapeDtypeStruct((), i32),            # s0
                jax.ShapeDtypeStruct((), i32),            # slot
                jax.ShapeDtypeStruct((), i32),            # n_pages
                jax.ShapeDtypeStruct((2,), jnp.uint32),   # req_key
                jax.ShapeDtypeStruct((), i32))            # samp0
    bucket = prompt_bucket(90, engine.page_size,
                           cfg.max_position_embeddings)
    return CaseProgram(fn=engine._admit_fn(bucket), args=args_for(90),
                       variants=[args_for(93)], max_traces=1, meta=meta)


def _build_optimizer_update(kind: str) -> CaseProgram:
    """sgd/novograd fused-update steps over the flat-buffer layout
    (adam/lamb already arrive via ``kernel_cases``)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops import flat_buffer, optim_kernels

    f32 = jnp.float32
    tree = {"emb": (8192, 64), "w1": (768, 768), "b": (768,)}
    spec = flat_buffer.build_spec(
        {k: jax.ShapeDtypeStruct(s, f32) for k, s in tree.items()})
    seg = np.asarray(spec.segment_rows())
    buf = jax.ShapeDtypeStruct((spec.total_rows, flat_buffer.LANE), f32)
    if kind == "sgd":
        fn = functools.partial(optim_kernels.sgd_update, lr=1e-3,
                               momentum=0.9, weight_decay=1e-4)
        return CaseProgram(fn=fn, args=(buf, buf, buf), donate=(1, 2))
    assert kind == "novograd"

    def nv(g, p, m, v):
        return optim_kernels.novograd_update(
            g, p, m, v, jnp.asarray(seg), spec.num_tensors, beta1=0.95,
            beta2=0.98, eps=1e-8, weight_decay=1e-3, lr=1e-3, step=1)

    vbuf = jax.ShapeDtypeStruct((spec.num_tensors,), f32)
    return CaseProgram(fn=nv, args=(buf, buf, buf, vbuf),
                       donate=(1, 2, 3))


def analysis_cases(root) -> List[AnalysisCase]:
    """The IR tier's registry: every AOT kernel case + the serving-engine
    programs + the remaining fused-optimizer steps. Spans serving,
    models, ops and optimizers (asserted by the tier-1 suite)."""
    root = Path(root).resolve()
    cases = _aot_cases(root)
    cases.append(AnalysisCase("gpt2s_engine_decode_chunk", "serving",
                              _build_engine_chunk))
    cases.append(AnalysisCase("gpt2s_engine_admit_bucketed", "serving",
                              _build_admit_bucketed))
    cases.append(AnalysisCase("gpt2s_engine_spec_step_chunk", "serving",
                              _build_spec_engine_program))
    cases.append(AnalysisCase("gpt2s_engine_prefill_chunk", "serving",
                              _build_prefill_chunk_program))
    cases.append(AnalysisCase(
        "gpt2s_frontend_decode_chunk", "serving",
        lambda: _build_frontend_program("decode")))
    cases.append(AnalysisCase(
        "gpt2s_frontend_admit_bucketed", "serving",
        lambda: _build_frontend_program("admit")))
    cases.append(AnalysisCase(
        "llama_windowed_engine_decode_chunk", "serving",
        lambda: _build_llama_windowed_program("decode")))
    cases.append(AnalysisCase(
        "llama_windowed_engine_admit_bucketed", "serving",
        lambda: _build_llama_windowed_program("admit")))
    cases.append(AnalysisCase(
        "tp2_engine_decode_chunk", "serving",
        lambda: _build_tp_engine_program("decode")))
    cases.append(AnalysisCase(
        "tp2_engine_admit_bucketed", "serving",
        lambda: _build_tp_engine_program("admit")))
    cases.append(AnalysisCase(
        "gpt2s_host_tier_gather", "serving",
        lambda: _build_host_tier_program("gather")))
    cases.append(AnalysisCase(
        "gpt2s_host_tier_promote", "serving",
        lambda: _build_host_tier_program("promote")))
    cases.append(AnalysisCase(
        "gpt2s_int8kv_engine_decode_chunk", "serving",
        lambda: _build_int8kv_engine_program("decode")))
    cases.append(AnalysisCase(
        "gpt2s_int8kv_engine_admit_bucketed", "serving",
        lambda: _build_int8kv_engine_program("admit")))
    cases.append(AnalysisCase(
        "tp2_int8kv_engine_decode_chunk", "serving",
        lambda: _build_tp_engine_program("decode", kv_dtype="int8")))
    cases.append(AnalysisCase(
        "gpt2s_w8_engine_decode_chunk", "serving",
        lambda: _build_wq_engine_program("decode", "int8")))
    cases.append(AnalysisCase(
        "gpt2s_w8_engine_admit_bucketed", "serving",
        lambda: _build_wq_engine_program("admit", "int8")))
    cases.append(AnalysisCase(
        "gpt2s_w4_engine_decode_chunk", "serving",
        lambda: _build_wq_engine_program("decode", "int4")))
    cases.append(AnalysisCase(
        "tp2_w8_engine_decode_chunk", "serving",
        lambda: _build_tp_engine_program("decode", weight_policy="int8")))
    cases.append(AnalysisCase(
        "optim_sgd_momentum_buffer", "optimizers",
        lambda: _build_optimizer_update("sgd")))
    cases.append(AnalysisCase(
        "optim_novograd_buffer", "optimizers",
        lambda: _build_optimizer_update("novograd")))
    return cases


# --------------------------------------------------------------------------
# tracing
# --------------------------------------------------------------------------

class _force_mosaic:
    """Stage the TPU kernel path during tracing regardless of the host
    backend (see module docstring); restores the env on exit.

    Exit also clears jax's trace caches: tracing through module-level
    jit wrappers bakes ``interpret=False`` pallas params into their
    cached jaxprs, and an in-process consumer (the tier-1 suite)
    EXECUTING the same op at the same shapes afterwards would reuse the
    poisoned trace and fail on CPU. Dropping the caches costs a
    re-trace, never correctness."""

    _KEYS = ("APEX_TPU_FORCE_MOSAIC", "APEX_TPU_FORCE_INTERPRET")

    def __enter__(self):
        self._old = {k: os.environ.get(k) for k in self._KEYS}
        os.environ["APEX_TPU_FORCE_MOSAIC"] = "1"
        os.environ.pop("APEX_TPU_FORCE_INTERPRET", None)

    def __exit__(self, *exc):
        for k, v in self._old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        import jax

        jax.clear_caches()
        return False


def _trace(prog: CaseProgram, args: tuple):
    import contextlib

    import jax

    ctx = jax.experimental.enable_x64() if prog.x64 \
        else contextlib.nullcontext()
    with _force_mosaic(), ctx:
        return jax.make_jaxpr(prog.fn)(*args)


def build_case_ir(case: AnalysisCase) -> CaseIR:
    """Trace one case (plus its cardinality variants) into a CaseIR."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")   # before jax wakes up
    import jax

    prog = case.build()
    closed = _trace(prog, prog.args)
    variant_closed = [_trace(prog, v) for v in prog.variants]
    donated = []
    for i in prog.donate:
        if 0 <= i < len(prog.args):
            # leaves are ShapeDtypeStructs/arrays: shape+dtype is all the
            # aliasing check needs
            donated.extend(jax.tree.leaves(prog.args[i]))
    return CaseIR(case=case, prog=prog, closed=closed,
                  variant_closed=variant_closed, donated_avals=donated,
                  origin=_origin_of(prog.fn))
