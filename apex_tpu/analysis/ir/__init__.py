"""tpu-lint IR tier: jaxpr-level semantic analysis of real entry points.

The AST tier (``apex_tpu.analysis.rules``) lints what the source says;
this tier lints what JAX stages. ``harness.analysis_cases()`` discovers
traceable entry points (every ``tpu_aot.kernel_cases()`` program plus
the serving engine's decode chunk and bucketed admission), builds their
jaxprs on CPU (``jax.make_jaxpr`` over ``ShapeDtypeStruct`` args — no
TPU, no compile), ``ir_rules`` checks them (dtype promotion drift, dead
outputs/scan carries, ineffective donation, large closed-over
constants, broadcast blowup, effectful primitives in scan bodies,
compile-key cardinality, minor-dim transposes feeding Pallas), and
``ir_report`` maps every finding back to source via ``eqn.source_info``
— file:line-addressable and suppressible with the ordinary
``# tpu-lint: disable=RULE`` pragma.

Usage::

    python -m apex_tpu.analysis --ir              # the whole registry
    python -m apex_tpu.analysis --ir-case NAME    # one entry point
    python -m apex_tpu.analysis --ir --select ir-dead-scan-carry
"""

from apex_tpu.analysis.ir.harness import (AnalysisCase, CaseIR,
                                          CaseProgram, analysis_cases,
                                          build_case_ir)
from apex_tpu.analysis.ir.ir_report import analyze_ir, findings_for_case
from apex_tpu.analysis.ir.ir_rules import IR_RULES

__all__ = [
    "AnalysisCase",
    "CaseIR",
    "CaseProgram",
    "IR_RULES",
    "analysis_cases",
    "analyze_ir",
    "build_case_ir",
    "findings_for_case",
]
