"""Source mapping + orchestration for the tpu-lint IR tier.

Jaxpr equations carry ``source_info`` tracebacks; :func:`eqn_anchor`
maps each finding back to the innermost frame inside the repo, so IR
findings are file:line-addressable exactly like AST ones — and
suppressible with the same ``# tpu-lint: disable=RULE`` pragmas, read
from the anchored file. Findings with no single equation (donation,
closed-over constants, trace cardinality) anchor at the case function's
definition site.

:func:`analyze_ir` is the tier's engine: build the case registry, trace
each case, run the selected IR rules, apply inline suppressions.
Baseline handling stays in the CLI (same split as the AST tier).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from apex_tpu.analysis.ir.harness import (AnalysisCase, CaseIR,
                                          analysis_cases, build_case_ir)
from apex_tpu.analysis.ir.ir_rules import IR_RULES
from apex_tpu.analysis.suppressions import Suppressions
from apex_tpu.analysis.walker import Finding


def _rel_to(root: Path, filename: str) -> Optional[str]:
    try:
        return Path(filename).resolve().relative_to(root).as_posix()
    except (ValueError, OSError):
        return None


def eqn_anchor(eqn, root: Path) -> Optional[Tuple[str, int]]:
    """(repo-relative path, line) of the innermost user frame under
    ``root`` for one equation, or None (e.g. jax-internal synthesized
    eqns)."""
    try:
        from jax._src import source_info_util as siu

        frames = siu.user_frames(eqn.source_info)
    except Exception:
        return None
    for frame in frames:
        rel = _rel_to(root, frame.file_name)
        if rel is not None and frame.start_line:
            return (rel, int(frame.start_line))
    return None


def _case_anchor(ir: CaseIR, root: Path) -> Tuple[str, int]:
    rel = _rel_to(root, ir.origin[0])
    if rel is not None:
        return (rel, ir.origin[1])
    # a case defined outside the repo (shouldn't happen) still needs a
    # stable, baseline-able path
    return (Path(ir.origin[0]).name, ir.origin[1])


class _SuppressionCache:
    """Suppressions per anchored file, loaded lazily from disk."""

    def __init__(self, root: Path):
        self.root = root
        self._cache: Dict[str, Suppressions] = {}

    def get(self, rel: str) -> Suppressions:
        if rel not in self._cache:
            try:
                src = (self.root / rel).read_text()
            except OSError:
                src = ""
            self._cache[rel] = Suppressions(src)
        return self._cache[rel]


def findings_for_case(ir: CaseIR, root: Path,
                      select: Optional[Iterable[str]] = None
                      ) -> List[Finding]:
    """Run the (selected) IR rules over one traced case; findings carry
    ``scope=<case name>`` so baseline keys are per-entry-point."""
    chosen = set(select) if select is not None else set(IR_RULES)
    out: List[Finding] = []
    for name in sorted(chosen):
        rule = IR_RULES[name]
        for raw in rule.check(ir):
            anchor = eqn_anchor(raw.eqn, root) if raw.eqn is not None \
                else None
            if anchor is None:
                anchor = _case_anchor(ir, root)
            out.append(Finding(
                rule=rule.name, severity=rule.severity, path=anchor[0],
                line=anchor[1], col=1,
                message=f"[case {ir.name}] {raw.message}",
                scope=ir.name))
    return out


def analyze_ir(root, *, select: Optional[Iterable[str]] = None,
               case: Optional[str] = None,
               ) -> Tuple[List[Finding], int, int]:
    """Trace the registry and lint every jaxpr; returns
    ``(findings, #suppressed, #cases)``.

    ``select`` restricts to a subset of IR rule names; ``case`` runs a
    single registered case (``--ir-case``). A case that fails to trace
    yields an ``ir-trace-error`` finding (severity error) instead of
    crashing the run — one broken entry point must not hide the rest.
    """
    root = Path(root).resolve()
    if select is not None:
        unknown = set(select) - set(IR_RULES)
        if unknown:
            raise ValueError(
                f"unknown IR rule(s): {', '.join(sorted(unknown))}")
    try:
        cases = analysis_cases(root)
    except Exception as e:          # noqa: BLE001 — findings, not crashes
        # an import-time failure in tpu_aot.py (env-dependent check,
        # missing dep) must keep the 0/1/2 contract, like parse-error
        return ([Finding(
            rule="ir-trace-error", severity="error", path="tpu_aot.py",
            line=1, col=1, scope="<registry>",
            message=f"failed to build the IR case registry: "
                    f"{type(e).__name__}: {e}")], 0, 0)
    if case is not None:
        cases = [c for c in cases if c.name == case]
        if not cases:
            raise ValueError(f"unknown IR case: {case}")
    supp = _SuppressionCache(root)
    findings: List[Finding] = []
    suppressed = 0
    for c in cases:
        try:
            ir = build_case_ir(c)
        except Exception as e:      # noqa: BLE001 — findings, not crashes
            findings.append(Finding(
                rule="ir-trace-error", severity="error",
                path="apex_tpu/analysis/ir/harness.py", line=1, col=1,
                scope=c.name,
                message=f"[case {c.name}] failed to trace: "
                        f"{type(e).__name__}: {e}"))
            continue
        for f in findings_for_case(ir, root, select):
            if supp.get(f.path).covers(f):
                suppressed += 1
            else:
                findings.append(f)
    return findings, suppressed, len(cases)
