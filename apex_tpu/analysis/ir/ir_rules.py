"""tpu-lint IR rules: hazards visible in the staged jaxpr, not the AST.

Each rule walks one :class:`~apex_tpu.analysis.ir.harness.CaseIR` (the
traced program of a registered entry point) and yields
:class:`RawFinding`\\ s — an offending equation (mapped to source by
``ir_report``) or ``None`` to anchor at the case's definition site.

The same precision bias as the AST tier, applied one layer down: every
check reads facts the trace PROVES (aval dtypes and byte sizes, scan
carry wiring, closed-over constants, effects), with byte thresholds
sized so only hot-path-relevant findings fire. Pallas kernel bodies are
NOT descended into — their internals are the kernel tests' and the AOT
sweep's domain; the IR tier judges the program *around* the kernels.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

from apex_tpu.analysis.ir.harness import MIB, CaseIR

#: bf16->f32 promotions below this many output bytes are noise;
#: above it the round-trip doubles a hot intermediate's HBM traffic
PROMOTION_BYTES = 8 * MIB
#: a closed-over constant this large belongs in the argument list
CONST_BYTES = 512 * 1024
#: broadcast-blowup: output >= FACTOR x largest non-literal input
#: AND at least this many bytes
BLOWUP_BYTES = 8 * MIB
BLOWUP_FACTOR = 32
#: expensive-output floor for the dead-computation rule
DEAD_BYTES = MIB
#: minor-dim transpose floor for the layout rule
TRANSPOSE_BYTES = MIB


@dataclasses.dataclass
class RawFinding:
    eqn: Optional[object]            # jaxpr eqn (source anchor) or None
    message: str


@dataclasses.dataclass(frozen=True)
class IRRule:
    name: str
    severity: str
    summary: str
    check: Callable                  # check(ir: CaseIR) -> Iterator


IR_RULES: Dict[str, IRRule] = {}


def ir_rule(name: str, severity: str, summary: str):
    def deco(fn):
        IR_RULES[name] = IRRule(name=name, severity=severity,
                                summary=summary, check=fn)
        return fn
    return deco


# --------------------------------------------------------------------------
# jaxpr plumbing
# --------------------------------------------------------------------------

def _sub_jaxprs(eqn) -> Iterator[object]:
    """Inner jaxprs of a higher-order eqn (NOT pallas_call kernels)."""
    if eqn.primitive.name == "pallas_call":
        return
    for key in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr"):
        v = eqn.params.get(key)
        if v is not None:
            yield getattr(v, "jaxpr", v)     # ClosedJaxpr -> Jaxpr
    for br in eqn.params.get("branches", ()):
        yield getattr(br, "jaxpr", br)


def _all_jaxprs(jaxpr) -> Iterator[object]:
    """This jaxpr and every nested one (scan/while/cond/pjit bodies)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for sub in _sub_jaxprs(eqn):
            yield from _all_jaxprs(sub)


def _iter_eqns(jaxpr, in_loop: bool = False
               ) -> Iterator[Tuple[object, bool]]:
    """(eqn, inside-a-scan/while-body) over the whole nest."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        looping = in_loop or eqn.primitive.name in ("scan", "while")
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub, looping)


def _is_var(v) -> bool:
    return hasattr(v, "aval") and not hasattr(v, "val")   # Var, not Literal


def _is_drop(v) -> bool:
    return type(v).__name__ == "DropVar"


def _nbytes(aval) -> int:
    try:
        size = 1
        for d in aval.shape:
            size *= int(d)
        return size * aval.dtype.itemsize
    except (AttributeError, TypeError):
        return 0


def _mib(n: int) -> str:
    return f"{n / MIB:.1f} MiB"


def _is_float(dt) -> bool:
    """True for any floating dtype INCLUDING the ml_dtypes extension
    types (bfloat16/fp8), whose numpy ``kind`` is not ``'f'``."""
    import jax.numpy as jnp

    try:
        return jnp.issubdtype(dt, jnp.floating)
    except TypeError:
        return False


def _float_leaf_dtypes(vars_) -> Set[str]:
    out: Set[str] = set()
    for v in vars_:
        dt = getattr(getattr(v, "aval", None), "dtype", None)
        if dt is not None and _is_float(dt):
            out.add(dt.name)
    return out


# --------------------------------------------------------------------------
# 1. ir-dtype-promotion-drift
# --------------------------------------------------------------------------

@ir_rule("ir-dtype-promotion-drift", "warning",
         "large bf16->fp32 promotion staged inside a bf16-in/bf16-out "
         "program — the round trip doubles a hot intermediate's bytes")
def check_promotion_drift(ir: CaseIR) -> Iterator[RawFinding]:
    jaxpr = ir.closed.jaxpr
    in_f = _float_leaf_dtypes(jaxpr.invars)
    out_f = _float_leaf_dtypes(jaxpr.outvars)
    if not in_f or not (in_f <= {"bfloat16", "float16"}):
        return
    if out_f - {"bfloat16", "float16"}:
        return                       # fp32 outputs are the declared deal
    for eqn, _ in _iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        new = eqn.params.get("new_dtype")
        src = getattr(eqn.invars[0].aval, "dtype", None)
        if new is None or src is None:
            continue
        if str(new) not in ("float32", "float64") \
                or src.name not in ("bfloat16", "float16"):
            continue
        nb = _nbytes(eqn.outvars[0].aval)
        if nb >= PROMOTION_BYTES:
            yield RawFinding(
                eqn,
                f"{src.name}->{new} promotion of a {_mib(nb)} "
                f"intermediate in a {'/'.join(sorted(in_f))}-in/"
                "bf16-out program — the compiler was handed a widened "
                "hot path (keep the accumulation, or suppress with the "
                "why)")


# --------------------------------------------------------------------------
# 2. ir-x64-leak
# --------------------------------------------------------------------------

_X64 = {"float64", "int64", "uint64", "complex128"}


@ir_rule("ir-x64-leak", "error",
         "a 64-bit dtype is staged into the program — double-width "
         "buffers and a disabled-x64 drift hazard")
def check_x64_leak(ir: CaseIR) -> Iterator[RawFinding]:
    jaxpr = ir.closed.jaxpr
    for v in list(jaxpr.invars) + list(jaxpr.outvars):
        dt = getattr(getattr(v, "aval", None), "dtype", None)
        if dt is not None and dt.name in _X64:
            yield RawFinding(
                None, f"{dt.name} program boundary value "
                      f"(shape {tuple(v.aval.shape)}) — x64 leaked into "
                      "the staged program")
            break                    # boundary summary once per case
    for eqn, _ in _iter_eqns(jaxpr):
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None and dt.name in _X64:
                yield RawFinding(
                    eqn, f"`{eqn.primitive.name}` stages a {dt.name} "
                         f"intermediate of shape {tuple(v.aval.shape)}")
                break


# --------------------------------------------------------------------------
# 3. ir-dead-output / 4. ir-dead-scan-carry
# --------------------------------------------------------------------------

#: dead-output flags ONLY these: kernel launches and contractions XLA
#: either cannot freely DCE (opaque custom calls) or whose dead staging
#: signals a drifted contract. Dead PURE elementwise eqns (a grad-of-
#: loss primal, a dropped slice) are free for XLA to DCE — flagging
#: them would bury the real findings in artifacts of how grad stages.
_EXPENSIVE_PRIMS = {"scan", "while", "cond", "pjit", "closed_call",
                    "core_call", "remat", "checkpoint", "dot_general",
                    "conv_general_dilated", "custom_jvp_call",
                    "custom_vjp_call", "pallas_call"}


def _dead_eqns(jaxpr, live_out: Optional[Set[int]] = None
               ) -> Iterator[Tuple[object, object]]:
    """(eqn, first dead outvar) for computation no consumer needs.

    ``live_out``: ids of this jaxpr's outvars that ARE consumed outside
    (None = all). Recurses into pjit/scan bodies with the outer
    liveness projected in, so an entire scan output nobody reads is
    caught along with the body computation feeding it.
    """
    live: Set[int] = {id(v) for v in jaxpr.outvars
                      if live_out is None or id(v) in live_out}
    alive_eqns: List[Tuple[object, bool]] = []
    for eqn in reversed(jaxpr.eqns):
        out_alive = [not _is_drop(v) and id(v) in live
                     for v in eqn.outvars]
        eqn_alive = any(out_alive) or bool(eqn.effects)
        alive_eqns.append((eqn, eqn_alive))
        if eqn_alive:
            for v in eqn.invars:
                if _is_var(v):
                    live.add(id(v))
    for eqn, eqn_alive in reversed(alive_eqns):
        if not eqn_alive:
            dead_v = next((v for v in eqn.outvars if not _is_drop(v)),
                          eqn.outvars[0] if eqn.outvars else None)
            yield eqn, dead_v
            continue
        # project outer liveness into pjit-like bodies (1:1 outputs)
        if eqn.primitive.name in ("pjit", "closed_call", "core_call",
                                  "remat", "checkpoint"):
            for sub in _sub_jaxprs(eqn):
                if len(sub.outvars) != len(eqn.outvars):
                    continue
                inner_live = {id(sub.outvars[i])
                              for i, v in enumerate(eqn.outvars)
                              if not _is_drop(v) and id(v) in live}
                yield from _dead_eqns(sub, inner_live)
        # a live scan can still stack a ys nobody reads (its CARRY
        # outputs are intrinsic — next-iteration inputs — but an
        # unread stacked output is pure dead weight per iteration)
        elif eqn.primitive.name == "scan":
            k = eqn.params.get("num_carry", 0)
            for v in eqn.outvars[k:]:
                if not _is_drop(v) and id(v) not in live \
                        and _nbytes(v.aval) >= DEAD_BYTES:
                    yield eqn, v


@ir_rule("ir-dead-output", "warning",
         "expensive computation whose result no consumer reads — dead "
         "weight XLA may or may not DCE, and a drifted-contract smell")
def check_dead_output(ir: CaseIR) -> Iterator[RawFinding]:
    for eqn, dead_v in _dead_eqns(ir.closed.jaxpr):
        if eqn.primitive.name not in _EXPENSIVE_PRIMS:
            continue
        nb = _nbytes(getattr(dead_v, "aval", None)) if dead_v is not None \
            else 0
        what = f"a {_mib(nb)} result" if nb >= DEAD_BYTES \
            else "its result"
        yield RawFinding(
            eqn, f"`{eqn.primitive.name}` computes {what} no consumer "
                 "reads — dead computation carried in the program")


@ir_rule("ir-dead-scan-carry", "warning",
         "a scan carry component is passed through unread and its "
         "final value unused — vestigial state copied every step")
def check_dead_scan_carry(ir: CaseIR) -> Iterator[RawFinding]:
    for jaxpr in _all_jaxprs(ir.closed.jaxpr):
        # per-jaxpr use map: vars read by any eqn or returned
        used: Set[int] = {id(v) for v in jaxpr.outvars}
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if _is_var(v):
                    used.add(id(v))
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "scan":
                continue
            body = eqn.params["jaxpr"].jaxpr
            nc = eqn.params["num_consts"]
            k = eqn.params["num_carry"]
            body_used: Set[int] = set()
            for be in body.eqns:
                for v in be.invars:
                    if _is_var(v):
                        body_used.add(id(v))
            for i in range(k):
                inv = body.invars[nc + i]
                outv = body.outvars[i]
                if outv is not inv:
                    continue                      # genuinely updated
                if id(inv) in body_used:
                    continue                      # read-only state: fine
                if i < len(body.outvars) \
                        and body.outvars.count(inv) > 1:
                    continue                      # aliased elsewhere
                carried_out = eqn.outvars[i]
                if not _is_drop(carried_out) and id(carried_out) in used:
                    continue                      # final value consumed
                yield RawFinding(
                    eqn,
                    f"scan carry component {i} "
                    f"(shape {tuple(inv.aval.shape)}, {inv.aval.dtype}) "
                    "is passed through unread and its final value is "
                    "never consumed — dead state copied every "
                    "iteration; hoist it out of the carry")


# --------------------------------------------------------------------------
# 5. ir-donation-ineffective
# --------------------------------------------------------------------------

@ir_rule("ir-donation-ineffective", "warning",
         "a donated input has no output of identical shape/dtype to "
         "alias — XLA keeps both buffers and the donation is a no-op")
def check_donation_ineffective(ir: CaseIR) -> Iterator[RawFinding]:
    if not ir.donated_avals:
        return
    budget: Dict[Tuple[tuple, str], int] = {}
    for v in ir.closed.jaxpr.outvars:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        key = (tuple(aval.shape), str(aval.dtype))
        budget[key] = budget.get(key, 0) + 1
    for leaf in ir.donated_avals:
        key = (tuple(leaf.shape), str(leaf.dtype))
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            continue
        yield RawFinding(
            None,
            f"donated input (shape {key[0]}, {key[1]}) has no "
            "unmatched output of the same shape/dtype — XLA cannot "
            "alias it; drop the donation or return the updated buffer "
            "(cross-check: the AST tier's jit-donated-reuse guards the "
            "caller side)")


# --------------------------------------------------------------------------
# 6. ir-large-const-capture
# --------------------------------------------------------------------------

@ir_rule("ir-large-const-capture", "warning",
         "a closed-over array above the byte threshold is baked into "
         "the jaxpr as a constant — re-staged per trace, bloats every "
         "compile-cache entry")
def check_large_const(ir: CaseIR) -> Iterator[RawFinding]:
    for const in ir.closed.consts:
        nb = int(getattr(const, "nbytes", 0) or 0)
        if nb >= CONST_BYTES:
            yield RawFinding(
                None,
                f"closed-over constant (shape "
                f"{tuple(getattr(const, 'shape', ()))}, "
                f"{getattr(const, 'dtype', '?')}, {_mib(nb)}) is baked "
                "into the jaxpr — pass it as an argument so it lives "
                "once on device")


# --------------------------------------------------------------------------
# 7. ir-broadcast-blowup
# --------------------------------------------------------------------------

@ir_rule("ir-broadcast-blowup", "warning",
         "an intermediate blows up far beyond its inputs via broadcast "
         "— a materialized tensor the math may not need")
def check_broadcast_blowup(ir: CaseIR) -> Iterator[RawFinding]:
    for eqn, _ in _iter_eqns(ir.closed.jaxpr):
        if eqn.primitive.name != "broadcast_in_dim":
            continue
        src = eqn.invars[0]
        if not _is_var(src):
            continue                  # literal fill (jnp.zeros) is fine
        in_nb = _nbytes(src.aval)
        out_nb = _nbytes(eqn.outvars[0].aval)
        if in_nb <= 128:
            continue                  # scalar/tiny seed: a fill, not a
        #                               relayout of real data
        if out_nb >= BLOWUP_BYTES and out_nb >= BLOWUP_FACTOR * in_nb:
            yield RawFinding(
                eqn,
                f"broadcast materializes {_mib(out_nb)} from "
                f"{_mib(in_nb)} (x{out_nb // max(in_nb, 1)}) — check "
                "whether the consumer could fuse the broadcast instead")


# --------------------------------------------------------------------------
# 8. ir-effectful-in-scan
# --------------------------------------------------------------------------

@ir_rule("ir-effectful-in-scan", "warning",
         "a callback/effectful primitive runs inside a scan/while body "
         "— host traffic on every iteration of the hot loop")
def check_effectful_in_scan(ir: CaseIR) -> Iterator[RawFinding]:
    def host_effects(eqn) -> bool:
        # named-axis effects are trace bookkeeping for collectives
        # (psum/all_gather/axis_index under shard_map) — on-device ICI
        # traffic, not host round-trips; a TP decode scan is SUPPOSED
        # to all-reduce every step
        return any("NamedAxis" not in type(e).__name__
                   for e in eqn.effects)

    for eqn, in_loop in _iter_eqns(ir.closed.jaxpr):
        if not in_loop:
            continue
        name = eqn.primitive.name
        if "callback" in name or name == "debug_print" \
                or (host_effects(eqn)
                    and name not in ("scan", "while", "cond", "pjit")):
            yield RawFinding(
                eqn,
                f"`{name}` executes inside a scan/while body: one host "
                "round-trip per iteration (even the non-blocking "
                "metrics channel pays transfer+queue each step — keep "
                "it at chunk boundaries)")


# --------------------------------------------------------------------------
# 9. ir-compile-key-cardinality
# --------------------------------------------------------------------------

@ir_rule("ir-compile-key-cardinality", "error",
         "bucketed input variants staged MORE distinct programs than "
         "the case's compile-count contract allows")
def check_compile_cardinality(ir: CaseIR) -> Iterator[RawFinding]:
    if not ir.variant_closed:
        return

    def canon(closed) -> str:
        # custom_vjp/thunk params print as `<function f at 0x...>`;
        # addresses differ per trace even for IDENTICAL programs
        return re.sub(r"0x[0-9a-f]+", "0x", str(closed.jaxpr))

    distinct = {canon(c) for c in [ir.closed] + ir.variant_closed}
    allowed = ir.prog.max_traces
    if len(distinct) > allowed:
        yield RawFinding(
            None,
            f"{len(ir.variant_closed) + 1} bucketed shape variants "
            f"traced to {len(distinct)} distinct programs (contract: "
            f"<= {allowed}) — the bucketing is not collapsing compile "
            "keys; every live value becomes a fresh XLA compile")


# --------------------------------------------------------------------------
# 10. ir-transpose-heavy-layout
# --------------------------------------------------------------------------

@ir_rule("ir-transpose-heavy-layout", "warning",
         "a minor-dim transpose feeds a Pallas kernel — the relayout "
         "Mosaic pays on the (sublane, lane) dims, per call")
def check_transpose_layout(ir: CaseIR) -> Iterator[RawFinding]:
    for jaxpr in _all_jaxprs(ir.closed.jaxpr):
        transposed: Dict[int, Tuple[object, int]] = {}
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "transpose":
                continue
            perm = tuple(eqn.params.get("permutation", ()))
            rank = len(perm)
            if rank < 2 or (perm[-1] == rank - 1
                            and perm[-2] == rank - 2):
                continue              # minor (sublane, lane) dims intact
            nb = _nbytes(eqn.outvars[0].aval)
            if nb >= TRANSPOSE_BYTES:
                transposed[id(eqn.outvars[0])] = (eqn, nb)
        if not transposed:
            continue
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "pallas_call":
                continue
            for v in eqn.invars:
                if _is_var(v) and id(v) in transposed:
                    teqn, nb = transposed[id(v)]
                    yield RawFinding(
                        teqn,
                        f"{_mib(nb)} operand is transposed on its minor "
                        "dims immediately before a pallas_call — Mosaic "
                        "relayouts the (sublane, lane) tiles every "
                        "call; feed the kernel the native layout or "
                        "fold the transpose into the index map")
