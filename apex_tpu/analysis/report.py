"""Finding rendering: ``--format text`` (human/CI log) and ``json``
(machine consumers — the bench harness and future dashboards)."""

from __future__ import annotations

import json
from typing import List

from apex_tpu.analysis.walker import Finding


def _sorted(findings: List[Finding]) -> List[Finding]:
    # (path, line, rule) first: CI logs stay stable and greppable when a
    # rule's column anchor shifts (col only breaks same-line ties)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.col))


def render_text(new: List[Finding], baselined: List[Finding],
                suppressed: int, show_baselined: bool = False) -> str:
    out = []
    for f in _sorted(new):
        out.append(f"{f.path}:{f.line}:{f.col}: [{f.rule}] {f.severity}: "
                   f"{f.message}")
    if show_baselined:
        for f in _sorted(baselined):
            out.append(f"{f.path}:{f.line}:{f.col}: [{f.rule}] "
                       f"{f.severity} (baselined): {f.message}")
    errors = sum(1 for f in new if f.severity == "error")
    warnings = len(new) - errors
    out.append(
        f"tpu-lint: {len(new)} finding(s) ({errors} error(s), "
        f"{warnings} warning(s)), {len(baselined)} baselined, "
        f"{suppressed} suppressed")
    return "\n".join(out)


def render_json(new: List[Finding], baselined: List[Finding],
                suppressed: int) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in _sorted(new)],
        "baselined": [f.to_dict() for f in _sorted(baselined)],
        "counts": {
            "new": len(new),
            "errors": sum(1 for f in new if f.severity == "error"),
            "warnings": sum(1 for f in new if f.severity == "warning"),
            "baselined": len(baselined),
            "suppressed": suppressed,
        },
    }, indent=2)
