"""Static per-chip memory estimation for the tpu-lint mem tier.

Three computations over one traced case (a :class:`CaseIR` from the IR
harness — the mem tier deliberately re-uses the same registry/trace
path so "registered for lint" means "covered by the fit proof"):

- **per-chip peak HBM** (:func:`estimate_case`): the liveness sweep of
  ``obs/costs.py`` but (a) pricing every array at its TPU tiled-layout
  PADDED size (``layout.py``), (b) analyzing a shard_map-wrapped
  program at its body's LOCAL shard shapes — per-chip bytes, exactly
  like the cost model prices per-chip FLOPs, (c) charging each
  ``lax.scan`` an extra copy of its carry (XLA double-buffers the
  decode scan's pool carry — the PR 10 lesson), and (d) crediting
  in-place updates: a scatter/dynamic_update_slice/scan whose output
  matches a buffer dying at that equation writes it in place instead of
  allocating, provided the buffer is writable (an intermediate or a
  donated input) — the static analogue of ``memory_analysis()``'s
  ``alias_bytes`` term, applied per equation so a chain of per-layer
  pool updates isn't credited once globally.
  Both the with- and without-double-buffer peaks are kept so the rules
  can say WHICH lesson a budget miss violates.

- **per-``pallas_call`` VMEM** (:class:`VmemCall`): block shape x dtype
  per operand at padded tile sizes, x2 when a non-trivial grid pipelines
  (Mosaic double-buffers grid blocks), vs the 16 MiB scoped-VMEM
  budget — the ``_check_block_mappings``/scoped-vmem overflow class
  (the r5 Adam regression, the PR 14 scale-view bring-up) before any
  compile.

- **sharding contracts** (:class:`ShardMapInfo`): every ``shard_map``
  equation's mesh axis sizes + per-operand ``in_names``/``out_names``,
  aligned positionally with the case's argument tree paths so rules can
  talk about ``cache/layers/0/k_scales`` rather than ``invar 17``.

Everything here is trace-only (CPU, AbstractMesh-friendly): no TPU, no
compile.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from apex_tpu.analysis.mem.layout import (aval_logical_bytes,
                                          aval_padded_bytes,
                                          tiled_padded_bytes)

#: Mosaic's scoped-VMEM stack per core — the budget the r5 Adam kernel
#: overflowed at block 256 and every ``_check_block_mappings`` failure
#: ultimately traces back to.
VMEM_BUDGET_BYTES = 16 * 1024 * 1024


# --------------------------------------------------------------------------
# jaxpr plumbing
# --------------------------------------------------------------------------

_JAXPR_PARAMS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr",
                 "body_jaxpr")


def _sub_jaxprs(eqn):
    """(sub_jaxpr, is_pallas_kernel) pairs under one equation."""
    is_pallas = eqn.primitive.name == "pallas_call"
    for key in _JAXPR_PARAMS:
        sub = eqn.params.get(key)
        if sub is None:
            continue
        inner = getattr(sub, "jaxpr", sub)
        if inner is not None:
            yield inner, is_pallas
    for sub in eqn.params.get("branches", ()):
        inner = getattr(sub, "jaxpr", sub)
        if inner is not None:
            yield inner, is_pallas


def iter_eqns(jaxpr, *, into_pallas: bool = False):
    """Every equation under ``jaxpr``, recursively (pallas kernel bodies
    skipped unless asked — their "arrays" are VMEM refs, not HBM)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub, is_pallas in _sub_jaxprs(eqn):
            if is_pallas and not into_pallas:
                continue
            yield from iter_eqns(sub, into_pallas=into_pallas)


def _is_literal(v) -> bool:
    return not hasattr(v, "count") and hasattr(v, "val")


def unwrap_trivial(jaxpr):
    """Descend through single-equation pjit/closed-call wrappers:
    ``make_jaxpr(jax.jit(f))`` stages one pjit eqn whose body is the
    program. Stops at the first level that has real structure."""
    depth = 0
    while depth < 8 and len(jaxpr.eqns) == 1 and \
            jaxpr.eqns[0].primitive.name in ("pjit", "closed_call",
                                             "custom_jvp_call",
                                             "custom_vjp_call",
                                             "remat", "checkpoint"):
        eqn = jaxpr.eqns[0]
        sub = next((s for s, _ in _sub_jaxprs(eqn)), None)
        if sub is None or len(sub.invars) != len(eqn.invars):
            break
        jaxpr = sub
        depth += 1
    return jaxpr


# --------------------------------------------------------------------------
# shard_map contracts
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ShardMapInfo:
    """One ``shard_map`` equation's declared contract."""

    eqn: object
    mesh_axes: Dict[str, int]            # axis name -> size
    in_names: Tuple[dict, ...]           # per operand: {dim: (axes...)}
    out_names: Tuple[dict, ...]
    body: object                         # the body jaxpr (LOCAL shapes)

    def in_axes(self, pos: int) -> Dict[int, Tuple[str, ...]]:
        return dict(self.in_names[pos]) if pos < len(self.in_names) else {}

    def out_axes(self, pos: int) -> Dict[int, Tuple[str, ...]]:
        return dict(self.out_names[pos]) \
            if pos < len(self.out_names) else {}


def shard_map_infos(closed) -> List[ShardMapInfo]:
    out: List[ShardMapInfo] = []
    for eqn in iter_eqns(unwrap_trivial(closed.jaxpr)):
        if eqn.primitive.name != "shard_map":
            continue
        mesh = eqn.params.get("mesh")
        try:
            mesh_axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        except Exception:
            mesh_axes = {}
        body = eqn.params.get("jaxpr")
        body = getattr(body, "jaxpr", body)
        out.append(ShardMapInfo(
            eqn=eqn, mesh_axes=mesh_axes,
            in_names=tuple(eqn.params.get("in_names", ())),
            out_names=tuple(eqn.params.get("out_names", ())),
            body=body))
    return out


def arg_leaf_paths(prog) -> Optional[List[Tuple[str, object, int]]]:
    """Flatten the case's argument tuple to ``(path, aval, arg_index)``
    leaves in jaxpr-invar order (``make_jaxpr`` flattens positionally).
    None when jax is too old to report paths."""
    try:
        import jax
    except Exception:
        return None
    leaves: List[Tuple[str, object, int]] = []
    for i, arg in enumerate(prog.args):
        flat = jax.tree_util.tree_flatten_with_path(arg)[0]
        for path, leaf in flat:
            name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                            for k in path)
            leaves.append((f"arg{i}" + (f"/{name}" if name else ""),
                           leaf, i))
    return leaves


# --------------------------------------------------------------------------
# the padded liveness sweep
# --------------------------------------------------------------------------

def _scan_carry_extra(eqn) -> int:
    """Padded bytes of one scan's carry — the extra in-flight copy XLA's
    double buffering holds while the next iteration's carry is built."""
    if eqn.primitive.name != "scan":
        return 0
    nc = int(eqn.params.get("num_consts", 0))
    ncarry = int(eqn.params.get("num_carry", 0))
    carry = list(eqn.invars)[nc:nc + ncarry]
    return sum(aval_padded_bytes(v.aval) for v in carry
               if not _is_literal(v))


#: primitives XLA reliably updates IN PLACE when a dying operand buffer
#: of the output's exact shape+dtype is writable: the pool scatter /
#: dynamic-update-slice class, the scan/while carry, and the masked
#: select that implements conditional updates. Deliberately narrow —
#: a dot_general can't overwrite its own operand.
_INPLACE_PRIMS = frozenset({
    "scatter", "scatter-add", "scatter-mul", "scatter-min",
    "scatter-max", "dynamic_update_slice", "scan", "while", "select_n",
    "copy", "pjit", "closed_call",
})


def _padded_liveness(jaxpr, owned_inputs=frozenset()
                     ) -> Tuple[int, int, int, int]:
    """(peak_with_double_buffer, peak_without, scan_carry_extra_max,
    inplace_credit_total) over the top-level equation list at padded
    sizes. Same sweep shape as ``obs.costs._peak_live_bytes`` — inner-
    jaxpr scratch is not modeled — plus two refinements:

    - each scan charges an extra copy of its carry (XLA's double
      buffering);
    - an in-place-capable equation whose output matches a buffer dying
      at that very equation does NOT allocate, provided the dying
      buffer is writable — an intermediate, or a DONATED program input
      (``owned_inputs``). This is how the per-layer pool scatters and
      the scan carry alias in the compiled program; a donated input
      with no matching update keeps both copies (the donation was
      ineffective)."""
    last_use: Dict[object, int] = {}
    n = len(jaxpr.eqns)
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not _is_literal(v):
            last_use[v] = n
    live: Dict[object, int] = {
        v: aval_padded_bytes(v.aval)
        for v in list(jaxpr.invars) + list(jaxpr.constvars)
        if v in last_use}
    writable = set(owned_inputs)
    cur = sum(live.values())
    peak_db = peak = cur
    carry_max = 0
    credit_total = 0
    for i, eqn in enumerate(jaxpr.eqns):
        dying: Dict[Tuple[tuple, str], int] = {}
        if eqn.primitive.name in _INPLACE_PRIMS:
            seen = set()
            for v in eqn.invars:
                if _is_literal(v) or id(v) in seen:
                    continue
                seen.add(id(v))
                if last_use.get(v) == i and v in live and v in writable:
                    aval = v.aval
                    if getattr(aval, "dtype", None) is None:
                        continue
                    key = (tuple(aval.shape), str(aval.dtype))
                    dying[key] = dying.get(key, 0) + 1
        out_bytes = 0
        for v in eqn.outvars:
            b = aval_padded_bytes(v.aval)
            aval = getattr(v, "aval", None)
            key = (tuple(getattr(aval, "shape", ())),
                   str(getattr(aval, "dtype", None)))
            if dying.get(key, 0) > 0:
                dying[key] -= 1
                credit_total += b
                continue                   # writes the dying buffer
            out_bytes += b
        extra = _scan_carry_extra(eqn)
        carry_max = max(carry_max, extra)
        peak = max(peak, cur + out_bytes)
        peak_db = max(peak_db, cur + out_bytes + extra)
        for v in eqn.outvars:
            if last_use.get(v, i) > i:
                live[v] = aval_padded_bytes(v.aval)
                cur += live[v]
        for v in eqn.invars:
            if not _is_literal(v) and last_use.get(v) == i and v in live:
                cur -= live.pop(v)
        writable.update(v for v in eqn.outvars if not _is_literal(v))
    return peak_db, peak, carry_max, credit_total


# --------------------------------------------------------------------------
# per-pallas_call VMEM
# --------------------------------------------------------------------------

@dataclasses.dataclass
class VmemCall:
    eqn: object
    kernel_name: str
    est_bytes: int               # sum of padded block bytes x buffering
    buffering: int               # 2 when a non-trivial grid pipelines
    n_blocks: int
    grid: Tuple[int, ...]


def _block_dims(block_shape) -> Tuple[int, ...]:
    # grid-mapped dims appear as pallas' Mapped sentinel (not an int):
    # the kernel sees them squeezed, i.e. extent 1
    dims = []
    for d in block_shape:
        try:
            dims.append(max(int(d), 1))
        except (TypeError, ValueError):
            dims.append(1)
    return tuple(dims)


def vmem_calls(closed) -> List[VmemCall]:
    out: List[VmemCall] = []
    for eqn in iter_eqns(unwrap_trivial(closed.jaxpr)):
        if eqn.primitive.name != "pallas_call":
            continue
        gm = eqn.params.get("grid_mapping")
        if gm is None:
            continue
        try:
            grid = tuple(int(g) for g in gm.grid)
        except (TypeError, ValueError):
            grid = ()                      # dynamic grid: size unknown
        total = 0
        n_blocks = 0
        for bm in getattr(gm, "block_mappings", ()):
            sds = getattr(bm, "array_shape_dtype", None)
            dtype = getattr(sds, "dtype", None)
            if dtype is None:
                continue
            total += tiled_padded_bytes(
                _block_dims(getattr(bm, "block_shape", ())), dtype)
            n_blocks += 1
        buffering = 2 if any(g > 1 for g in grid) else 1
        name = str(eqn.params.get("name_and_src_info",
                                  eqn.params.get("name", "<kernel>")))
        out.append(VmemCall(eqn=eqn, kernel_name=name.split(" ")[0],
                            est_bytes=total * buffering,
                            buffering=buffering, n_blocks=n_blocks,
                            grid=grid))
    return out


# --------------------------------------------------------------------------
# the per-case estimate
# --------------------------------------------------------------------------

@dataclasses.dataclass
class BoundaryArray:
    """One program-boundary array (input or output) at the analyzed
    scope's shapes — LOCAL shard shapes for shard_map programs."""

    label: str
    kind: str                    # "in" | "out"
    shape: Tuple[int, ...]
    dtype: str
    logical_bytes: int
    padded_bytes: int


@dataclasses.dataclass
class MemEstimate:
    """The mem tier's static memory model of one traced case."""

    scope: str                   # "per-chip" | "global"
    peak_bytes: int              # padded, double-buffered, alias-credited
    peak_no_db_bytes: int        # same sweep without the scan 2x
    scan_carry_extra_bytes: int
    alias_bytes: int             # in-place-update bytes credited
    boundary: List[BoundaryArray]
    vmem: List[VmemCall]
    shard_maps: List[ShardMapInfo]
    arg_leaves: Optional[List[Tuple[str, object, int]]]
    notes: List[str]


def _analyzed_jaxpr(closed, infos: List[ShardMapInfo]):
    """The jaxpr whose boundary IS a chip's resident set: the body of a
    whole-program shard_map (local shard shapes), else the (unwrapped)
    top level. "Whole-program" = the unwrapped level is exactly one
    shard_map equation."""
    top = unwrap_trivial(closed.jaxpr)
    if len(top.eqns) == 1 and top.eqns[0].primitive.name == "shard_map":
        for info in infos:
            if info.eqn is top.eqns[0]:
                return unwrap_trivial(info.body), "per-chip"
        body = top.eqns[0].params.get("jaxpr")
        return unwrap_trivial(getattr(body, "jaxpr", body)), "per-chip"
    return top, "global"


def _donated_positions(prog) -> List[int]:
    """Flattened invar positions of the donated argument indices."""
    if not prog.donate:
        return []
    try:
        import jax
    except Exception:
        return []
    positions: List[int] = []
    offset = 0
    for i, arg in enumerate(prog.args):
        n = len(jax.tree_util.tree_leaves(arg))
        if i in prog.donate:
            positions.extend(range(offset, offset + n))
        offset += n
    return positions


def estimate_case(ir) -> MemEstimate:
    """Build the full static estimate for one traced case (a CaseIR)."""
    infos = shard_map_infos(ir.closed)
    jaxpr, scope = _analyzed_jaxpr(ir.closed, infos)
    owned = {jaxpr.invars[p] for p in _donated_positions(ir.prog)
             if p < len(jaxpr.invars)}
    peak_db, peak, carry, alias = _padded_liveness(jaxpr, owned)
    leaves = arg_leaf_paths(ir.prog)
    notes: List[str] = []
    if scope == "per-chip":
        notes.append("shard_map body analyzed at local shard shapes "
                     "(per-chip bytes)")

    def _label(kind: str, idx: int) -> str:
        if kind == "in" and leaves is not None and idx < len(leaves) \
                and len(leaves) == len(jaxpr.invars):
            return leaves[idx][0]
        return f"{kind}[{idx}]"

    boundary: List[BoundaryArray] = []
    for kind, vs in (("in", jaxpr.invars), ("out", jaxpr.outvars)):
        for idx, v in enumerate(vs):
            if _is_literal(v):
                continue
            aval = v.aval
            if getattr(aval, "dtype", None) is None:
                continue
            boundary.append(BoundaryArray(
                label=_label(kind, idx), kind=kind,
                shape=tuple(aval.shape), dtype=str(aval.dtype),
                logical_bytes=aval_logical_bytes(aval),
                padded_bytes=aval_padded_bytes(aval)))
    return MemEstimate(
        scope=scope,
        peak_bytes=peak_db,
        peak_no_db_bytes=peak,
        scan_carry_extra_bytes=carry,
        alias_bytes=alias,
        boundary=boundary,
        vmem=vmem_calls(ir.closed),
        shard_maps=infos,
        arg_leaves=leaves,
        notes=notes)
