"""tpu-lint mem tier: static memory-budget & sharding-contract analysis.

The fourth lint tier (``--mem``). The AST tier reads source, the IR
tier reads staged jaxprs, the conc tier reads the host side; this tier
proves MEMORY FIT — per-chip, before any compile, on any machine:

- **tiled-layout-aware peak HBM** (``layout.py`` + ``estimator.py``):
  the cost model's liveness sweep re-priced at TPU tile-padded sizes
  (minor dim -> 128 lanes, second-minor -> the dtype's sublane
  multiple), at LOCAL shard shapes inside shard_map, with each scan's
  carry double-buffered and donated buffers alias-credited — checked
  against the case's declared ``ChipProfile`` budget;
- **per-``pallas_call`` VMEM** vs the 16 MiB scoped stack;
- **sharding contracts** over shard_map programs: divisibility,
  replicated-output honesty under ``check_vma=False``, donation spec
  aliasing, quantization-scale/weight co-sharding.

Eight rules (``mem_rules.py``), each mechanizing a lesson the repo paid
for on hardware or in a compile log — the PR 10 d=64 padding OOM and
pool double-buffering, the PR 14 VMEM block rejections, the PR 16
scale-sharding invariant.

Usage::

    python -m apex_tpu.analysis --mem
    python -m apex_tpu.analysis --mem --select mem-hbm-over-budget

Findings share the AST tier's suppression pragmas, baseline file
(tier-partitioned by the ``mem-`` prefix — ``analysis/tiers.py``), and
the ``--diff`` CI mode (the base side re-runs the tier in a temporary
worktree of the base rev).
"""

from apex_tpu.analysis.mem.estimator import (MemEstimate,  # noqa: F401
                                             VMEM_BUDGET_BYTES,
                                             estimate_case)
from apex_tpu.analysis.mem.layout import (sublane_multiple,  # noqa: F401
                                          tiled_padded_bytes)
from apex_tpu.analysis.mem.mem_report import (ACCEPTANCE_TO_AOT,  # noqa: F401
                                              acceptance_estimates,
                                              analyze_mem, hbm_budget,
                                              mem_cases)
from apex_tpu.analysis.mem.mem_rules import (MEM_RULES,  # noqa: F401
                                             MemContext)

__all__ = ["MEM_RULES", "MemContext", "MemEstimate",
           "VMEM_BUDGET_BYTES", "ACCEPTANCE_TO_AOT",
           "acceptance_estimates", "analyze_mem", "estimate_case",
           "hbm_budget", "mem_cases", "sublane_multiple",
           "tiled_padded_bytes"]
