"""TPU tiled-layout padding math, shared by the mem tier and the cost
model.

On-chip arrays are stored in (sublane, lane) tiles: the minor dimension
pads to a multiple of 128 lanes, the second-minor to a multiple of the
dtype's sublane count — 8 rows of 4-byte elements, 16 of 2-byte, 32 of
1-byte (narrower dtypes pack more rows per physical sublane, so the
minimum tile covers more of them). Every dimension above the second-
minor is untiled and costs its logical extent.

The practical consequence this module exists to price (docs/
tp_serving.md "Pool sizing"): a ``head_dim=64`` KV pool pays 2x its
logical bytes on chip — 64 lanes pad to 128 — which is how PR 10's
first 512-slot acceptance pool OOM'd a 16 GiB chip at 25.6 GiB
"logical" 12.8. ``obs/costs.py`` deliberately prices LOGICAL bytes
(bandwidth and roofline math follow the bytes the program streams);
this helper answers the other question — the bytes the array OCCUPIES —
which is the one HBM/VMEM fit proofs need.

Stdlib-only on purpose: callers hand in plain shapes + an object with
``itemsize`` (a numpy/jax dtype) or an aval.
"""

from __future__ import annotations

from typing import Sequence, Tuple

LANE = 128          #: minor-dim tile width (all dtypes)
_SUBLANE_4B = 8     #: second-minor tile height for 4-byte elements


def _itemsize(dtype) -> int:
    size = getattr(dtype, "itemsize", None)
    if size is None:
        # extended dtypes (PRNG keys) carry no itemsize; 4 B/elem is the
        # same stand-in obs/costs.py uses for what is metadata-sized
        return 4
    return max(int(size), 1)


def sublane_multiple(dtype) -> int:
    """Second-minor tile height for ``dtype``: 8 (f32/i32), 16 (bf16),
    32 (int8/fp8/bool). 8-byte dtypes still tile at 8 rows."""
    return _SUBLANE_4B * max(4 // _itemsize(dtype), 1)


def _round_up(n: int, multiple: int) -> int:
    return -(-int(n) // multiple) * multiple


def padded_shape(shape: Sequence[int], dtype) -> Tuple[int, ...]:
    """``shape`` with the minor dim padded to 128 and the second-minor
    to the dtype's sublane multiple. Rank 0/1 arrays only pad the minor
    dim (they occupy a single sublane row; modeling the full 8-row tile
    would call every small 1-D table an 8x blowup, which is noise at the
    sizes such arrays actually have)."""
    dims = [int(d) for d in shape]
    if not dims:
        return ()
    dims[-1] = _round_up(dims[-1], LANE)
    if len(dims) >= 2:
        dims[-2] = _round_up(dims[-2], sublane_multiple(dtype))
    return tuple(dims)


def tiled_padded_bytes(shape: Sequence[int], dtype) -> int:
    """Physical HBM/VMEM bytes of one array in TPU tiled layout."""
    n = 1
    for d in padded_shape(shape, dtype):
        n *= d
    return n * _itemsize(dtype)


def logical_bytes(shape: Sequence[int], dtype) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n * _itemsize(dtype)


def aval_padded_bytes(aval) -> int:
    """``tiled_padded_bytes`` over an aval / ShapeDtypeStruct; objects
    without shape+dtype (tokens, opaque effects) cost 0."""
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return 0
    return tiled_padded_bytes(getattr(aval, "shape", ()), dt)


def aval_logical_bytes(aval) -> int:
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return 0
    return logical_bytes(getattr(aval, "shape", ()), dt)
