"""Rule definitions for the tpu-lint mem tier (``mem-*`` namespace).

Eight rules over one :class:`MemContext` (a traced case + its static
memory estimate + its declared budget):

fit proofs
    ``mem-hbm-over-budget``        raw padded peak exceeds the chip
    ``mem-scan-carry-double-buffer``  fits, until the scan's double-
                                   buffered carry is charged (the
                                   docs/tp_serving.md pool-sizing rule)
    ``mem-vmem-over-budget``       a pallas_call's blocks overflow the
                                   16 MiB scoped-VMEM stack
    ``mem-padding-blowup``         an array pays >= 2x its logical
                                   bytes in tile padding (the d=64 pool)

sharding contracts
    ``mem-spec-indivisible``       declared spec axes don't divide the
                                   mesh (caught BEFORE shard_map's own
                                   opaque trace error)
    ``mem-replicated-no-collective``  a replicated output depends on a
                                   sharded input with no collective on
                                   the path (check_vma=False hides it)
    ``mem-donation-spec-mismatch`` a donated sharded buffer has no
                                   same-spec output to alias in place
    ``mem-scale-shard-drift``      a quantization scale doesn't shard
                                   with its weight's axis (PR 16
                                   invariant)

The two HBM rules are deliberately DISJOINT: over-budget fires only
when the no-double-buffer peak already misses, the scan-carry rule only
when double buffering is the difference — so each failure names the
lesson that was violated, and each rule is individually load-bearing.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from apex_tpu.analysis.mem.estimator import (MemEstimate, ShardMapInfo,
                                             VMEM_BUDGET_BYTES)

GIB = 1024 ** 3
MIB = 1024 ** 2

#: padding-blowup thresholds: ratio is the lesson (2x), the waste floor
#: keeps lint-scale fixtures (tiny pools, small tables) quiet — the rule
#: is about buffers that matter to a 16 GiB chip
PAD_BLOWUP_RATIO = 2.0
PAD_BLOWUP_MIN_WASTE_BYTES = 64 * MIB

#: primitives that make a sharded value consistent across the axis —
#: crossing one of these blesses a replicated output's data path
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "all_gather", "all_gather_invariant", "all_to_all",
    "ppermute", "pbroadcast", "psum_scatter", "reduce_scatter", "pmin",
    "pmax", "pgather",
})


def _fmt_bytes(n: int) -> str:
    if n >= GIB:
        return f"{n / GIB:.2f} GiB"
    return f"{n / MIB:.1f} MiB"


@dataclasses.dataclass
class MemContext:
    """Everything one rule may consult for one case."""

    ir: object                    # CaseIR
    est: MemEstimate
    budget_bytes: int
    budget_label: str             # "v5e" / "v5p" / "meta override"

    @property
    def meta(self) -> dict:
        return self.ir.prog.meta or {}

    def aligned_leaves(self) -> Optional[List[
            Tuple[str, object, Dict[int, Tuple[str, ...]]]]]:
        """``(path_label, global_aval, {dim: axes})`` per argument leaf,
        via the whole-program shard_map whose operand count matches the
        flattened argument tree — None when there is no such alignment
        (non-sharded program, or consts broke positionality)."""
        leaves = self.est.arg_leaves
        if not leaves:
            return None
        for info in self.est.shard_maps:
            if len(info.in_names) != len(leaves) or \
                    len(info.eqn.invars) != len(leaves):
                continue
            return [(label, info.eqn.invars[i].aval, info.in_axes(i))
                    for i, (label, _leaf, _arg) in enumerate(leaves)]
        return None


@dataclasses.dataclass
class RawMemFinding:
    """Pre-anchor finding: the report maps ``eqn`` through source_info
    (case-origin fallback when None)."""

    message: str
    eqn: object = None


@dataclasses.dataclass
class MemRule:
    name: str
    severity: str
    summary: str
    check: Callable[[MemContext], List[RawMemFinding]]


MEM_RULES: Dict[str, MemRule] = {}


def mem_rule(name: str, severity: str, summary: str):
    def wrap(fn):
        MEM_RULES[name] = MemRule(name, severity, summary, fn)
        return fn
    return wrap


# --------------------------------------------------------------------------
# fit proofs
# --------------------------------------------------------------------------

@mem_rule("mem-hbm-over-budget", "error",
          "static per-chip peak HBM (tiled-padded, liveness-swept) "
          "exceeds the case's declared chip budget")
def _hbm_over_budget(ctx: MemContext) -> List[RawMemFinding]:
    est = ctx.est
    if est.peak_no_db_bytes <= ctx.budget_bytes:
        return []
    return [RawMemFinding(
        f"{est.scope} peak HBM {_fmt_bytes(est.peak_no_db_bytes)} "
        f"(tiled-padded, before scan double-buffering) exceeds the "
        f"{ctx.budget_label} budget {_fmt_bytes(ctx.budget_bytes)} — "
        f"shard further, quantize, or shrink the resident state")]


@mem_rule("mem-scan-carry-double-buffer", "error",
          "the program fits only if XLA's double-buffered scan carry is "
          "ignored — the docs/tp_serving.md pool-sizing rule")
def _scan_carry_double_buffer(ctx: MemContext) -> List[RawMemFinding]:
    est = ctx.est
    if not (est.peak_no_db_bytes <= ctx.budget_bytes < est.peak_bytes):
        return []
    return [RawMemFinding(
        f"{est.scope} peak {_fmt_bytes(est.peak_no_db_bytes)} fits the "
        f"{ctx.budget_label} budget {_fmt_bytes(ctx.budget_bytes)}, but "
        f"XLA double-buffers the scan carry "
        f"(+{_fmt_bytes(est.scan_carry_extra_bytes)}) for a true peak of "
        f"{_fmt_bytes(est.peak_bytes)} — size the pool shard to ~half "
        f"the free HBM (docs/tp_serving.md 'Pool sizing')")]


@mem_rule("mem-vmem-over-budget", "error",
          "a pallas_call's block working set overflows the 16 MiB "
          "scoped-VMEM stack")
def _vmem_over_budget(ctx: MemContext) -> List[RawMemFinding]:
    out: List[RawMemFinding] = []
    for call in ctx.est.vmem:
        if call.est_bytes <= VMEM_BUDGET_BYTES:
            continue
        out.append(RawMemFinding(
            f"pallas_call {call.kernel_name!r}: {call.n_blocks} blocks "
            f"x{call.buffering} grid buffering = "
            f"{_fmt_bytes(call.est_bytes)} VMEM > "
            f"{_fmt_bytes(VMEM_BUDGET_BYTES)} — shrink the block shape "
            f"(Mosaic will reject or spill this at compile)",
            eqn=call.eqn))
    return out


@mem_rule("mem-padding-blowup", "warning",
          "a boundary array pays >= 2x its logical bytes in TPU tile "
          "padding (e.g. a head_dim-64 pool)")
def _padding_blowup(ctx: MemContext) -> List[RawMemFinding]:
    out: List[RawMemFinding] = []
    for arr in ctx.est.boundary:
        if arr.logical_bytes <= 0:
            continue
        waste = arr.padded_bytes - arr.logical_bytes
        if arr.padded_bytes < PAD_BLOWUP_RATIO * arr.logical_bytes or \
                waste < PAD_BLOWUP_MIN_WASTE_BYTES:
            continue
        out.append(RawMemFinding(
            f"{arr.kind} array {arr.label} {arr.shape} {arr.dtype}: "
            f"tiled layout pads {_fmt_bytes(arr.logical_bytes)} logical "
            f"to {_fmt_bytes(arr.padded_bytes)} on chip "
            f"({arr.padded_bytes / arr.logical_bytes:.1f}x) — lane-align "
            f"the minor dims (docs/tp_serving.md: a d=64 pool pays 2x)"))
    return out


# --------------------------------------------------------------------------
# sharding contracts
# --------------------------------------------------------------------------

def _spec_dims(spec) -> List[Tuple[int, Tuple[str, ...]]]:
    """PartitionSpec -> [(dim, axis names)] for sharded dims."""
    out = []
    for d, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        out.append((d, tuple(str(a) for a in axes)))
    return out


def _declared_specs(ctx: MemContext):
    """Zip declared ``meta['arg_specs']`` with the argument leaves:
    yields ``(label, aval, spec)`` per (leaf, PartitionSpec) pair."""
    import jax

    specs = ctx.meta.get("arg_specs")
    if specs is None:
        return
    for i, arg in enumerate(ctx.ir.prog.args):
        if i >= len(specs) or specs[i] is None:
            continue
        flat = jax.tree_util.tree_flatten_with_path(arg)[0]
        spec_leaves = jax.tree_util.tree_leaves(
            specs[i], is_leaf=lambda s: hasattr(s, "index") or s is None)
        if len(flat) != len(spec_leaves):
            continue                       # malformed declaration: skip
        for (path, leaf), spec in zip(flat, spec_leaves):
            if spec is None or not hasattr(leaf, "shape"):
                continue
            name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                            for k in path)
            yield (f"arg{i}" + (f"/{name}" if name else ""), leaf, spec)


@mem_rule("mem-spec-indivisible", "error",
          "a declared operand PartitionSpec axis does not divide the "
          "mesh axis size into the operand's dimension")
def _spec_indivisible(ctx: MemContext) -> List[RawMemFinding]:
    mesh_axes = ctx.meta.get("mesh_axes") or {}
    if not mesh_axes:
        return []
    out: List[RawMemFinding] = []
    for label, aval, spec in _declared_specs(ctx):
        shape = tuple(getattr(aval, "shape", ()))
        for d, axes in _spec_dims(spec):
            total = 1
            for a in axes:
                total *= int(mesh_axes.get(a, 1))
            if d >= len(shape) or total <= 1:
                continue
            if int(shape[d]) % total:
                out.append(RawMemFinding(
                    f"{label} {shape}: dim {d} (size {shape[d]}) is "
                    f"declared sharded over {'*'.join(axes)} = {total} "
                    f"chips, which does not divide it — shard_map will "
                    f"refuse this program at trace time"))
    return out


def _contains_collective(eqn) -> bool:
    from apex_tpu.analysis.mem.estimator import iter_eqns

    if eqn.primitive.name in COLLECTIVE_PRIMS:
        return True
    for sub, _ in _iter_subs(eqn):
        for e in iter_eqns(sub):
            if e.primitive.name in COLLECTIVE_PRIMS:
                return True
    return False


def _iter_subs(eqn):
    from apex_tpu.analysis.mem.estimator import _sub_jaxprs

    return _sub_jaxprs(eqn)


@mem_rule("mem-replicated-no-collective", "error",
          "a shard_map output declared replicated depends on a sharded "
          "input with no collective on the path (check_vma=False makes "
          "this a silent cross-chip divergence)")
def _replicated_no_collective(ctx: MemContext) -> List[RawMemFinding]:
    out: List[RawMemFinding] = []
    for info in ctx.est.shard_maps:
        sharded_in = {info.body.invars[i]
                      for i in range(len(info.body.invars))
                      if i < len(info.in_names) and info.in_names[i]}
        if not sharded_in:
            continue
        producer = {}
        for eqn in info.body.eqns:
            for v in eqn.outvars:
                producer[v] = eqn
        for o, outvar in enumerate(info.body.outvars):
            if o < len(info.out_names) and info.out_names[o]:
                continue                       # output is sharded: fine
            if not hasattr(outvar, "count"):
                continue                       # literal output
            # reverse BFS: does this replicated output reach a sharded
            # input without crossing a collective?
            stack, seen, tainted = [outvar], set(), False
            while stack and not tainted:
                v = stack.pop()
                if id(v) in seen:
                    continue
                seen.add(id(v))
                if v in sharded_in:
                    tainted = True
                    break
                eqn = producer.get(v)
                if eqn is None or _contains_collective(eqn):
                    continue                   # input/const, or blessed
                stack.extend(u for u in eqn.invars
                             if hasattr(u, "count"))
            if tainted:
                out.append(RawMemFinding(
                    f"shard_map output {o} is declared replicated "
                    f"(out spec {{}}) but depends on a sharded input "
                    f"with no psum/all_gather on the path — each chip "
                    f"returns a DIFFERENT value and check_vma=False "
                    f"asserts nothing", eqn=info.eqn))
    return out


@mem_rule("mem-donation-spec-mismatch", "error",
          "a donated sharded buffer has no output with the same "
          "shape+dtype+spec to alias — the donation cannot happen "
          "in place")
def _donation_spec_mismatch(ctx: MemContext) -> List[RawMemFinding]:
    leaves = ctx.est.arg_leaves
    donate = ctx.ir.prog.donate
    if not donate or not leaves:
        return []
    out: List[RawMemFinding] = []
    for info in ctx.est.shard_maps:
        if len(info.in_names) != len(leaves) or \
                len(info.eqn.invars) != len(leaves):
            continue
        # output alias budget: (shape, dtype, frozen dim->axes)
        budget: Dict[tuple, int] = {}
        for o, outvar in enumerate(info.eqn.outvars):
            aval = getattr(outvar, "aval", None)
            if getattr(aval, "dtype", None) is None:
                continue
            key = (tuple(aval.shape), str(aval.dtype),
                   tuple(sorted((d, tuple(a)) for d, a in
                                info.out_axes(o).items())))
            budget[key] = budget.get(key, 0) + 1
        for pos, (label, _leaf, arg_i) in enumerate(leaves):
            if arg_i not in donate:
                continue
            axes = info.in_axes(pos)
            if not axes:
                continue                   # replicated: ir tier's job
            aval = info.eqn.invars[pos].aval
            if getattr(aval, "dtype", None) is None:
                continue
            key = (tuple(aval.shape), str(aval.dtype),
                   tuple(sorted((d, tuple(a)) for d, a in axes.items())))
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                continue
            spec = ", ".join(f"dim{d}:{'*'.join(a)}"
                             for d, a in sorted(axes.items()))
            out.append(RawMemFinding(
                f"donated sharded buffer {label} {tuple(aval.shape)} "
                f"({spec}) has no output with the same shape+dtype+spec "
                f"to alias — the donation is dead weight and the chip "
                f"holds both copies", eqn=info.eqn))
    return out


#: (scale leaf key -> its weight partner's key) — the repo's two
#: quantized families: KV pools pair k/v_scales with k/v_pages
#: (serving/kv_pool.py), quantized linears pair scale with weight
#: (transformer/tensor_parallel/layers.py `_quantized_params`)
_SCALE_PARTNERS = (("k_scales", "k_pages"), ("v_scales", "v_pages"),
                   ("scale", "weight"), ("w_scale", "w"))


@mem_rule("mem-scale-shard-drift", "error",
          "a quantization scale does not shard with its weight's axis "
          "(the PR 16 invariant: scales follow the channels they scale)")
def _scale_shard_drift(ctx: MemContext) -> List[RawMemFinding]:
    aligned = ctx.aligned_leaves()
    if not aligned:
        return []
    by_path = {label: (aval, axes) for label, aval, axes in aligned}
    out: List[RawMemFinding] = []
    for label, scale_aval, scale_axes in aligned:
        head, _, key = label.rpartition("/")
        partner_key = dict(_SCALE_PARTNERS).get(key)
        if partner_key is None:
            continue
        partner = by_path.get(f"{head}/{partner_key}" if head
                              else partner_key)
        if partner is None:
            continue
        w_aval, w_axes = partner
        w_shape = tuple(getattr(w_aval, "shape", ()))
        s_shape = tuple(getattr(scale_aval, "shape", ()))
        s_axis_names = {a for axes in scale_axes.values() for a in axes}
        w_axis_names = {a for axes in w_axes.values() for a in axes}
        # every weight axis whose sharded dim the scale MIRRORS (same
        # extent appears in the scale's shape) must shard the scale too;
        # axes over dims the scale lacks (e.g. row-parallel input
        # channels vs a per-out-channel scale) legitimately replicate.
        # The extent match must be UNAMBIGUOUS: a square row-parallel
        # weight (1024, 1024) sharded on its input dim has a (1024,)
        # per-out-channel scale that mirrors the OTHER dim — matching on
        # a repeated extent would call every such scale drifted
        for d, axes in w_axes.items():
            if d >= len(w_shape) or w_shape[d] not in s_shape or \
                    w_shape.count(w_shape[d]) > 1:
                continue
            for a in axes:
                if a not in s_axis_names:
                    out.append(RawMemFinding(
                        f"scale {label} {s_shape} replicates over "
                        f"{a!r} while its weight {head or label}/"
                        f"{partner_key} {w_shape} shards dim {d} "
                        f"(size {w_shape[d]}) on it — each chip would "
                        f"scale its shard with the WRONG rows "
                        f"(docs/tp_serving.md: scales follow their "
                        f"weight's axis)"))
        for a in sorted(s_axis_names - w_axis_names):
            out.append(RawMemFinding(
                f"scale {label} {s_shape} shards over {a!r} but its "
                f"weight {head or label}/{partner_key} {w_shape} does "
                f"not — the scale rows no longer line up with the "
                f"weight shard"))
    return out
