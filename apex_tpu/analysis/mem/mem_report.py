"""Orchestration for the tpu-lint mem tier (``--mem``).

Same engine shape as the IR tier one directory over: build the case
registry, trace each case (the IR harness's ``build_case_ir`` — one
trace serves both tiers' rule sets), run the selected ``mem-*`` rules
over the static estimate, anchor findings to source via equation
``source_info`` (case-origin fallback), apply inline suppressions.
Baseline handling stays in the CLI.

The registry is ``analysis_cases()`` **plus the AOT acceptance meshes**:
the ``tp4_paged_engine_*`` programs ``tpu_aot.py`` compiles for the
deviceless v5e topology are re-registered here over an ``AbstractMesh``
at the same acceptance shape (384 slots, hidden 1024, tp=4) — so the
per-chip fit proof the slow AOT tier measures with
``memory_analysis()`` is also computed statically on every lint run,
and ``tests/test_aot_mosaic.py`` pins the two within a ±20% band
instead of hand-typed byte pins.

A case that fails to trace (or estimate) yields a ``mem-trace-error``
finding instead of crashing — one broken entry point must not hide the
rest.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from apex_tpu.analysis.ir.harness import (AnalysisCase, CaseIR,
                                          CaseProgram, analysis_cases,
                                          build_case_ir)
from apex_tpu.analysis.ir.ir_report import (_case_anchor,
                                            _SuppressionCache,
                                            eqn_anchor)
from apex_tpu.analysis.mem.estimator import MemEstimate, estimate_case
from apex_tpu.analysis.mem.mem_rules import MEM_RULES, MemContext
from apex_tpu.analysis.walker import Finding

#: mem-tier case name -> the AOT multichip case it mirrors (the ±20%
#: static-vs-measured band in tests/test_aot_mosaic.py joins on this)
ACCEPTANCE_TO_AOT = {
    "tp4_serving_admit": "tp4_paged_engine_admit",
    "tp4_serving_decode_chunk": "tp4_paged_engine_decode_chunk",
    "tp4_serving_decode_w8": "tp4_paged_engine_decode_w8",
}


def hbm_budget(prog: CaseProgram) -> Tuple[int, str]:
    """The case's declared per-chip HBM budget: an explicit
    ``meta['hbm_budget_bytes']`` override, else the
    ``meta['chip_profile']`` entry of ``obs.costs.PROFILES``
    (default v5e, 16 GiB — the serving acceptance chip)."""
    meta = prog.meta or {}
    if "hbm_budget_bytes" in meta:
        return int(meta["hbm_budget_bytes"]), "declared"
    from apex_tpu.obs.costs import PROFILES

    name = meta.get("chip_profile", "v5e")
    profile = PROFILES.get(name, PROFILES["v5e"])
    return profile.hbm_bytes, profile.name


# --------------------------------------------------------------------------
# the acceptance-mesh cases
# --------------------------------------------------------------------------

def _build_tp4_acceptance(kind: str, weight_policy=None) -> CaseProgram:
    """The ``tpu_aot.py`` tp=4 serving acceptance programs, traced over
    a deviceless ``AbstractMesh`` at the REAL acceptance shape (the IR
    registry's tp2 twins run lint-scale pools; the fit proof needs the
    18 GiB-unsharded one). Shape constants and config come from
    ``tpu_aot`` so the static and AOT sides cannot drift apart."""
    import jax
    import jax.numpy as jnp
    import tpu_aot

    from apex_tpu.serving.scheduler import prompt_bucket
    from apex_tpu.serving.tp import (TensorParallelPagedEngine,
                                     abstract_tp_mesh,
                                     infer_variable_specs)
    from jax.sharding import PartitionSpec as P

    tp = tpu_aot.TP_SERVING_TP
    cfg = tpu_aot.tp_serving_config(weight_policy=weight_policy)
    engine = TensorParallelPagedEngine(
        model=__import__("apex_tpu.models.gpt",
                         fromlist=["GPTModel"]).GPTModel(cfg),
        variables=None, mesh=abstract_tp_mesh(tp),
        num_slots=tpu_aot.TP_SERVING_SLOTS,
        page_size=tpu_aot.TP_SERVING_PAGE_SIZE,
        max_pages_per_seq=tpu_aot.TP_SERVING_MAX_PAGES_PER_SEQ,
        sync_every=4)
    dvars, var_specs = infer_variable_specs(engine.model)
    i32 = jnp.int32
    meta = {"chip_profile": "v5e", "mesh_axes": {"model": tp}}
    n = tpu_aot.TP_SERVING_SLOTS
    if kind == "decode":
        args = (engine.cache, dvars,
                jax.ShapeDtypeStruct((n,), i32),
                jax.ShapeDtypeStruct((n,), jnp.bool_),
                jax.ShapeDtypeStruct((n,), i32),
                jax.ShapeDtypeStruct((n, 2), jnp.uint32),
                jax.ShapeDtypeStruct((n,), i32))
        meta["arg_specs"] = (engine._cache_specs, var_specs,
                             P(), P(), P(), P(), P())
        # the pool updates in place in production (tpu_aot donates arg
        # 0 the same way) — without the alias credit no 16 GiB chip
        # holds a >4 GiB-sharded double-buffered program
        return CaseProgram(fn=engine._step_fn(), args=args, donate=(0,),
                           meta=meta)
    assert kind == "admit"
    bucket = prompt_bucket(128, engine.page_size,
                           cfg.max_position_embeddings)
    args = (engine.cache, dvars,
            jax.ShapeDtypeStruct((1, bucket), i32),
            jax.ShapeDtypeStruct((), i32),
            jax.ShapeDtypeStruct((), i32),
            jax.ShapeDtypeStruct((), i32),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
            jax.ShapeDtypeStruct((), i32))
    meta["arg_specs"] = (engine._cache_specs, var_specs,
                         P(), P(), P(), P(), P(), P())
    return CaseProgram(fn=engine._admit_fn(bucket), args=args,
                       donate=(0,), meta=meta)


def acceptance_cases(root: Path) -> List[AnalysisCase]:
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))
    return [
        AnalysisCase("tp4_serving_admit", "serving",
                     lambda: _build_tp4_acceptance("admit")),
        AnalysisCase("tp4_serving_decode_chunk", "serving",
                     lambda: _build_tp4_acceptance("decode")),
        AnalysisCase("tp4_serving_decode_w8", "serving",
                     lambda: _build_tp4_acceptance(
                         "decode", weight_policy="int8")),
    ]


def mem_cases(root) -> List[AnalysisCase]:
    """The mem tier's registry: every IR-harness case plus the AOT
    acceptance meshes."""
    root = Path(root).resolve()
    return list(analysis_cases(root)) + acceptance_cases(root)


def acceptance_estimates(root) -> Dict[str, MemEstimate]:
    """``{aot_case_name: MemEstimate}`` for the tp4 acceptance
    programs — what ``tests/test_aot_mosaic.py`` bands against
    ``compiled.memory_analysis()``."""
    root = Path(root).resolve()
    out: Dict[str, MemEstimate] = {}
    for case in acceptance_cases(root):
        out[ACCEPTANCE_TO_AOT[case.name]] = estimate_case(
            build_case_ir(case))
    return out


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------

def findings_for_mem_case(ir: CaseIR, root: Path,
                          select: Optional[Iterable[str]] = None
                          ) -> List[Finding]:
    """Estimate + run the (selected) mem rules over one traced case."""
    chosen = set(select) if select is not None else set(MEM_RULES)
    try:
        est = estimate_case(ir)
        budget, label = hbm_budget(ir.prog)
        ctx = MemContext(ir=ir, est=est, budget_bytes=budget,
                         budget_label=label)
    except Exception as e:          # noqa: BLE001 — findings, not crashes
        anchor = _case_anchor(ir, root)
        return [Finding(
            rule="mem-trace-error", severity="error", path=anchor[0],
            line=anchor[1], col=1, scope=ir.name,
            message=f"[case {ir.name}] failed to estimate: "
                    f"{type(e).__name__}: {e}")]
    out: List[Finding] = []
    for name in sorted(chosen):
        rule = MEM_RULES[name]
        for raw in rule.check(ctx):
            anchor = eqn_anchor(raw.eqn, root) if raw.eqn is not None \
                else None
            if anchor is None:
                anchor = _case_anchor(ir, root)
            out.append(Finding(
                rule=rule.name, severity=rule.severity, path=anchor[0],
                line=anchor[1], col=1,
                message=f"[case {ir.name}] {raw.message}",
                scope=ir.name))
    return out


def analyze_mem(root, *, select: Optional[Iterable[str]] = None,
                case: Optional[str] = None,
                ) -> Tuple[List[Finding], int, int]:
    """Trace the mem registry and run the fit proofs; returns
    ``(findings, #suppressed, #cases)`` — the same contract as
    ``analyze_ir``."""
    root = Path(root).resolve()
    if select is not None:
        unknown = set(select) - set(MEM_RULES)
        if unknown:
            raise ValueError(
                f"unknown mem rule(s): {', '.join(sorted(unknown))}")
    try:
        cases = mem_cases(root)
    except Exception as e:          # noqa: BLE001 — findings, not crashes
        return ([Finding(
            rule="mem-trace-error", severity="error", path="tpu_aot.py",
            line=1, col=1, scope="<registry>",
            message=f"failed to build the mem case registry: "
                    f"{type(e).__name__}: {e}")], 0, 0)
    if case is not None:
        cases = [c for c in cases if c.name == case]
        if not cases:
            raise ValueError(f"unknown mem case: {case}")
    supp = _SuppressionCache(root)
    findings: List[Finding] = []
    suppressed = 0
    for c in cases:
        try:
            ir = build_case_ir(c)
        except Exception as e:      # noqa: BLE001 — findings, not crashes
            findings.append(Finding(
                rule="mem-trace-error", severity="error",
                path="apex_tpu/analysis/mem/mem_report.py", line=1,
                col=1, scope=c.name,
                message=f"[case {c.name}] failed to trace: "
                        f"{type(e).__name__}: {e}"))
            continue
        for f in findings_for_mem_case(ir, root, select):
            if supp.get(f.path).covers(f):
                suppressed += 1
            else:
                findings.append(f)
    return findings, suppressed, len(cases)
