"""Checked-in baseline of justified legacy findings.

The baseline stores *counts* per line-number-free key
(``path::rule::scope``), so findings survive unrelated edits above them
but a NEW finding of the same rule in the same function still fails
the run (the count grows past the recorded one). The file is plain
sorted JSON so diffs are reviewable: shrink it freely, grow it only
with a PR that argues why.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Iterable, List, Tuple

from apex_tpu.analysis.walker import Finding

FORMAT_VERSION = 1


class Baseline:
    def __init__(self, counts: Counter | None = None,
                 path: Path | None = None):
        self.counts: Counter = counts or Counter()
        self.path = path

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls(path=path)
        data = json.loads(path.read_text())
        if data.get("version") != FORMAT_VERSION:
            raise ValueError(
                f"{path}: unsupported baseline version "
                f"{data.get('version')!r} (expected {FORMAT_VERSION})")
        return cls(Counter({k: int(v)
                            for k, v in data.get("findings", {}).items()}),
                   path=path)

    def split(self, findings: Iterable[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """(new, baselined): the first ``counts[key]`` findings per key
        are absorbed by the baseline, the rest are live."""
        budget = Counter(self.counts)
        new: List[Finding] = []
        absorbed: List[Finding] = []
        for f in findings:
            key = f.baseline_key()
            if budget[key] > 0:
                budget[key] -= 1
                absorbed.append(f)
            else:
                new.append(f)
        return new, absorbed

    @staticmethod
    def write(path: Path, findings: Iterable[Finding],
              keep: dict | None = None) -> None:
        """Record ``findings``; ``keep`` carries out-of-scope entries a
        path-filtered run must not erase (current findings win on
        shared keys)."""
        counts = dict(keep or {})
        counts.update(Counter(f.baseline_key() for f in findings))
        payload = {"version": FORMAT_VERSION,
                   "findings": dict(sorted(counts.items()))}
        path.write_text(json.dumps(payload, indent=2, sort_keys=False)
                        + "\n")
