"""tpu-lint: AST static analysis for jit / Pallas / serving hazards.

The hazard classes this package guards are the ones PR 1-2 established by
convention and reviewer memory alone:

* host synchronisation reachable from jitted code (``.item()``,
  ``np.asarray``, ``jax.device_get`` inside the serving scheduler's
  ``lax.scan`` hot loop would stall every decode step);
* Pallas kernel contracts (BlockSpec index-map arity vs. the grid,
  (sublane, lane) tiling multiples, ``out_shape`` dtype drift, Python
  branches on traced refs inside kernel bodies);
* recompile hazards (unhashable objects flowing into ``static_argnums``,
  unbounded f-string compile-cache keys — the prefix cache's
  power-of-two match-depth flooring is the house style);
* donation misuse (reading a buffer after passing it through
  ``donate_argnums``);
* AOT case-list drift between ``tpu_aot.py`` and the CI tier's
  ``CASE_NAMES``.

Three tiers share the CLI, the suppression pragmas and the baseline
(tier-partitioned by rule namespace — ``apex_tpu.analysis.tiers``):

* the **AST tier** (this package's ``rules``/``walker``/``project``)
  reads source — whole-repo INTERPROCEDURAL since ISSUE 5: imports are
  linked into one call graph, so ``host-sync-in-jit`` sees through
  helpers imported from ``utils/`` and ``jit-donated-reuse`` tracks
  wrappers imported from other modules;
* the **IR tier** (``apex_tpu.analysis.ir``, ``--ir``) traces the
  registered entry points (``tpu_aot.kernel_cases()`` + the serving
  engine programs) with ``jax.make_jaxpr`` on CPU and lints the STAGED
  programs — dtype promotion drift, dead scan state, ineffective
  donation, compile-key cardinality — mapping findings back to source
  via ``eqn.source_info``;
* the **concurrency tier** (``apex_tpu.analysis.conc``, ``--conc``)
  reads what the HOST does across threads — pump/exporter/callback
  thread coloring, lockset propagation with Eraser-style GuardedBy
  inference, lock-order cycles, blocking-under-lock, and
  alloc/release / begin/end resource pairing on early-exit paths.

The AST and conc tiers are stdlib-``ast`` only (no third-party lint
deps, no jax import); the IR tier needs jax but no TPU.

Usage::

    python -m apex_tpu.analysis [paths ...] [--format text|json]
    python -m apex_tpu.analysis --ir [--ir-case NAME]
    python -m apex_tpu.analysis --conc
    python -m apex_tpu.analysis --diff <base-rev>
    apex-tpu-lint --list-rules

Inline suppression (same line, the statement's first line, or a
comment-only line directly above)::

    x = traced.item()  # tpu-lint: disable=host-sync-in-jit -- why it's ok

Justified legacy findings can instead live in a checked-in baseline
(``tpu_lint_baseline.json``, written with ``--write-baseline``); only
findings *above* the baseline fail the run.
"""

from apex_tpu.analysis.baseline import Baseline
from apex_tpu.analysis.cli import analyze_paths, analyze_sources, main
from apex_tpu.analysis.project import ProjectIndex
from apex_tpu.analysis.walker import Finding, ModuleIndex
from apex_tpu.analysis.rules import RULES, Rule

__all__ = [
    "Baseline",
    "Finding",
    "ModuleIndex",
    "ProjectIndex",
    "RULES",
    "Rule",
    "analyze_paths",
    "analyze_sources",
    "main",
]
