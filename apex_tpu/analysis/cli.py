"""tpu-lint command line: discovery, engine, exit codes.

``python -m apex_tpu.analysis`` (or the ``apex-tpu-lint`` console
script) with no path arguments scans the production surface — the
``apex_tpu/`` package plus the repo-root ``tpu_*.py`` / ``bench*.py``
drivers — exactly the set ``run_tpu_round.sh`` gates on. Exit status:

* 0 — clean (every finding suppressed inline or absorbed by the
  baseline);
* 1 — findings above the baseline;
* 2 — usage error / unreadable baseline.

Files that fail to parse produce a ``parse-error`` finding rather than
crashing the run: a syntax error in one driver must not hide findings
in the other twenty files.
"""

from __future__ import annotations

import argparse
import ast
import sys
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Tuple

from apex_tpu.analysis import report
from apex_tpu.analysis.baseline import Baseline
from apex_tpu.analysis.project import ProjectIndex
from apex_tpu.analysis.rules import RULES, module_rules, project_rules
from apex_tpu.analysis.suppressions import Suppressions
from apex_tpu.analysis.walker import Finding, ModuleIndex

DEFAULT_GLOBS = ("apex_tpu/**/*.py", "tpu_*.py", "bench*.py")
DEFAULT_BASELINE = "tpu_lint_baseline.json"

#: generated/vendored files never worth linting
_SKIP_PARTS = {"__pycache__", ".git", ".jax_cache"}


def discover(root: Path, paths: Sequence[str]) -> List[Path]:
    files: List[Path] = []
    if paths:
        for p in paths:
            pp = Path(p)
            if not pp.is_absolute() and not pp.exists() \
                    and (root / pp).exists():
                pp = root / pp       # cwd-relative first, root as fallback
            if pp.is_dir():
                files.extend(sorted(pp.rglob("*.py")))
            else:
                files.append(pp)
    else:
        for pattern in DEFAULT_GLOBS:
            files.extend(sorted(root.glob(pattern)))
    out, seen = [], set()
    for f in files:
        if any(part in _SKIP_PARTS for part in f.parts):
            continue
        key = f.resolve()
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def _rel(root: Path, path: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def read_sources(root: Path, paths: Sequence[str] = ()
                 ) -> Tuple["dict[str, str]", List[Finding]]:
    """The discovered surface as ``{rel posix path: source}`` plus
    unreadable-file findings — the one surface reader every source-only
    consumer (AST tier, conc tier, ``--diff``) shares."""
    findings: List[Finding] = []
    sources: "dict[str, str]" = {}
    for path in discover(root, paths):
        rel = _rel(root, path)
        try:
            sources[rel] = path.read_text()
        except OSError as e:
            findings.append(Finding(
                rule="parse-error", severity="error", path=rel, line=1,
                col=1, message=f"unreadable: {e}"))
    return sources, findings


def parse_sources(sources: "dict[str, str]"
                  ) -> Tuple["dict[str, ModuleIndex]", List[Finding]]:
    """Phase 1 for the source-only tiers: parse every module, turning
    syntax errors into findings instead of crashes."""
    findings: List[Finding] = []
    modules: "dict[str, ModuleIndex]" = {}
    for rel in sorted(sources):
        try:
            modules[rel] = ModuleIndex(rel, sources[rel])
        except SyntaxError as e:
            findings.append(Finding(
                rule="parse-error", severity="error", path=rel,
                line=e.lineno or 1, col=(e.offset or 0) + 1,
                message=f"syntax error: {e.msg}"))
    return modules, findings


def analyze_sources(sources: "dict[str, str]", *,
                    select: Optional[Iterable[str]] = None,
                    interprocedural: bool = True,
                    modules: "Optional[dict[str, ModuleIndex]]" = None,
                    ) -> Tuple[List[Finding], int]:
    """Run the MODULE rules over an in-memory ``{rel path: source}``
    map; returns (surviving findings, #suppressed). This is the engine
    under both :func:`analyze_paths` (sources read from disk) and
    ``--diff`` (sources read from a git base rev).

    Phase 1 parses every module; phase 2 (``interprocedural``) links
    them into one call graph (``project.ProjectIndex``) so jit
    reachability and imported jit wrappers cross file boundaries; then
    each module's rules run as before. ``modules`` supplies a
    pre-parsed (and, for interprocedural use, pre-LINKED) map so
    ``--diff`` can feed one parse to both source-only tiers — the
    caller then owns the parse-error findings.
    """
    chosen = set(select) if select is not None else set(RULES)
    findings: List[Finding] = []
    if modules is None:
        modules, findings = parse_sources(sources)
        if interprocedural:
            ProjectIndex(modules).link()
    suppressed = 0
    for rel, mi in modules.items():
        supp = Suppressions(mi.source)
        for r in module_rules():
            if r.name not in chosen:
                continue
            for f in r.check(mi):
                if supp.covers(f):
                    suppressed += 1
                else:
                    findings.append(f)
    return findings, suppressed


def analyze_paths(paths: Sequence[str] = (), *,
                  root: Optional[object] = None,
                  select: Optional[Iterable[str]] = None,
                  with_project_rules: bool = True,
                  ) -> Tuple[List[Finding], int]:
    """Run the rule set; returns (surviving findings, #suppressed).

    ``select`` limits to a subset of rule names (None = all). Inline
    suppressions are already applied; baseline handling is the
    caller's job (`main` does it) so library users see everything.
    """
    root = (Path(root) if root is not None else Path.cwd()).resolve()
    chosen = set(select) if select is not None else set(RULES)
    unknown = chosen - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")

    sources, findings = read_sources(root, paths)
    module_findings, suppressed = analyze_sources(sources, select=chosen)
    findings.extend(module_findings)
    if with_project_rules:
        for r in project_rules():
            if r.name in chosen:
                findings.extend(r.check(root))
    return findings, suppressed


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="apex-tpu-lint",
        description="AST + jaxpr-IR + host-concurrency + memory-budget "
                    "+ wire-contract static analysis for "
                    "jit/Pallas/serving hazards (five tiers: source, "
                    "staged jaxprs, the host threading/lock/resource "
                    "discipline of the serving stack, per-chip "
                    "HBM/VMEM fit proofs, and producer/consumer drift "
                    "proofs for the string-keyed observability "
                    "surface)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: apex_tpu/, "
                        "tpu_*.py, bench*.py under --root)")
    p.add_argument("--root", default=".",
                   help="repo root for default globs, the baseline file "
                        "and the cross-file drift rules")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: <root>/"
                        f"{DEFAULT_BASELINE} when present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="absorb every current finding into the baseline "
                        "file and exit 0")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings the baseline absorbs")
    p.add_argument("--select", default=None,
                   help="comma-separated rule names to run (default all; "
                        "validated against the active tier)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--ir", action="store_true",
                   help="run the jaxpr IR tier instead of the AST tier: "
                        "trace every registered entry point on CPU "
                        "(no TPU needed) and lint the staged programs")
    p.add_argument("--ir-case", default=None, metavar="NAME",
                   help="IR tier for ONE registered case (implies --ir)")
    p.add_argument("--conc", action="store_true",
                   help="run the host-concurrency tier instead: thread "
                        "coloring, lockset/GuardedBy inference, lock-"
                        "order cycles, blocking-under-lock, resource-"
                        "lifecycle pairing over the whole surface")
    p.add_argument("--mem", action="store_true",
                   help="run the memory-budget tier instead: trace every "
                        "registered case (plus the AOT acceptance "
                        "meshes) on CPU and prove per-chip HBM/VMEM fit "
                        "at tiled-padded sizes, plus shard_map sharding "
                        "contracts")
    p.add_argument("--mem-case", default=None, metavar="NAME",
                   help="mem tier for ONE registered case (implies "
                        "--mem)")
    p.add_argument("--contract", action="store_true",
                   help="run the wire/observability contract tier "
                        "instead: index every metric family, event "
                        "kind, HTTP route, SSE frame, schema pin and "
                        "ledger class against its consumers (docs "
                        "catalogs, goldens, validators, parsers) and "
                        "prove both directions agree")
    p.add_argument("--diff", default=None, metavar="BASE_REV",
                   help="fail only on findings introduced relative to "
                        "this git rev. Default: AST module rules + the "
                        "conc and contract tiers (source-only, so the "
                        "base rev is analyzable from git history). "
                        "With --mem: the mem tier on both sides — the "
                        "base side runs in a temporary worktree of the "
                        "base rev")
    return p


def _glob_regexes() -> "list":
    """DEFAULT_GLOBS translated to regexes with Path.glob semantics
    (``*`` does not cross ``/``; ``**/`` matches zero or more dirs) —
    fnmatch gets both wrong, and a hand-rolled per-shape matcher would
    silently drop files if the glob list ever grows a new shape."""
    import re

    out = []
    for g in DEFAULT_GLOBS:
        esc = re.escape(g)
        esc = esc.replace(r"\*\*/", "(?:.*/)?").replace(r"\*\*", ".*")
        esc = esc.replace(r"\*", "[^/]*").replace(r"\?", "[^/]")
        out.append(re.compile("^" + esc + "$"))
    return out


def _base_rev_sources(root: Path, rev: str) -> "dict[str, str]":
    """The default lint surface as it existed at ``rev`` (one
    ``git ls-tree`` + one ``git cat-file --batch``); raises ValueError
    on git errors (exit code 2)."""
    import subprocess

    def git(*args: str) -> str:
        proc = subprocess.run(["git", "-C", str(root), *args],
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise ValueError(
                f"git {' '.join(args[:2])} failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        return proc.stdout

    regexes = _glob_regexes()

    def on_surface(rel: str) -> bool:
        return any(rx.match(rel) for rx in regexes)

    listing = git("ls-tree", "-r", "--name-only", rev)
    wanted = [rel for rel in listing.splitlines()
              if on_surface(rel)
              and not any(p in _SKIP_PARTS for p in rel.split("/"))]
    if not wanted:
        return {}
    # ONE `cat-file --batch` round trip for all ~130 files (a `git show`
    # per file would pay fork+exec each); bytes mode — the size header
    # counts bytes, not str characters
    proc = subprocess.run(
        ["git", "-C", str(root), "cat-file", "--batch"],
        input="\n".join(f"{rev}:{rel}" for rel in wanted).encode(),
        capture_output=True)
    if proc.returncode != 0:
        raise ValueError(
            f"git cat-file failed: {proc.stderr.decode().strip()}")
    sources: "dict[str, str]" = {}
    buf, pos = proc.stdout, 0
    for rel in wanted:
        nl = buf.index(b"\n", pos)
        header = buf[pos:nl].decode()
        pos = nl + 1
        if header.endswith(("missing", "ambiguous")):
            continue                    # path absent at rev: new file
        size = int(header.rsplit(" ", 1)[1])
        sources[rel] = buf[pos:pos + size].decode(errors="replace")
        pos += size + 1                 # trailing newline after content
    return sources


def _base_rev_texts(root: Path, rev: str) -> "dict[str, str]":
    """The contract tier's text surface (docs catalogs + goldens) as it
    existed at ``rev``. Missing paths are simply absent — a base rev
    that predates a catalog contributes no consumer entries, so
    everything the current catalog pins reads as new."""
    import subprocess

    from apex_tpu.analysis.contract import TEXT_SURFACE

    proc = subprocess.run(
        ["git", "-C", str(root), "cat-file", "--batch"],
        input="\n".join(f"{rev}:{rel}"
                        for rel in TEXT_SURFACE).encode(),
        capture_output=True)
    if proc.returncode != 0:
        raise ValueError(
            f"git cat-file failed: {proc.stderr.decode().strip()}")
    texts: "dict[str, str]" = {}
    buf, pos = proc.stdout, 0
    for rel in TEXT_SURFACE:
        nl = buf.index(b"\n", pos)
        header = buf[pos:nl].decode()
        pos = nl + 1
        if header.endswith(("missing", "ambiguous")):
            continue                    # path absent at rev
        size = int(header.rsplit(" ", 1)[1])
        texts[rel] = buf[pos:pos + size].decode(errors="replace")
        pos += size + 1                 # trailing newline after content
    return texts


def _run_diff(args, root: Path, select) -> int:
    """Diff-aware mode: current module-rule, conc-tier AND
    contract-tier findings, minus whatever the base rev already had
    (counted with the same line-number-free ``path::rule::scope`` keys
    the baseline uses). All three tiers are source-only, so the base
    side is fully analyzable from git history (the contract tier's
    text surface rides along via ``_base_rev_texts``). Project rules
    are skipped on both sides — they need an on-disk tree; the
    absolute gate still runs them."""
    from collections import Counter

    from apex_tpu.analysis.conc.conc_report import (analyze_conc_sources,
                                                    build_model)
    from apex_tpu.analysis.conc.conc_rules import CONC_RULES
    from apex_tpu.analysis.contract import (analyze_contract_sources,
                                            read_text_surface)
    from apex_tpu.analysis.contract.contract_rules import CONTRACT_RULES

    ast_sel = conc_sel = contract_sel = None
    if select is not None:
        ast_sel = [s for s in select if s in RULES]
        conc_sel = [s for s in select if s in CONC_RULES]
        contract_sel = [s for s in select if s in CONTRACT_RULES]

    def all_tiers(sources, texts):
        """AST module rules + conc rules + contract rules over ONE
        parse+link of a surface (each side of the diff pays the parse
        once; the text surface feeds only the contract tier)."""
        model, findings = build_model(sources)
        ast_f, ast_supp = analyze_sources(
            sources, select=ast_sel, modules=model.modules)
        conc_f, conc_supp = analyze_conc_sources(
            sources, select=conc_sel, model=model)
        con_f, con_supp = analyze_contract_sources(
            {**sources, **texts}, select=contract_sel,
            modules=model.modules)
        return (findings + ast_f + conc_f + con_f,
                ast_supp + conc_supp + con_supp)

    try:
        base_sources = _base_rev_sources(root, args.diff)
        base_texts = _base_rev_texts(root, args.diff)
    except ValueError as e:
        print(f"error: --diff {args.diff}: {e}", file=sys.stderr)
        return 2
    base_findings, _ = all_tiers(base_sources, base_texts)
    base = Baseline(Counter(f.baseline_key() for f in base_findings))

    cur_sources, findings = read_sources(root)
    cur_findings, suppressed = all_tiers(cur_sources,
                                         read_text_surface(root))
    findings += cur_findings
    new, absorbed = base.split(findings)
    if args.format == "json":
        print(report.render_json(new, absorbed, suppressed))
    else:
        print(report.render_text(new, absorbed, suppressed,
                                 show_baselined=args.show_baselined))
        if new:
            print(f"tpu-lint: the findings above are NEW relative to "
                  f"{args.diff} ({len(absorbed)} pre-existing "
                  "absorbed)")
    return 1 if new else 0


def _mem_base_findings(root: Path, rev: str) -> "Counter":
    """Baseline-key counts of the mem tier at ``rev``. Unlike the
    source-only tiers, the mem tier TRACES live programs, so a source
    snapshot is not enough — the base rev is materialized in a
    temporary ``git worktree`` and its own ``--mem`` runs there as a
    subprocess (apex_tpu resolves from the working directory, so the
    worktree's code analyzes the worktree's cases). A base rev that
    predates the tier (usage error / unknown flag, exit 2) contributes
    no findings: everything the new tier reports is new."""
    import json as _json
    import os
    import shutil
    import subprocess
    import tempfile
    from collections import Counter

    tmp = Path(tempfile.mkdtemp(prefix="tpu-lint-mem-base-"))
    wt = tmp / "base"
    add = subprocess.run(
        ["git", "-C", str(root), "worktree", "add", "--detach",
         str(wt), rev], capture_output=True, text=True)
    if add.returncode != 0:
        shutil.rmtree(tmp, ignore_errors=True)
        raise ValueError(f"git worktree add {rev} failed: "
                         f"{add.stderr.strip() or add.stdout.strip()}")
    try:
        env = dict(os.environ)
        env["PYTHONPATH"] = str(wt)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # a baseline path that does not exist at the base rev: the diff
        # wants the base's RAW findings, not what its checked-in
        # baseline had already absorbed (render_json reports absorbed
        # findings too, but raw keeps the two sides symmetric)
        proc = subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis", "--mem",
             "--format", "json",
             "--baseline", str(wt / "_mem_diff_no_baseline.json")],
            cwd=str(wt), env=env, capture_output=True, text=True,
            timeout=1800)
        try:
            data = _json.loads(proc.stdout) \
                if proc.returncode in (0, 1) else None
        except ValueError:
            data = None
        if data is None:
            # exit 2 (pre-mem CLI rejects the flag), a crashed import
            # (the growth seed has no package at all), or junk output:
            # the tier didn't exist there, so nothing can be absorbed
            print(f"tpu-lint: base rev {rev} has no working --mem tier "
                  f"(exit {proc.returncode}); treating every mem "
                  f"finding as new", file=sys.stderr)
            return Counter()
        keys = [f"{f['path']}::{f['rule']}::{f.get('scope', '<module>')}"
                for f in data.get("findings", [])
                + data.get("baselined", [])]
        return Counter(keys)
    finally:
        subprocess.run(["git", "-C", str(root), "worktree", "remove",
                        "--force", str(wt)], capture_output=True)
        shutil.rmtree(tmp, ignore_errors=True)


def _run_mem_diff(args, root: Path, select) -> int:
    """``--diff BASE --mem``: the mem tier on both sides, the base
    side's findings acting as the baseline (same key arithmetic as
    ``_run_diff``). The base side always runs ALL mem rules — a
    --select'ed current side still diffs against the full base so a
    narrowed run cannot misreport pre-existing findings as new."""
    from apex_tpu.analysis.mem import analyze_mem

    base = Baseline(_mem_base_findings(root, args.diff))
    findings, suppressed, _ = analyze_mem(root, select=select)
    new, absorbed = base.split(findings)
    if args.format == "json":
        print(report.render_json(new, absorbed, suppressed))
    else:
        print(report.render_text(new, absorbed, suppressed,
                                 show_baselined=args.show_baselined))
        if new:
            print(f"tpu-lint: the mem findings above are NEW relative "
                  f"to {args.diff} ({len(absorbed)} pre-existing "
                  f"absorbed)")
    return 1 if new else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.ir_case:
        args.ir = True
    if args.mem_case:
        args.mem = True
    if args.list_rules:
        from apex_tpu.analysis.conc.conc_rules import CONC_RULES
        from apex_tpu.analysis.contract.contract_rules import \
            CONTRACT_RULES
        from apex_tpu.analysis.ir.ir_rules import IR_RULES
        from apex_tpu.analysis.mem.mem_rules import MEM_RULES

        width = max(len(n) for n in
                    list(RULES) + list(IR_RULES) + list(CONC_RULES)
                    + list(MEM_RULES) + list(CONTRACT_RULES))
        for name, r in sorted(RULES.items()):
            kind = "project" if r.project else "module"
            print(f"{name:<{width}}  {r.severity:<7} ast:{kind:<7} "
                  f"{r.summary}")
        for name, r in sorted(IR_RULES.items()):
            print(f"{name:<{width}}  {r.severity:<7} ir:jaxpr    "
                  f"{r.summary}")
        for name, r in sorted(CONC_RULES.items()):
            print(f"{name:<{width}}  {r.severity:<7} conc:host   "
                  f"{r.summary}")
        for name, r in sorted(MEM_RULES.items()):
            print(f"{name:<{width}}  {r.severity:<7} mem:budget  "
                  f"{r.summary}")
        for name, r in sorted(CONTRACT_RULES.items()):
            print(f"{name:<{width}}  {r.severity:<7} contract:wire "
                  f"{r.summary}")
        return 0

    root = Path(args.root)
    if not root.is_dir():
        print(f"error: --root {root} is not a directory", file=sys.stderr)
        return 2
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if sum((args.ir, args.conc, args.mem, args.contract)) > 1:
        print("error: --ir, --conc, --mem and --contract are separate "
              "tiers; run them in separate invocations", file=sys.stderr)
        return 2
    if args.diff is not None:
        if args.ir:
            print("error: --diff covers the source-only tiers (AST "
                  "module rules + conc); the base rev's programs "
                  "cannot be traced from git history — run --ir "
                  "separately", file=sys.stderr)
            return 2
        if args.conc:
            print("error: --diff already covers the conc tier; drop "
                  "--conc", file=sys.stderr)
            return 2
        if args.contract:
            print("error: --diff already covers the contract tier; "
                  "drop --contract", file=sys.stderr)
            return 2
        if args.write_baseline or args.baseline:
            print("error: --diff uses the base rev's findings AS the "
                  "baseline; it neither reads nor writes the baseline "
                  "file (drop --baseline/--write-baseline)",
                  file=sys.stderr)
            return 2
        if args.paths:
            # the base side always lints the default surface; scoping
            # only the current side would misreport an off-surface
            # file's pre-existing findings as new
            print("error: --diff compares the default surface; drop "
                  "the explicit paths", file=sys.stderr)
            return 2
        try:
            if args.mem:
                from apex_tpu.analysis.mem.mem_rules import MEM_RULES

                if select:
                    unknown = set(select) - set(MEM_RULES)
                    if unknown:
                        raise ValueError("unknown mem rule(s): "
                                         + ", ".join(sorted(unknown)))
                return _run_mem_diff(args, root, select)
            if select:
                from apex_tpu.analysis.conc.conc_rules import CONC_RULES
                from apex_tpu.analysis.contract.contract_rules import \
                    CONTRACT_RULES

                unknown = (set(select) - set(RULES) - set(CONC_RULES)
                           - set(CONTRACT_RULES))
                if unknown:
                    raise ValueError("unknown rule(s): "
                                     + ", ".join(sorted(unknown)))
            return _run_diff(args, root, select)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    try:
        if args.ir:
            if args.paths:
                print("error: --ir lints registered entry points, not "
                      "paths (use --ir-case NAME to narrow)",
                      file=sys.stderr)
                return 2
            from apex_tpu.analysis.ir import analyze_ir

            findings, suppressed, _ = analyze_ir(
                root, select=select, case=args.ir_case)
        elif args.conc:
            if args.paths:
                print("error: --conc analyzes the whole default "
                      "surface (locksets and thread colors come from "
                      "the global call graph); drop the explicit paths",
                      file=sys.stderr)
                return 2
            from apex_tpu.analysis.conc import analyze_conc

            findings, suppressed = analyze_conc(root, select=select)
        elif args.contract:
            if args.paths:
                print("error: --contract indexes the whole default "
                      "surface plus the docs/golden text surface (a "
                      "producer and its consumer live in different "
                      "files); drop the explicit paths",
                      file=sys.stderr)
                return 2
            from apex_tpu.analysis.contract import analyze_contract

            findings, suppressed = analyze_contract(root, select=select)
        elif args.mem:
            if args.paths:
                print("error: --mem lints registered entry points, not "
                      "paths (use --mem-case NAME to narrow)",
                      file=sys.stderr)
                return 2
            from apex_tpu.analysis.mem import analyze_mem

            findings, suppressed, _ = analyze_mem(
                root, select=select, case=args.mem_case)
        else:
            findings, suppressed = analyze_paths(
                args.paths, root=root, select=select)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = (Path(args.baseline) if args.baseline
                     else root / DEFAULT_BASELINE)
    if args.write_baseline:
        if select:
            print("error: --write-baseline with --select would record a "
                  "partial view and erase other rules' baselined findings; "
                  "run it unfiltered", file=sys.stderr)
            return 2
        try:
            existing = Baseline.load(baseline_path)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

        from apex_tpu.analysis.tiers import tier_of_key

        # the tiers share one baseline file but never clobber each
        # other: a write from one tier keeps every other tier's entries
        # (tier membership comes from the rule-namespace registry in
        # analysis/tiers.py, not per-tier string checks)
        active = "ir" if args.ir else "conc" if args.conc \
            else "mem" if args.mem \
            else "contract" if args.contract else "ast"
        keep = {k: v for k, v in existing.counts.items()
                if tier_of_key(k) != active}
        if args.ir and args.ir_case:
            # case-scoped run: replace only THIS case's entries (IR
            # scopes are case names — the last key component)
            keep.update(
                {k: v for k, v in existing.counts.items()
                 if tier_of_key(k) == "ir"
                 and k.split("::")[-1] != args.ir_case})
        elif args.mem and args.mem_case:
            keep.update(
                {k: v for k, v in existing.counts.items()
                 if tier_of_key(k) == "mem"
                 and k.split("::")[-1] != args.mem_case})
        elif active == "ast" and args.paths:
            # scoped run: replace entries for the scanned files
            # only, keep the rest of the baseline untouched
            scanned = {_rel(root, p)
                       for p in discover(root, args.paths)}
            keep.update(
                {k: v for k, v in existing.counts.items()
                 if tier_of_key(k) == "ast"
                 and k.split("::", 1)[0] not in scanned})
        Baseline.write(baseline_path, findings, keep=keep)
        print(f"tpu-lint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}"
              + (f" (kept {sum(keep.values())} out-of-scope)" if keep
                 else ""))
        return 0
    try:
        baseline = Baseline.load(baseline_path)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    new, absorbed = baseline.split(findings)

    if args.format == "json":
        print(report.render_json(new, absorbed, suppressed))
    else:
        print(report.render_text(new, absorbed, suppressed,
                                 show_baselined=args.show_baselined))
    return 1 if new else 0
