"""tpu-lint command line: discovery, engine, exit codes.

``python -m apex_tpu.analysis`` (or the ``apex-tpu-lint`` console
script) with no path arguments scans the production surface — the
``apex_tpu/`` package plus the repo-root ``tpu_*.py`` / ``bench*.py``
drivers — exactly the set ``run_tpu_round.sh`` gates on. Exit status:

* 0 — clean (every finding suppressed inline or absorbed by the
  baseline);
* 1 — findings above the baseline;
* 2 — usage error / unreadable baseline.

Files that fail to parse produce a ``parse-error`` finding rather than
crashing the run: a syntax error in one driver must not hide findings
in the other twenty files.
"""

from __future__ import annotations

import argparse
import ast
import sys
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Tuple

from apex_tpu.analysis import report
from apex_tpu.analysis.baseline import Baseline
from apex_tpu.analysis.rules import RULES, module_rules, project_rules
from apex_tpu.analysis.suppressions import Suppressions
from apex_tpu.analysis.walker import Finding, ModuleIndex

DEFAULT_GLOBS = ("apex_tpu/**/*.py", "tpu_*.py", "bench*.py")
DEFAULT_BASELINE = "tpu_lint_baseline.json"

#: generated/vendored files never worth linting
_SKIP_PARTS = {"__pycache__", ".git", ".jax_cache"}


def discover(root: Path, paths: Sequence[str]) -> List[Path]:
    files: List[Path] = []
    if paths:
        for p in paths:
            pp = Path(p)
            if not pp.is_absolute() and not pp.exists() \
                    and (root / pp).exists():
                pp = root / pp       # cwd-relative first, root as fallback
            if pp.is_dir():
                files.extend(sorted(pp.rglob("*.py")))
            else:
                files.append(pp)
    else:
        for pattern in DEFAULT_GLOBS:
            files.extend(sorted(root.glob(pattern)))
    out, seen = [], set()
    for f in files:
        if any(part in _SKIP_PARTS for part in f.parts):
            continue
        key = f.resolve()
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def _rel(root: Path, path: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def analyze_paths(paths: Sequence[str] = (), *,
                  root: Optional[object] = None,
                  select: Optional[Iterable[str]] = None,
                  with_project_rules: bool = True,
                  ) -> Tuple[List[Finding], int]:
    """Run the rule set; returns (surviving findings, #suppressed).

    ``select`` limits to a subset of rule names (None = all). Inline
    suppressions are already applied; baseline handling is the
    caller's job (`main` does it) so library users see everything.
    """
    root = (Path(root) if root is not None else Path.cwd()).resolve()
    chosen = set(select) if select is not None else set(RULES)
    unknown = chosen - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")

    findings: List[Finding] = []
    suppressed = 0
    for path in discover(root, paths):
        rel = _rel(root, path)
        try:
            source = path.read_text()
        except OSError as e:
            findings.append(Finding(
                rule="parse-error", severity="error", path=rel, line=1,
                col=1, message=f"unreadable: {e}"))
            continue
        try:
            mi = ModuleIndex(rel, source)
        except SyntaxError as e:
            findings.append(Finding(
                rule="parse-error", severity="error", path=rel,
                line=e.lineno or 1, col=(e.offset or 0) + 1,
                message=f"syntax error: {e.msg}"))
            continue
        supp = Suppressions(source)
        for r in module_rules():
            if r.name not in chosen:
                continue
            for f in r.check(mi):
                if supp.covers(f):
                    suppressed += 1
                else:
                    findings.append(f)
    if with_project_rules:
        for r in project_rules():
            if r.name in chosen:
                findings.extend(r.check(root))
    return findings, suppressed


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="apex-tpu-lint",
        description="AST static analysis for jit/Pallas/serving hazards")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: apex_tpu/, "
                        "tpu_*.py, bench*.py under --root)")
    p.add_argument("--root", default=".",
                   help="repo root for default globs, the baseline file "
                        "and the cross-file drift rules")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: <root>/"
                        f"{DEFAULT_BASELINE} when present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="absorb every current finding into the baseline "
                        "file and exit 0")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings the baseline absorbs")
    p.add_argument("--select", default=None,
                   help="comma-separated rule names to run (default all)")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        width = max(len(n) for n in RULES)
        for name, r in sorted(RULES.items()):
            kind = "project" if r.project else "module"
            print(f"{name:<{width}}  {r.severity:<7} {kind:<7} "
                  f"{r.summary}")
        return 0

    root = Path(args.root)
    if not root.is_dir():
        print(f"error: --root {root} is not a directory", file=sys.stderr)
        return 2
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    try:
        findings, suppressed = analyze_paths(
            args.paths, root=root, select=select)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = (Path(args.baseline) if args.baseline
                     else root / DEFAULT_BASELINE)
    if args.write_baseline:
        if select:
            print("error: --write-baseline with --select would record a "
                  "partial view and erase other rules' baselined findings; "
                  "run it unfiltered", file=sys.stderr)
            return 2
        keep = {}
        if args.paths:
            # scoped run: replace entries for the scanned files only,
            # keep the rest of the baseline untouched
            scanned = {_rel(root, p) for p in discover(root, args.paths)}
            try:
                existing = Baseline.load(baseline_path)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            keep = {k: v for k, v in existing.counts.items()
                    if k.split("::", 1)[0] not in scanned}
        Baseline.write(baseline_path, findings, keep=keep)
        print(f"tpu-lint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}"
              + (f" (kept {sum(keep.values())} out-of-scope)" if keep
                 else ""))
        return 0
    try:
        baseline = Baseline.load(baseline_path)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    new, absorbed = baseline.split(findings)

    if args.format == "json":
        print(report.render_json(new, absorbed, suppressed))
    else:
        print(report.render_text(new, absorbed, suppressed,
                                 show_baselined=args.show_baselined))
    return 1 if new else 0
